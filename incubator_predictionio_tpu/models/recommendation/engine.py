"""The recommendation engine: event store → BiMap reindex → TPU ALS →
device-resident top-K serving.

Reference parity (examples/scala-parallel-recommendation/custom-query/):

- ``Query(user, num, creationYear?)`` / ``PredictedResult(itemScores)``
  (Engine.scala:23-28).
- DataSource reads ``rate`` events and extracts the ``rating`` property
  (DataSource.scala:60-75); a ``buy`` event counts as rating 4.0 (the
  quickstart variant's convention).
- ALSAlgorithm trains MLlib ALS with (rank, numIterations, lambda, seed)
  (ALSAlgorithm.scala:25-31) — here ops.als on the TPU mesh.
- Model keeps String↔Int BiMaps next to the factors (ALSModel.scala).
- Serving returns the first algorithm's result (Serving.scala).

TPU-first deltas: batch predict is a single jitted (B×K)·(K×I) matmul +
top-k rather than a per-query loop, and the whole catalog is scored on
device at serve time (ops/topk.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    OptionAverageMetric,
    Params,
    Preparator,
    Serving,
)
from incubator_predictionio_tpu.core.self_cleaning import (
    EventWindow,
    SelfCleaningDataSource,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Query / result model (Engine.scala:23-28)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True  # wire format parity: creationYear, excludeSeen

    user: str
    num: int
    creation_year: Optional[int] = None  # custom-query variant filter
    categories: Optional[Tuple[str, ...]] = None  # filter-by-category variant
    whitelist: Optional[Tuple[str, ...]] = None
    blacklist: Optional[Tuple[str, ...]] = None
    exclude_seen: bool = False  # drop items the user already interacted with


@dataclasses.dataclass(frozen=True)
class ItemScore:
    __camel_case__ = True

    item: str
    score: float
    creation_year: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True  # serves {"itemScores": [...]} like the reference

    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class Rating:
    user: str
    item: str
    rating: float


# ---------------------------------------------------------------------------
# DataSource (DataSource.scala:55-90)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True  # engine.json parity: appName, eventWindow...

    app_name: str
    channel_name: Optional[str] = None
    buy_rating: float = 4.0  # implicit weight of a "buy" event
    eval_k: int = 0          # >0 enables k-fold read_eval
    eval_queries_num: int = 10
    event_window: Optional[str] = None  # SelfCleaningDataSource duration


@dataclasses.dataclass
class TrainingData:
    """Training set in columnar form (``interactions``) or, for hand-built
    fixtures and the legacy path, a ``ratings`` list. The columnar form is
    what the event store's streamed ingest produces (SURVEY §7(b)) — no
    per-event Python objects exist on that path."""

    ratings: Optional[List[Rating]] = None
    item_years: Dict[str, int] = dataclasses.field(default_factory=dict)
    item_categories: Dict[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    interactions: Optional[Interactions] = None

    def __len__(self) -> int:
        if self.interactions is not None:
            return len(self.interactions)
        return len(self.ratings or [])

    def materialize_ratings(self) -> List[Rating]:
        """Compat view for consumers that want per-triple objects."""
        if self.ratings is None and self.interactions is not None:
            inter = self.interactions
            self.ratings = [
                Rating(inter.user_ids[int(u)], inter.item_ids[int(i)],
                       float(v))
                for u, i, v in zip(inter.user_idx, inter.item_idx,
                                   inter.values)
            ]
        return self.ratings or []

    def sanity_check(self) -> None:
        if not len(self):
            raise ValueError(
                "TrainingData has no ratings — ingest rate/buy events first"
            )


class RecommendationDataSource(DataSource, SelfCleaningDataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self.app_name = params.app_name
        self.channel_name = params.channel_name
        if params.event_window:
            self.event_window = EventWindow(duration=params.event_window)
        else:
            self.event_window = None

    def _read_interactions(self) -> Interactions:
        """Columnar ingest: rate events contribute their ``rating``
        property (missing/non-numeric skipped, DataSource.scala:66-72),
        buy events the fixed implicit weight — streamed straight to COO
        arrays by the store backend, no Event objects."""
        return EventStore.interactions(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=("rate", "buy"),
            value_prop="rating",
            event_values={"buy": self.params.buy_rating},
        )

    def _read_item_meta(self) -> Tuple[Dict[str, int], Dict[str, Tuple[str, ...]]]:
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="item",
        )
        years, cats = {}, {}
        for item_id, pm in props.items():
            year = pm.opt("creationYear", int)
            if year is not None:
                years[item_id] = year
            categories = pm.opt("categories", list)
            if categories:
                cats[item_id] = tuple(str(c) for c in categories)
        return years, cats

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        if self.event_window is not None:
            self.clean_persisted_events()
        years, cats = self._read_item_meta()
        return TrainingData(
            interactions=self._read_interactions(),
            item_years=years, item_categories=cats,
        )

    def read_eval(self, ctx: RuntimeContext):
        """k-fold split (parity: e2 CrossValidation + the integration-test
        engine's Evaluation). Queries ask top-N for each user in the test
        fold; actuals are that user's held-out items. Folds are columnar
        slices — no per-triple objects."""
        k = self.params.eval_k
        if k <= 0:
            return []
        td = self.read_training(ctx)
        inter = td.interactions
        nnz = len(inter)
        out = []
        for fold in range(k):
            mask = (np.arange(nnz) % k) != fold
            train_inter = Interactions(
                user_idx=inter.user_idx[mask],
                item_idx=inter.item_idx[mask],
                values=inter.values[mask],
                user_ids=inter.user_ids,
                item_ids=inter.item_ids,
            )
            by_user: Dict[str, set] = {}
            for u, i in zip(inter.user_idx[~mask], inter.item_idx[~mask]):
                by_user.setdefault(inter.user_ids[int(u)], set()).add(
                    inter.item_ids[int(i)])
            qa = [
                (Query(user=user, num=self.params.eval_queries_num,
                       exclude_seen=True),
                 ActualResult(items=tuple(sorted(items))))
                for user, items in sorted(by_user.items())
            ]
            out.append(
                (
                    TrainingData(interactions=train_inter,
                                 item_years=td.item_years,
                                 item_categories=td.item_categories),
                    EvalInfo(fold=fold),
                    qa,
                )
            )
        return out


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    fold: int


@dataclasses.dataclass(frozen=True)
class ActualResult:
    items: Tuple[str, ...]


# ---------------------------------------------------------------------------
# Preparator (Preparator.scala — reindex to dense COO for the device)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PreparedData:
    users: np.ndarray           # [nnz] int32
    items: np.ndarray           # [nnz] int32
    ratings: np.ndarray         # [nnz] float32
    user_bimap: BiMap
    item_bimap: BiMap
    item_years: Dict[str, int]
    item_categories: Dict[str, Tuple[str, ...]]


class RecommendationPreparator(Preparator):
    """BiMap reindex + COO assembly — the host/device boundary. Duplicate
    (user, item) pairs keep the latest occurrence (event-ordered reads make
    that the newest rating), matching the template's dedup-by-entity
    convention."""

    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        if td.interactions is not None:
            return self._prepare_columnar(td)
        user_bimap = BiMap.string_int(r.user for r in td.ratings)
        item_bimap = BiMap.string_int(r.item for r in td.ratings)
        latest: Dict[Tuple[int, int], float] = {}
        for r in td.ratings:
            latest[(user_bimap[r.user], item_bimap[r.item])] = r.rating
        coo = np.array(
            [(u, i, v) for (u, i), v in latest.items()], dtype=np.float64
        ).reshape(-1, 3)
        return PreparedData(
            users=coo[:, 0].astype(np.int32),
            items=coo[:, 1].astype(np.int32),
            ratings=coo[:, 2].astype(np.float32),
            user_bimap=user_bimap,
            item_bimap=item_bimap,
            item_years=td.item_years,
            item_categories=td.item_categories,
        )

    def _prepare_columnar(self, td: TrainingData) -> PreparedData:
        """Vectorized reindex: the scan already interned ids, so the BiMaps
        are direct table views and the latest-wins dedup is one np.unique
        over packed (user, item) keys — O(nnz log nnz) C work, no Python
        loop over triples."""
        inter = td.interactions
        user_bimap = BiMap({u: i for i, u in enumerate(inter.user_ids)})
        item_bimap = BiMap({t: i for i, t in enumerate(inter.item_ids)})
        n_items = max(len(inter.item_ids), 1)
        keys = inter.user_idx.astype(np.int64) * n_items \
            + inter.item_idx.astype(np.int64)
        # keep the LAST occurrence of each (user, item): scan order is
        # event-time order, so the newest rating wins (template convention)
        _, first_in_rev = np.unique(keys[::-1], return_index=True)
        keep = np.sort(len(keys) - 1 - first_in_rev)
        return PreparedData(
            users=inter.user_idx[keep],
            items=inter.item_idx[keep],
            ratings=inter.values[keep],
            user_bimap=user_bimap,
            item_bimap=item_bimap,
            item_years=td.item_years,
            item_categories=td.item_categories,
        )


def _plan_key(tag: str, pd: Any) -> str:
    """Process-resident prep-plan key for one training stream.

    Derived from the stream's FIRST interned ids — stable across tail
    folds (first-seen interning never reorders existing ids). Two
    streams sharing first ids would collide, which is SAFE: the plan
    verifies a full COO prefix digest before any reuse, so a collision
    only costs a fresh rebuild, never a wrong splice."""
    return (f"{tag}:{next(iter(pd.user_bimap), '')}"
            f":{next(iter(pd.item_bimap), '')}")


# ---------------------------------------------------------------------------
# ALS algorithm (ALSAlgorithm.scala:25-31 → ops.als)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    __camel_case__ = True  # engine.json parity: numIterations, lambda

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None
    #: mixed-precision schedule: run this many early sweeps with bf16
    #: gathers/matmuls before the f32 polish sweeps (ops/als.py
    #: ``_mixed_run``) — the TPU fast path; 0 = all-f32 (MLlib parity)
    bf16_sweeps: int = 0


@dataclasses.dataclass
class ALSModel:
    user_factors: Any           # [U, K] device/host array
    item_factors: Any           # [I, K]
    user_bimap: BiMap
    item_bimap: BiMap
    item_years: Dict[str, int]
    item_categories: Dict[str, Tuple[str, ...]]
    #: user index -> sorted np.ndarray of seen item indices (exclude_seen)
    user_seen: Dict[int, Any] = dataclasses.field(default_factory=dict)

    def year_of(self, item_index: int) -> Optional[int]:
        return self.item_years.get(self.item_bimap.inverse[item_index])


class ALSAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams
    query_class_ = Query

    def __init__(self, params: ALSAlgorithmParams = ALSAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> ALSModel:
        from incubator_predictionio_tpu.ops import als_train

        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        if n_users == 0 or n_items == 0:
            raise ValueError("No ratings to train on")
        seed = self.params.seed if self.params.seed is not None else ctx.seed
        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        placement = placement_for_ctx(ctx, n_users, n_items)
        if placement is not None:
            # `pio train --model-parallelism N` (or PIO_SHARD_TABLES=1):
            # BOTH factor tables shard on rows over the mesh (the ALX
            # layout, ops/als.py als_train_placed) and each device
            # solves the row buckets it owns under shard_map. The model
            # keeps host-shaped (unplaced) factors — serving re-routes
            # to the sharded top-k merge whenever placed tables are
            # handed to it directly.
            from incubator_predictionio_tpu.ops.als import als_train_placed

            state = als_train_placed(
                pd.users, pd.items, pd.ratings, n_users, n_items,
                placement=placement,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_,
                seed=seed,
                bf16_sweeps=self.params.bf16_sweeps,
            )
            state = placement.unplace_state(state)
        else:
            state, _ = als_train(
                pd.users, pd.items, pd.ratings,
                n_users=n_users, n_items=n_items,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_,
                seed=seed,
                bf16_sweeps=self.params.bf16_sweeps,
            )
        logger.info(
            "ALS trained: %d users × %d items, rank %d",
            n_users, n_items, self.params.rank,
        )
        model = self._assemble_model(pd, state)
        self._refresh_mips_index(model)
        return model

    def train_with_previous(
        self, ctx: RuntimeContext, pd: PreparedData, prev_model: Any
    ) -> ALSModel:
        """Continuation retrain (ops/retrain.py): seed from the previous
        model's factors when its id space is an exact prefix of this
        PreparedData's, and let the convergence early-stop turn the warm
        start into fewer sweeps. Any incompatibility (rank change, index
        space rebuilt) falls back to a fresh train. Under a mesh
        placement the retrain runs the sharded one-dispatch path —
        ``continue_state`` + ``place_state`` re-distribute a previous
        model even when it was trained at a different mesh shape."""
        seed = self.params.seed if self.params.seed is not None else ctx.seed
        prev_state = self._continuation_seed(pd, prev_model)
        if prev_state is None:
            return self.train(ctx, pd)
        from incubator_predictionio_tpu.ops.retrain import als_retrain
        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        placement = placement_for_ctx(ctx, n_users, n_items)
        stats: Dict[str, Any] = {}
        state = als_retrain(
            pd.users, pd.items, pd.ratings, n_users, n_items,
            rank=self.params.rank, iterations=self.params.num_iterations,
            l2=self.params.lambda_, seed=seed,
            bf16_sweeps=self.params.bf16_sweeps,
            prev_state=prev_state, plan_key=_plan_key("rec", pd),
            stats=stats, placement=placement)
        if placement is not None:
            state = placement.unplace_state(state)
        logger.info(
            "ALS continuation retrain: %d users × %d items, rank %d, "
            "%s sweeps (mode=%s, delta=%.3e)", n_users, n_items,
            self.params.rank, stats.get("sweeps_used"),
            stats.get("mode"), stats.get("final_delta", float("nan")))
        model = self._assemble_model(pd, state)
        self._refresh_mips_index(model, prev_model=prev_model,
                                 retrain_stats=stats)
        return model

    def _continuation_seed(self, pd: PreparedData, prev_model: Any):
        """Prior factors as an (ungrown) ALSState, or None when they
        cannot seed this training run."""
        from incubator_predictionio_tpu.ops.als import ALSState

        if not isinstance(prev_model, ALSModel):
            return None
        uf = np.asarray(prev_model.user_factors)
        vf = np.asarray(prev_model.item_factors)
        if uf.ndim != 2 or vf.ndim != 2 or uf.shape[1] != vf.shape[1] \
                or uf.shape[1] != self.params.rank:
            return None
        if not (prev_model.user_bimap.is_index_prefix_of(pd.user_bimap)
                and prev_model.item_bimap.is_index_prefix_of(
                    pd.item_bimap)):
            return None
        return ALSState(user_factors=uf, item_factors=vf)

    def _assemble_model(self, pd: PreparedData, state) -> ALSModel:
        user_seen: Dict[int, Any] = {}
        for u, i in zip(pd.users.tolist(), pd.items.tolist()):
            user_seen.setdefault(u, []).append(i)
        user_seen = {
            u: np.asarray(sorted(ids), np.int32)
            for u, ids in user_seen.items()
        }
        return ALSModel(
            user_factors=state.user_factors,
            item_factors=state.item_factors,
            user_bimap=pd.user_bimap,
            item_bimap=pd.item_bimap,
            item_years=pd.item_years,
            item_categories=pd.item_categories,
            user_seen=user_seen,
        )

    def prepare_model(self, ctx: RuntimeContext, model: ALSModel) -> ALSModel:
        """Push restored factors back onto the device (TPU-resident serving
        state; see Algorithm.prepare_model)."""
        import jax

        from incubator_predictionio_tpu.ops.host_serving import (
            warm_host_arrays,
        )

        from incubator_predictionio_tpu.ops import mips

        prev_table = model.item_factors
        np_users = np.asarray(model.user_factors)
        np_items = np.asarray(model.item_factors)
        model = dataclasses.replace(
            model,
            user_factors=jax.device_put(np_users),
            item_factors=jax.device_put(np_items),
        )
        # pre-warm the host mirror (same field order as the serving call
        # sites) — the first query never pays a device→host factor fetch
        warm_host_arrays(
            model, user_factors=np_users, item_factors=np_items)
        # deploy-time MIPS index: a just-trained-in-this-process model
        # already carries one — ADOPT it onto the re-device_put table
        # (same values, new object) instead of paying a second full
        # build; disk-restored models build fresh from the host copy
        # already in hand
        if mips.adopt_index(prev_table, model.item_factors) is None:
            self._refresh_mips_index(model, host_factors=np_items)
        return model

    def _refresh_mips_index(self, model: ALSModel, prev_model=None,
                            retrain_stats=None,
                            host_factors=None) -> None:
        """Keep the two-stage MIPS serving index (ops/mips.py) riding
        the model's item table: O(delta) splice on a plan-reusing
        continuation retrain (only the touched rows re-quantize and
        re-home), full rebuild otherwise. Gated by PIO_SERVE_MIPS +
        the auto-mode catalogue floor; never fatal — exhaustive
        serving is always a correct fallback."""
        from incubator_predictionio_tpu.ops import mips

        n_items = len(model.item_bimap)
        if not mips.build_enabled(n_items):
            return
        try:
            if prev_model is not None and retrain_stats is not None:
                touched = retrain_stats.get("touched_item_rows")
                if touched is not None and mips.update_index(
                        prev_model.item_factors, model.item_factors,
                        n_items, touched) is not None:
                    # the splice only re-quantizes the delta rows while
                    # a retrain nudges EVERY factor row — re-probe so
                    # pio_serve_mips_recall reads the post-splice truth
                    # (the runbook's recall-sag trigger). The probe's
                    # one table fetch + tiny host oracle is O(I·K),
                    # bounded by the retrain that triggered it (each
                    # ALS sweep already streams ≥ nnz·K ≫ I·K).
                    mips.recall_probe(model.item_factors)
                    return
            mips.build_index(model.item_factors, n_items,
                             seed=self.params.seed or 0,
                             host_factors=host_factors,
                             probe_recall=True,
                             engine="recommendation")
        except Exception:  # index is an optimization, never a failure
            logger.exception("MIPS index build failed; serving stays "
                             "exhaustive")

    # -- speed layer -------------------------------------------------------
    def make_speed_overlay(self, model: ALSModel, app_name, channel_name,
                           data_source_params=None):
        """Explicit fold-in over the frozen item factors: same event
        shape as the DataSource's training read (rate events carry
        ``rating``; buy events the fixed implicit weight) and the same
        ALS-WR regularization (λ·nnz) the trainer used — a dirty or
        brand-new user's overlay row IS the row training would solve."""
        if app_name is None:
            return None
        from incubator_predictionio_tpu.speed.overlay import (
            SpeedOverlay,
            SpeedOverlayConfig,
        )

        buy_rating = float(getattr(data_source_params, "buy_rating", 4.0))
        return SpeedOverlay(
            SpeedOverlayConfig(
                app_name=app_name, channel_name=channel_name,
                engine="recommendation",
                entity_type="user", target_entity_type="item",
                event_names=("rate", "buy"), value_prop="rating",
                event_values={"buy": buy_rating},
                key_side="entity",
                l2=self.params.lambda_, reg_nnz=True, implicit=False,
            ),
            other_factors=np.asarray(model.item_factors),
            other_index=model.item_bimap,
            key_index=model.user_bimap,
        )

    # -- serving ----------------------------------------------------------
    def _allowed_mask(
        self, model: ALSModel, query: Query
    ) -> Optional[np.ndarray]:
        """Serve-time filters (custom-query creationYear; filter-by-category;
        white/blacklists) → boolean mask over item indices; seen-item
        exclusion is handled in predict. Always a fixed [n_items] shape so
        the jitted scoring path compiles once."""
        n_items = len(model.item_bimap)
        mask = None

        def ensure() -> np.ndarray:
            nonlocal mask
            if mask is None:
                mask = np.ones(n_items, dtype=bool)
            return mask

        if query.creation_year is not None:
            m = ensure()
            for item, idx in model.item_bimap.items():
                if model.item_years.get(item) is None or \
                        model.item_years[item] < query.creation_year:
                    m[idx] = False
        if query.categories:
            m = ensure()
            wanted = set(query.categories)
            for item, idx in model.item_bimap.items():
                if not wanted.intersection(model.item_categories.get(item, ())):
                    m[idx] = False
        if query.whitelist:
            m = ensure()
            allowed = {
                model.item_bimap[i] for i in query.whitelist
                if i in model.item_bimap
            }
            for idx in range(n_items):
                if idx not in allowed:
                    m[idx] = False
        if query.blacklist:
            m = ensure()
            for item in query.blacklist:
                idx = model.item_bimap.get(item)
                if idx is not None:
                    m[idx] = False
        return mask

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.topk import score_user_and_top_k

        user_idx = model.user_bimap.get(query.user)
        # speed layer: a folded-in vector (fresh session / dirty user)
        # takes precedence over the frozen base row — exact model-quality
        # scores seconds after the first events, not after the retrain
        ov = self.speed_overlay
        ov_vec = ov.lookup(query.user) if ov is not None else None
        if user_idx is None and ov_vec is None:
            # unknown user → empty result (ALSAlgorithm.scala predict miss)
            return PredictedResult(item_scores=())
        mask = self._allowed_mask(model, query)
        seen = None
        if query.exclude_seen and user_idx is not None:
            seen = model.user_seen.get(user_idx)
            if seen is not None and not len(seen):
                seen = None
        k = min(query.num, len(model.item_bimap))
        if k <= 0:
            # num=0 must be an empty result on BOTH serving paths
            return PredictedResult(item_scores=())

        from incubator_predictionio_tpu.ops.host_serving import (
            host_arrays, host_top_k,
        )
        host = host_arrays(model, "user_factors", "item_factors")
        if host is not None:
            np_users, np_items = host
            scores = np_items @ (np.asarray(ov_vec, np.float32)
                                 if ov_vec is not None
                                 else np_users[user_idx])
            if seen is not None:
                scores = scores.copy()
                scores[np.asarray(seen)] = -3.4e38
            top_s, top_i = host_top_k(scores, k, allowed_mask=mask)
            packed = np.stack([top_s, top_i.astype(np.float64)])
        elif ov_vec is not None:
            from incubator_predictionio_tpu.ops.topk import (
                pad_exclude,
                score_and_top_k,
            )

            exclude = pad_exclude(seen) if seen is not None else None
            packed = np.asarray(score_and_top_k(
                jnp.asarray(np.asarray(ov_vec, np.float32)),
                model.item_factors, k=k, exclude=exclude,
                allowed_mask=None if mask is None else jnp.asarray(mask),
            ))
        else:
            from incubator_predictionio_tpu.ops.topk import pad_exclude

            # pow2-padded (-1 = no-op slots) so the jitted serve call
            # compiles O(log max-seen) times total
            exclude = pad_exclude(seen) if seen is not None else None
            packed = np.asarray(score_user_and_top_k(  # ONE dispatch+fetch
                model.user_factors,
                model.item_factors,
                int(user_idx),
                k=k,
                exclude=exclude,
                allowed_mask=None if mask is None else jnp.asarray(mask),
            ))
        scores, indices = packed[0], packed[1].astype(np.int64)
        inv = model.item_bimap.inverse
        out = []
        for s, i in zip(scores, indices):
            if s <= -1e37:  # masked-out filler
                continue
            item = inv[int(i)]
            out.append(
                ItemScore(item=item, score=float(s),
                          creation_year=model.item_years.get(item))
            )
        return PredictedResult(item_scores=tuple(out))

    def batch_predict(
        self, model: ALSModel, queries: Sequence[Tuple[int, Query]]
    ) -> List[Tuple[int, PredictedResult]]:
        """Batched serving/evaluation path: one (B×K)·(K×I) matmul + batched
        top-k for all unfiltered queries (the MXU-shaped path; the serving
        micro-batcher routes concurrent /queries.json traffic here —
        CreateServer.scala:523 leaves this as "TODO: Parallelize"). Filtered
        queries fall back to per-query predict."""
        ov = self.speed_overlay
        plain = [
            (qx, q) for qx, q in queries
            if q.creation_year is None and not q.categories
            and not q.whitelist and not q.blacklist and not q.exclude_seen
            and model.user_bimap.get(q.user) is not None
            # overlay-covered users have a FRESHER vector than the base
            # row — they take the per-query path (which consults it)
            and (ov is None or not ov.covers(q.user))
        ]
        out: List[Tuple[int, PredictedResult]] = []
        if plain:
            k = min(max(q.num for _qx, q in plain), len(model.item_bimap))
            rows = [model.user_bimap[q.user] for _qx, q in plain]
            tops = self._score_plain_batch(model, rows, k)
            for (qx, q), (top_s, top_i) in zip(plain, tops):
                out.append((qx, self._pack_scores(
                    model, top_s[: q.num], top_i[: q.num])))
        handled = {qx for qx, _ in out}
        for qx, q in queries:
            if qx not in handled:
                out.append((qx, self.predict(model, q)))
        return out

    @staticmethod
    def _score_plain_batch(model: ALSModel, rows, k: int):
        """Score a batch of user rows and return per-row ``(top_s, top_i)``
        pairs — the ONE copy of the host/device crossover shared by
        ``batch_predict`` and ``batch_serve_json`` (the byte-identity
        contract between those two paths depends on them scoring
        identically)."""
        from incubator_predictionio_tpu.ops.host_serving import (
            host_arrays, host_batch_top_k,
        )
        from incubator_predictionio_tpu.ops.topk import batch_score_top_k

        host = host_arrays(model, "user_factors", "item_factors")
        if host is not None:
            # model small enough for a host copy: one [B,K]@[K,I] numpy
            # matmul is a few ms at any batch size, always under the
            # device dispatch+fetch round trip such a model would pay
            np_users, np_items = host
            top_s, top_i = host_batch_top_k(np_users[rows] @ np_items.T, k)
            return [(top_s[b], top_i[b]) for b in range(len(rows))]
        packed = np.asarray(batch_score_top_k(     # ONE fetch
            model.user_factors, model.item_factors, rows, k))
        return [(packed[0][b], packed[1][b].astype(np.int64))
                for b in range(len(rows))]

    def warmup(self, model: ALSModel, max_batch: int = 1) -> None:
        """Pre-compile the serving dispatches (core/base.py Algorithm.warmup):
        the singleton path once, then the batched path at each power-of-two
        size up to the micro-batch cap (batch_score_top_k pads B to the
        next power of two, so these are exactly the shapes concurrency can
        produce). Uses a real known user so the device path executes."""
        first = next(iter(model.user_bimap), None)
        if first is None:
            return
        q = Query(user=str(first), num=10)
        self.predict(model, q)
        if int(max_batch) <= 0:
            return  # micro-batching disabled: the batched path never runs
        from incubator_predictionio_tpu.ops.topk import ladder_rungs

        # the SAME ladder the scheduler can dispatch (ops/topk
        # ladder_rungs — one rule, shared, so warmed shapes cannot
        # drift from dispatchable shapes). Rung 1 is skipped: the
        # scheduler routes singleton batches through predict(), so B=1
        # is a batched shape live traffic never produces
        for size in ladder_rungs(int(max_batch)):
            if size < 2:
                continue
            self.batch_predict(model, [(i, q) for i in range(size)])

    def _pack_scores(self, model: ALSModel, scores, indices) -> PredictedResult:
        inv = model.item_bimap.inverse
        years = model.item_years
        packed = []
        for s, i in zip(scores, indices):
            if s > -1e37:
                iid = inv[int(i)]
                packed.append(ItemScore(item=iid, score=float(s),
                                        creation_year=years.get(iid)))
        return PredictedResult(item_scores=tuple(packed))

    def batch_serve_json(self, model: ALSModel, docs) -> list:
        """Columnar serving fast path (core/base.py batch_serve_json): the
        plain ``{"user": ..., "num": ...}`` wire shape renders straight
        from the batched top-k arrays to response bytes — no Query /
        ItemScore / PredictedResult objects, no jsonable tree walk. Output
        is byte-identical to ``json.dumps(to_jsonable(...))`` of the
        object path (pinned by tests/test_prediction_server.py); anything
        else (extra keys, unknown user, filters) stays None and falls to
        the object path."""
        import json as _json
        import math

        get_row = model.user_bimap.get
        ov = self.speed_overlay
        plain = []  # (slot, row, num)
        for slot, d in enumerate(docs):
            if (type(d) is dict and len(d) == 2 and "user" in d
                    and "num" in d):
                u, num = d["user"], d["num"]
                if (isinstance(u, str) and isinstance(num, int)
                        and not isinstance(num, bool) and num > 0):
                    row = get_row(u)
                    # overlay-covered users fall to the object path: the
                    # rendered bytes must reflect the folded-in vector
                    if row is not None and (ov is None
                                            or not ov.covers(u)):
                        plain.append((slot, row, num))
        out: list = [None] * len(docs)
        if not plain:
            return out
        k = min(max(num for _s, _r, num in plain), len(model.item_bimap))
        rows = [r for _s, r, _n in plain]
        tops = self._score_plain_batch(model, rows, k)
        inv = model.item_bimap.inverse
        years = model.item_years
        dumps = _json.dumps
        isfinite = math.isfinite
        for (slot, _row, num), (top_s, top_i) in zip(plain, tops):
            parts = []
            ok = True
            for s, i in zip(top_s[:num].tolist(), top_i[:num].tolist()):
                if s > -1e37:
                    if not isfinite(s):
                        # repr(inf) is not JSON (json.dumps says
                        # 'Infinity') — an overflowed score falls back
                        # to the object path rather than diverge
                        ok = False
                        break
                    iid = inv[i]
                    y = years.get(iid)
                    # mirror json.dumps' default formatting exactly
                    # (', '/': ' separators, float repr)
                    parts.append('{"item": %s, "score": %s, '
                                 '"creationYear": %s}'
                                 % (dumps(iid), repr(s),
                                    "null" if y is None else repr(y)))
            if ok:
                out[slot] = ('{"itemScores": [' + ", ".join(parts)
                             + "]}").encode("utf-8")
        return out


# ---------------------------------------------------------------------------
# Serving + metrics + factory
# ---------------------------------------------------------------------------

class RecommendationServing(Serving):
    """First-algorithm serving (Serving.scala / LFirstServing)."""

    FIRST_PREDICTION_ONLY = True

    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


class PrecisionAtK(OptionAverageMetric):
    """Precision@K against held-out items (parity: the integration-test
    engine's Evaluation metric)."""

    def __init__(self, k: int = 10):
        super().__init__()
        self.k = k

    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_qpa(self, q: Query, p: PredictedResult, a: ActualResult):
        if not a.items:
            return None
        predicted = [s.item for s in p.item_scores[: self.k]]
        hits = sum(1 for item in predicted if item in set(a.items))
        # standard precision@k: divide by k, not by the returned count —
        # returning fewer than k items must not inflate the score
        return hits / self.k


class RecommendationEngine(EngineFactory):
    """EngineFactory (Engine.scala:30-40 of the template)."""

    def apply(self) -> Engine:
        return Engine(
            RecommendationDataSource,
            RecommendationPreparator,
            {"als": ALSAlgorithm},
            RecommendationServing,
        )
