"""Session-based sequence recommendation engine (next-item prediction).

Capability parity target: the reference's closest artifact is the
MarkovChain top-N transition model (e2/.../engine/MarkovChain.scala:33,71)
used by experimental session templates. This engine is its TPU-native
upgrade: a SASRec-style causal transformer (ops/transformer.py) trained on
each user's time-ordered item-event sequence from the event store.

- ``Query(user, num, recentItems?)`` / ``PredictedResult(itemScores)`` —
  the standard template wire shape. ``recentItems`` lets stateless clients
  pass the session history explicitly; otherwise the algorithm reads the
  user's recent events from the event store at serve time (the ecommerce
  template's recentFeatures pattern).
- Long sessions are first-class: ``seq_parallel`` ∈ {none, ring, ulysses}
  selects sequence/context parallelism over the mesh's ``sp`` axis
  (parallel/ring.py) for training on long histories.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    user: str
    num: int
    #: explicit session history (most recent last); overrides the event store
    recent_items: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    __camel_case__ = True

    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True

    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None
    event_names: Tuple[str, ...] = ("view", "buy")
    #: sessions shorter than this are dropped (nothing to predict from)
    min_session_length: int = 2


@dataclasses.dataclass
class TrainingData:
    #: per-user time-ordered item id sequences
    sessions: List[List[str]]

    def sanity_check(self) -> None:
        if not self.sessions:
            raise ValueError("TrainingData has no usable sessions")


class SequenceDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        events = EventStore.find(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
        )
        per_user: Dict[str, List[Tuple[Any, str]]] = {}
        for e in events:
            if e.target_entity_id:
                per_user.setdefault(e.entity_id, []).append(
                    (e.event_time, e.target_entity_id)
                )
        sessions = []
        for items in per_user.values():
            items.sort(key=lambda t: t[0])
            seq = [i for _, i in items]
            if len(seq) >= self.params.min_session_length:
                sessions.append(seq)
        return TrainingData(sessions=sessions)


@dataclasses.dataclass
class PreparedData:
    #: [N, max_len] int32, PAD(0)-left-padded, items indexed from 1
    sequences: np.ndarray
    item_bimap: BiMap


@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    __camel_case__ = True

    max_len: int = 64


class SequencePreparator(Preparator):
    def __init__(self, params: PreparatorParams = PreparatorParams()):
        super().__init__(params)

    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        # index items from 1; 0 is the PAD token
        item_bimap = BiMap.string_int(
            i for s in td.sessions for i in s
        )
        max_len = self.params.max_len
        rows = np.zeros((len(td.sessions), max_len), np.int32)
        for r, seq in enumerate(td.sessions):
            idx = [item_bimap[i] + 1 for i in seq][-max_len:]
            rows[r, max_len - len(idx):] = idx
        return PreparedData(sequences=rows, item_bimap=item_bimap)


@dataclasses.dataclass(frozen=True)
class SeqRecAlgorithmParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None
    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    epochs: int = 20
    batch_size: int = 128
    learning_rate: float = 1e-3
    seed: Optional[int] = None
    #: sequence-parallel strategy for long sessions: none | ring | ulysses
    seq_parallel: str = "none"
    #: event types read to reconstruct a live session at serve time
    recent_events: Tuple[str, ...] = ("view", "buy")


@dataclasses.dataclass
class SeqRecModel:
    weights: Any            # ops.transformer.TransformerWeights
    item_bimap: BiMap
    n_heads: int
    max_len: int
    final_loss: float


class SeqRecAlgorithm(Algorithm):
    params_class = SeqRecAlgorithmParams
    query_class_ = Query

    def __init__(self, params: SeqRecAlgorithmParams):
        super().__init__(params)
        # bounded TTL micro-cache in front of the per-query session-history
        # read (`serve-blocking-io`): versioned by the store's write
        # cursor, so new events invalidate immediately and repeat queries
        # between writes stop paying a storage scan
        from incubator_predictionio_tpu.speed.cache import (
            TTLCache,
            serve_cache_ttl,
        )

        self._history_cache = TTLCache(maxsize=4096,
                                       ttl_s=serve_cache_ttl())

    def _store_version(self):
        from incubator_predictionio_tpu.speed.cache import store_version

        return store_version(self.params.app_name,
                             self.params.channel_name)

    def _attn_fn(self, ctx: RuntimeContext, train_len: int):
        """Sequence-parallel attention backend per params.seq_parallel.

        Builds a dedicated 1-axis ``sp`` mesh whose degree is the largest
        device count that divides the training sequence length
        (``max_len - 1`` after the next-item shift) — and, for ulysses, the
        head count. Degenerates to single-device attention (None) when no
        useful degree exists.
        """
        mode = self.params.seq_parallel
        if mode == "none":
            return None
        if mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq_parallel mode: {mode!r}")
        import jax
        from jax.sharding import Mesh

        from incubator_predictionio_tpu.parallel.mesh import SEQ_AXIS
        from incubator_predictionio_tpu.parallel.ring import (
            ring_attention, ulysses_attention,
        )

        sp = len(jax.devices())
        while sp > 1 and (
            train_len % sp != 0
            or (mode == "ulysses" and self.params.n_heads % sp != 0)
        ):
            sp -= 1
        if sp <= 1:
            logger.warning(
                "sequence: seq_parallel=%s requested but no device count "
                "≤ %d divides train length %d%s; training single-device",
                mode, len(jax.devices()), train_len,
                f" and {self.params.n_heads} heads" if mode == "ulysses"
                else "",
            )
            return None
        mesh = Mesh(np.array(jax.devices()[:sp]), (SEQ_AXIS,))
        fn = ring_attention if mode == "ring" else ulysses_attention
        return functools.partial(fn, mesh=mesh)

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> SeqRecModel:
        from incubator_predictionio_tpu.ops.transformer import sasrec_fit

        seed = self.params.seed if self.params.seed is not None else ctx.seed
        weights, losses = sasrec_fit(
            pd.sequences,
            n_items=len(pd.item_bimap),  # token ids 1..n; fit adds the PAD slot
            d_model=self.params.d_model,
            n_heads=self.params.n_heads,
            n_layers=self.params.n_layers,
            epochs=self.params.epochs,
            batch_size=self.params.batch_size,
            learning_rate=self.params.learning_rate,
            seed=seed,
            attn_fn=self._attn_fn(ctx, train_len=pd.sequences.shape[1] - 1),
        )
        logger.info("sequence: trained %d sessions, loss %.4f → %.4f",
                    len(pd.sequences), losses[0], losses[-1])
        return SeqRecModel(
            weights=weights,
            item_bimap=pd.item_bimap,
            n_heads=self.params.n_heads,
            max_len=pd.sequences.shape[1],
            final_loss=float(losses[-1]),
        )

    def prepare_model(self, ctx, model: SeqRecModel) -> SeqRecModel:
        import jax

        model.weights = jax.tree_util.tree_map(
            lambda x: jax.device_put(jax.numpy.asarray(x)), model.weights
        )
        return model

    def _history(self, query: Query, model: SeqRecModel) -> List[int]:
        """Session history as model token ids, oldest first. The
        event-store read goes through the TTL micro-cache (new writes
        invalidate via the store cursor)."""
        if query.recent_items is not None:
            names: Sequence[str] = query.recent_items
        else:
            names = self._history_cache.get_or_load(
                query.user,
                lambda: self._load_history_names(query.user, model),
                version=self._store_version())
        return [model.item_bimap[n] + 1 for n in names
                if n in model.item_bimap]

    def _load_history_names(self, user: str,
                            model: SeqRecModel) -> List[str]:
        try:
            events = list(EventStore.find_by_entity(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.recent_events),
                limit=model.max_len,
                latest=True,
            ))
        except Exception:
            logger.warning(
                "sequence: recent-event lookup failed for user %r",
                user, exc_info=True,
            )
            events = []
        return [e.target_entity_id for e in reversed(events)
                if e.target_entity_id]

    def warmup(self, model: SeqRecModel, max_batch: int = 1) -> None:
        """Pre-compile the serving forward (core/base.py Algorithm.warmup):
        the transformer's first query otherwise pays the full XLA compile
        — the most expensive cold path of any template. Uses an explicit
        one-item history so no event-store read happens."""
        first = next(iter(model.item_bimap), None)
        if first is not None:
            self.predict(model, Query(user="__warmup__", num=10,
                                      recent_items=(str(first),)))

    def predict(self, model: SeqRecModel, query: Query) -> PredictedResult:
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.transformer import sasrec_topk

        hist = self._history(query, model)
        if not hist:
            return PredictedResult(item_scores=())
        # Score at width max_len-1 — the width training ran at
        # (sasrec_fit shifts batch[:, :-1] → batch[:, 1:]), so every
        # positional-embedding row used here received gradients.
        window = model.max_len - 1
        tokens = np.zeros((1, window), np.int32)
        hist = hist[-window:]
        tokens[0, window - len(hist):] = hist
        k = min(query.num, len(model.item_bimap))
        scores, ids = sasrec_topk(
            model.weights, jnp.asarray(tokens), model.n_heads, k=k
        )
        inv = model.item_bimap.inverse
        out = []
        for s, i in zip(np.asarray(scores[0]), np.asarray(ids[0])):
            if not np.isfinite(s) or int(i) == 0:
                continue
            out.append(ItemScore(item=inv[int(i) - 1], score=float(s)))
        return PredictedResult(item_scores=tuple(out))


class HitAtK(AverageMetric):
    """Next-item hit rate over held-out (query, actual) pairs."""

    def calculate_one(self, query: Query, predicted: PredictedResult,
                      actual: Any) -> float:
        wanted = actual if isinstance(actual, str) else actual.item
        return 1.0 if any(s.item == wanted for s in predicted.item_scores) \
            else 0.0


class SequenceEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            SequenceDataSource,
            SequencePreparator,
            {"sasrec": SeqRecAlgorithm},
            FirstServing,
        )
