"""Session-based sequence recommendation (next-item transformer)."""

from incubator_predictionio_tpu.models.sequence.engine import (
    HitAtK,
    PredictedResult,
    Query,
    SeqRecAlgorithm,
    SeqRecAlgorithmParams,
    SequenceDataSource,
    SequenceEngine,
    SequencePreparator,
)

__all__ = [
    "HitAtK",
    "PredictedResult",
    "Query",
    "SeqRecAlgorithm",
    "SeqRecAlgorithmParams",
    "SequenceDataSource",
    "SequenceEngine",
    "SequencePreparator",
]
