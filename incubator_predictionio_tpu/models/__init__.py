"""Engine templates — the framework's model zoo.

Each subpackage is a complete DASE engine mirroring one of the reference's
template families (SURVEY.md §2.6, examples/scala-parallel-*):

- ``recommendation``  — explicit-rating ALS matrix factorization
- ``classification``  — categorical NaiveBayes + optax logistic regression
- ``similarproduct``  — implicit-feedback ALS, item-to-item queries
- ``ecommerce``       — implicit ALS + serve-time business-rule filtering
- ``sequence``        — session-based next-item transformer (SASRec-style)
  with ring/Ulysses sequence parallelism for long histories
- ``regression``      — linear regression (exact ridge solve + SGD) under
  AverageServing (examples/experimental/scala-{parallel,local}-regression)
- ``friendrecommendation`` — KDD-2012 acceptance prediction: keyword
  similarity, random baseline, dense device SimRank
  (examples/experimental/scala-*-friend-recommendation)
- ``stock``           — price-panel strategies (momentum, batched
  per-ticker regression) + backtesting evaluator
  (examples/experimental/scala-stock)
"""
