"""E-commerce recommendation engine.

Reference parity (examples/scala-parallel-ecommercerecommendation/
train-with-rate-event + adjust-score + weighted-items variants):

- ``Query(user, num, categories?, whiteList?, blackList?)`` /
  ``PredictedResult(itemScores)`` (Engine.scala:23-38).
- DataSource reads ``view``/``buy`` (train-with-rate-event adds ``rate``)
  user→item events plus item ``$set`` properties.
- ECommAlgorithm trains implicit ALS; at serve time it filters
  *unavailable items* (the ``constraint`` entity's ``unavailableItems``
  property, re-read per query so ops can flip availability without
  retraining — ECommAlgorithm.scala predict), seen items, black/whitelists
  and categories.
- Unknown users fall back to a vector built from their recent view events
  (ECommAlgorithm.scala recentFeatures), so fresh sessions still get
  personalized results without retraining.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator,
    Serving,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    user: str
    num: int
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    __camel_case__ = True

    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True

    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None
    event_weights: Tuple[Tuple[str, float], ...] = (
        ("view", 1.0), ("buy", 4.0), ("rate", 2.0),
    )


@dataclasses.dataclass(frozen=True)
class Interaction:
    user: str
    item: str
    weight: float


@dataclasses.dataclass
class TrainingData:
    interactions: Optional[List[Interaction]] = None  # fixture/legacy form
    item_categories: Dict[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    columnar: Optional[Interactions] = None           # columnar ingest form

    def __len__(self) -> int:
        if self.columnar is not None:
            return len(self.columnar)
        return len(self.interactions or [])

    def sanity_check(self) -> None:
        if not len(self):
            raise ValueError("TrainingData has no user-item interactions")


class ECommerceDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        weights = dict(self.params.event_weights)
        columnar = EventStore.interactions(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=tuple(weights),
            event_values={k: float(v) for k, v in weights.items()},
        )
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="item",
        )
        cats = {
            item: tuple(str(c) for c in (pm.opt("categories", list) or ()))
            for item, pm in props.items()
        }
        return TrainingData(columnar=columnar, item_categories=cats)


@dataclasses.dataclass
class PreparedData:
    users: np.ndarray
    items: np.ndarray
    weights: np.ndarray
    user_bimap: BiMap
    item_bimap: BiMap
    item_categories: Dict[str, Tuple[str, ...]]


class ECommercePreparator(Preparator):
    def prepare(self, ctx: RuntimeContext, td: TrainingData) -> PreparedData:
        if td.columnar is not None:
            return self._prepare_columnar(td)
        user_bimap = BiMap.string_int(i.user for i in td.interactions)
        item_bimap = BiMap.string_int(i.item for i in td.interactions)
        agg: Dict[Tuple[int, int], float] = {}
        for i in td.interactions:
            key = (user_bimap[i.user], item_bimap[i.item])
            agg[key] = agg.get(key, 0.0) + i.weight
        coo = np.array([(u, i, w) for (u, i), w in agg.items()],
                       np.float64).reshape(-1, 3)
        return PreparedData(
            users=coo[:, 0].astype(np.int32),
            items=coo[:, 1].astype(np.int32),
            weights=coo[:, 2].astype(np.float32),
            user_bimap=user_bimap,
            item_bimap=item_bimap,
            item_categories=td.item_categories,
        )

    def _prepare_columnar(self, td: TrainingData) -> PreparedData:
        """Vectorized weight summation over the columnar scan (same math
        as the legacy loop: repeated events sum their weights)."""
        inter = td.columnar
        n_items = max(len(inter.item_ids), 1)
        keys = inter.user_idx.astype(np.int64) * n_items \
            + inter.item_idx.astype(np.int64)
        uniq, inverse = np.unique(keys, return_inverse=True)
        sums = np.zeros(len(uniq), np.float64)
        np.add.at(sums, inverse, inter.values.astype(np.float64))
        return PreparedData(
            users=(uniq // n_items).astype(np.int32),
            items=(uniq % n_items).astype(np.int32),
            weights=sums.astype(np.float32),
            user_bimap=BiMap({u: i for i, u in enumerate(inter.user_ids)}),
            item_bimap=BiMap({t: i for i, t in enumerate(inter.item_ids)}),
            item_categories=td.item_categories,
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    __camel_case__ = True

    app_name: str
    channel_name: Optional[str] = None
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    #: events counted as "seen" and excluded from results
    seen_events: Tuple[str, ...] = ("buy", "view")
    unseen_only: bool = True
    #: recent events used to build an unknown user's vector
    similar_events: Tuple[str, ...] = ("view",)
    num_recent_events: int = 10


@dataclasses.dataclass
class ECommModel:
    user_factors: Any
    item_factors: Any
    user_bimap: BiMap
    item_bimap: BiMap
    item_categories: Dict[str, Tuple[str, ...]]
    user_seen: Dict[int, Any]
    #: popularity ranks (interaction counts) for the cold fallback
    item_popularity: Any


class ECommAlgorithm(Algorithm):
    params_class = ECommAlgorithmParams
    query_class_ = Query

    def __init__(self, params: ECommAlgorithmParams):
        super().__init__(params)
        # bounded TTL micro-caches in front of the serve-time storage
        # round trips (speed/cache.py): the recent-events read is keyed
        # per user and versioned by the speed-layer cursor (a user whose
        # events the overlay has seen misses immediately); the constraint
        # read is one shared entry. Both default to a short TTL — ops
        # flips (unavailableItems) still land within seconds while the
        # hot path stops paying a storage scan per query.
        from incubator_predictionio_tpu.speed.cache import (
            TTLCache,
            serve_cache_ttl,
        )

        ttl = serve_cache_ttl()
        self._recent_cache = TTLCache(maxsize=4096, ttl_s=ttl)
        self._constraint_cache = TTLCache(maxsize=4, ttl_s=ttl)

    def make_speed_overlay(self, model: "ECommModel", app_name,
                           channel_name, data_source_params=None):
        """Implicit fold-in over the frozen item factors — the EXACT
        Hu-Koren-Volinsky row solve replacing the crude averaged
        ``recentFeatures`` fallback for unknown/dirty users. Event shape
        mirrors the DataSource's weighted training read."""
        if app_name is None:
            return None
        from incubator_predictionio_tpu.speed.overlay import (
            SpeedOverlay,
            SpeedOverlayConfig,
        )

        weights = dict(getattr(data_source_params, "event_weights", ())
                       or (("view", 1.0), ("buy", 4.0), ("rate", 2.0)))
        return SpeedOverlay(
            SpeedOverlayConfig(
                app_name=app_name, channel_name=channel_name,
                engine="ecommerce",
                entity_type="user", target_entity_type="item",
                event_names=tuple(weights),
                event_values={k: float(v) for k, v in weights.items()},
                key_side="entity",
                l2=self.params.lambda_, implicit=True,
                alpha=self.params.alpha,
            ),
            other_factors=np.asarray(model.item_factors),
            other_index=model.item_bimap,
            key_index=model.user_bimap,
        )

    def train(self, ctx: RuntimeContext, pd: PreparedData) -> ECommModel:
        from incubator_predictionio_tpu.ops.als import als_train_implicit
        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        seed = self.params.seed if self.params.seed is not None else ctx.seed
        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        placement = placement_for_ctx(ctx, n_users, n_items)
        if placement is not None:
            # mesh-sharded implicit training (ops/als.py als_train_placed)
            from incubator_predictionio_tpu.ops.als import als_train_placed

            state = placement.unplace_state(als_train_placed(
                pd.users, pd.items, pd.weights,
                n_users=n_users, n_items=n_items, placement=placement,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_, alpha=self.params.alpha,
                seed=seed, implicit=True))
        else:
            state = als_train_implicit(
                pd.users, pd.items, pd.weights,
                n_users=n_users, n_items=n_items,
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                l2=self.params.lambda_, alpha=self.params.alpha,
                seed=seed,
            )
        return self._assemble_model(pd, state)

    def train_with_previous(
        self, ctx: RuntimeContext, pd: PreparedData, prev_model: Any
    ) -> ECommModel:
        """Continuation retrain: both factor tables seed from the prior
        model when both BiMaps are exact index prefixes of the new
        PreparedData's (the traincache first-seen contract); otherwise
        train fresh."""
        from incubator_predictionio_tpu.ops.als import ALSState

        ok = (isinstance(prev_model, ECommModel)
              and np.asarray(prev_model.user_factors).ndim == 2
              and np.asarray(prev_model.user_factors).shape[1]
              == self.params.rank
              and prev_model.user_bimap.is_index_prefix_of(pd.user_bimap)
              and prev_model.item_bimap.is_index_prefix_of(pd.item_bimap))
        if not ok:
            return self.train(ctx, pd)
        from incubator_predictionio_tpu.ops.retrain import als_retrain

        from incubator_predictionio_tpu.models.recommendation.engine import (
            _plan_key,
        )

        from incubator_predictionio_tpu.parallel.placement import (
            placement_for_ctx,
        )

        seed = self.params.seed if self.params.seed is not None else ctx.seed
        n_users, n_items = len(pd.user_bimap), len(pd.item_bimap)
        placement = placement_for_ctx(ctx, n_users, n_items)
        stats: Dict[str, Any] = {}
        state = als_retrain(
            pd.users, pd.items, pd.weights,
            n_users=n_users, n_items=n_items,
            rank=self.params.rank, iterations=self.params.num_iterations,
            l2=self.params.lambda_, alpha=self.params.alpha, seed=seed,
            implicit=True, plan_key=_plan_key("ecomm", pd),
            prev_state=ALSState(
                user_factors=np.asarray(prev_model.user_factors),
                item_factors=np.asarray(prev_model.item_factors)),
            stats=stats, placement=placement)
        if placement is not None:
            state = placement.unplace_state(state)
        logger.info("ecommerce continuation retrain: %s sweeps (mode=%s)",
                    stats.get("sweeps_used"), stats.get("mode"))
        return self._assemble_model(pd, state)

    def _assemble_model(self, pd: PreparedData, state) -> ECommModel:
        # seen set honors params.seen_events — only those event types make an
        # item "seen" (a viewed-but-not-bought item stays recommendable when
        # seen_events=("buy",)), so re-read the raw events by name
        user_seen: Dict[int, Any] = {}
        seen_raw = EventStore.find(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.seen_events),
        )
        for e in seen_raw:
            u = pd.user_bimap.get(e.entity_id)
            i = pd.item_bimap.get(e.target_entity_id)
            if u is not None and i is not None:
                user_seen.setdefault(u, []).append(i)
        user_seen = {
            u: np.asarray(sorted(set(ids)), np.int32)
            for u, ids in user_seen.items()
        }
        popularity = np.zeros(len(pd.item_bimap), np.float32)
        for i, w in zip(pd.items.tolist(), pd.weights.tolist()):
            popularity[i] += w
        return ECommModel(
            user_factors=state.user_factors,
            item_factors=state.item_factors,
            user_bimap=pd.user_bimap,
            item_bimap=pd.item_bimap,
            item_categories=pd.item_categories,
            user_seen=user_seen,
            item_popularity=popularity,
        )

    def prepare_model(self, ctx, model: ECommModel) -> ECommModel:
        import jax

        return dataclasses.replace(
            model,
            user_factors=jax.device_put(np.asarray(model.user_factors)),
            item_factors=jax.device_put(np.asarray(model.item_factors)),
        )

    # -- serve-time constraints --------------------------------------------
    def _store_version(self):
        """Write-cursor version for the micro-caches (speed/cache.py
        ``store_version``): a ``$set`` constraint flip still lands on the
        very next query — the reference's re-read-per-query contract."""
        from incubator_predictionio_tpu.speed.cache import store_version

        return store_version(self.params.app_name,
                             self.params.channel_name)

    def _constraints(
        self, model: ECommModel
    ) -> Tuple[List[int], Optional[np.ndarray]]:
        """Constraint state for this query, via the TTL micro-cache —
        the storage aggregate runs once per write/TTL window instead of
        once per query (`serve-blocking-io`)."""
        return self._constraint_cache.get_or_load(
            "constraints", lambda: self._load_constraints(model),
            version=self._store_version())

    def _load_constraints(
        self, model: ECommModel
    ) -> Tuple[List[int], Optional[np.ndarray]]:
        """Re-read the ``constraint`` entities → (unavailable item
        indices, per-item weight multipliers or None).

        The ops team ``$set``s these without retraining:
        ``constraint/unavailableItems`` {items: [...]} drops items from
        results (ECommAlgorithm.scala predict), and
        ``constraint/weightedItems`` {weights: [{items: [...], weight: w}]}
        multiplies matching items' scores — the weighted-items template
        variant (weighted-items/ECommAlgorithm.scala:234-261, WeightsGroup
        at :71-74; unlisted items default to weight 1.0)."""
        try:
            props = EventStore.aggregate_properties(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name,
                entity_type="constraint",
            )
        except Exception:
            logger.warning(
                "ecommerce: constraint lookup failed for app %r; "
                "serving without unavailable-item/weight constraints",
                self.params.app_name, exc_info=True,
            )
            return [], None
        unavailable: List[int] = []
        pm = props.get("unavailableItems")
        if pm is not None:
            names = pm.opt("items", list) or []
            unavailable = [
                model.item_bimap[n] for n in names if n in model.item_bimap
            ]
        weights: Optional[np.ndarray] = None
        wm = props.get("weightedItems")
        if wm is not None:
            # ops-authored data: one malformed group must degrade to
            # weight-1.0, not turn every predict into a 500
            groups = wm.opt("weights", list) or []
            weights = np.ones(len(model.item_bimap), np.float32)
            for group in groups:
                try:
                    w = float(group.get("weight", 1.0))
                    items = group.get("items", ())
                    if isinstance(items, str):
                        raise TypeError("items must be a list, not a string")
                    for name in items:
                        idx = model.item_bimap.get(name)
                        if idx is not None:
                            weights[idx] = w
                except Exception:
                    logger.warning(
                        "ecommerce: malformed weightedItems group %r "
                        "ignored", group, exc_info=True)
        return unavailable, weights

    def _recent_items(self, model: ECommModel, user: str) -> List[int]:
        """Recent-event item indices for one user, via the TTL micro-cache.

        Versioned by the speed layer's per-user event cursor when an
        overlay is attached (unrelated users' writes don't invalidate);
        by the store's write cursor otherwise (exact re-read-on-write
        semantics, reads deduped between writes)."""
        ov = self.speed_overlay
        version = (("u", ov.key_version(user)) if ov is not None
                   else ("s", self._store_version()))
        return self._recent_cache.get_or_load(
            ("recent", user),
            lambda: self._load_recent_items(model, user),
            version=version)

    def _seen_item_indices(self, model: ECommModel, user: str) -> List[int]:
        """Seen-item indices for a user the MODEL doesn't know (overlay
        fold-in users): their ``seen_events`` history is read through the
        same micro-cache, so unseen_only filtering holds for fresh
        sessions too — the reference reads this per query; here it costs
        one storage read per write/TTL window."""
        ov = self.speed_overlay
        version = (("u", ov.key_version(user)) if ov is not None
                   else ("s", self._store_version()))

        def load() -> List[int]:
            try:
                events = EventStore.find_by_entity(
                    app_name=self.params.app_name,
                    channel_name=self.params.channel_name,
                    entity_type="user",
                    entity_id=user,
                    event_names=list(self.params.seen_events),
                )
            except Exception:
                logger.warning(
                    "ecommerce: seen-event lookup failed for user %r; "
                    "serving without the seen filter", user, exc_info=True)
                return []
            out = set()
            for e in events:
                idx = (model.item_bimap.get(e.target_entity_id)
                       if e.target_entity_id else None)
                if idx is not None:
                    out.add(int(idx))
            return sorted(out)

        return self._recent_cache.get_or_load(("seen", user), load,
                                              version=version)

    def _load_recent_items(self, model: ECommModel, user: str) -> List[int]:
        try:
            events = EventStore.find_by_entity(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.similar_events),
                limit=self.params.num_recent_events,
                latest=True,
            )
        except Exception:
            logger.warning(
                "ecommerce: recent-event lookup failed for app %r user %r; "
                "falling back to popularity ranking",
                self.params.app_name, user, exc_info=True,
            )
            return []
        out = []
        for e in events:
            if e.target_entity_id and e.target_entity_id in model.item_bimap:
                out.append(model.item_bimap[e.target_entity_id])
        return out

    def _allowed_mask(self, model: ECommModel, query: Query,
                      user_idx: Optional[int],
                      unavailable: Sequence[int]) -> np.ndarray:
        n = len(model.item_bimap)
        mask = np.ones(n, bool)
        for idx in unavailable:
            mask[idx] = False
        if query.categories:
            wanted = set(query.categories)
            for item, idx in model.item_bimap.items():
                if not wanted.intersection(model.item_categories.get(item, ())):
                    mask[idx] = False
        if query.white_list:
            allowed = {
                model.item_bimap[i] for i in query.white_list
                if i in model.item_bimap
            }
            for idx in range(n):
                if idx not in allowed:
                    mask[idx] = False
        if query.black_list:
            for item in query.black_list:
                idx = model.item_bimap.get(item)
                if idx is not None:
                    mask[idx] = False
        if self.params.unseen_only and user_idx is not None:
            seen = model.user_seen.get(user_idx)
            if seen is not None and len(seen):
                mask[np.asarray(seen)] = False
        return mask

    def warmup(self, model: ECommModel, max_batch: int = 1) -> None:
        """Pre-compile the serving path (core/base.py Algorithm.warmup):
        one real predict compiles whichever path this model size uses
        (host mirror = free, device top-k = the XLA compile to pre-pay)."""
        first = next(iter(model.user_bimap), None)
        if first is not None:
            self.predict(model, Query(user=str(first), num=10))

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        user_idx = model.user_bimap.get(query.user)
        unavailable, weights = self._constraints(model)
        mask = self._allowed_mask(model, query, user_idx, unavailable)
        k = min(query.num, len(model.item_bimap))
        # speed layer first: the exact device fold-in replaces BOTH a
        # stale base row (dirty user) and the averaged recentFeatures
        # approximation (unknown user). Misses fall through to the
        # original ladder: base factors → recent average → popularity.
        ov = self.speed_overlay
        ov_vec = ov.lookup(query.user) if ov is not None else None
        if ov_vec is not None and self.params.unseen_only:
            # the model's train-time seen set misses everything this
            # user did SINCE (or entirely, for brand-new users): apply
            # the freshly-read seen filter on the overlay path
            for idx in self._seen_item_indices(model, query.user):
                mask[idx] = False

        from incubator_predictionio_tpu.ops.host_serving import (
            host_arrays,
            host_top_k,
        )
        host = host_arrays(model, "user_factors", "item_factors",
                           "item_popularity")
        if host is not None:
            np_users, np_items, np_pop = host
            if ov_vec is not None:
                scores = np_items @ np.asarray(ov_vec, np.float32)
            elif user_idx is not None:
                scores = np_items @ np_users[user_idx]
            else:
                recent = self._recent_items(model, query.user)
                if recent:
                    scores = np_items @ np_items[
                        np.asarray(recent, np.int32)].mean(axis=0)
                else:
                    # cold user with no history → popularity ranking
                    scores = np.asarray(np_pop, np.float32)
            if weights is not None:
                scores = scores * weights
            top_s, top_i = host_top_k(scores, k, allowed_mask=mask)
        else:
            import jax.numpy as jnp

            from incubator_predictionio_tpu.ops.topk import (
                top_k_with_exclusions,
            )

            factors = jnp.asarray(model.item_factors)
            if ov_vec is not None:
                scores = factors @ jnp.asarray(
                    np.asarray(ov_vec, np.float32))
            elif user_idx is not None:
                user_vec = jnp.asarray(model.user_factors)[user_idx]
                scores = factors @ user_vec
            else:
                recent = self._recent_items(model, query.user)
                if recent:
                    user_vec = factors[
                        jnp.asarray(recent, jnp.int32)].mean(axis=0)
                    scores = factors @ user_vec
                else:
                    # cold user with no history → popularity ranking
                    scores = jnp.asarray(model.item_popularity)
            if weights is not None:
                scores = scores * jnp.asarray(weights)
            top_s, top_i = top_k_with_exclusions(
                scores, k=k, allowed_mask=jnp.asarray(mask),
            )
        inv = model.item_bimap.inverse
        out = []
        for s, i in zip(np.asarray(top_s), np.asarray(top_i)):
            if s <= -1e37:
                continue
            out.append(ItemScore(item=inv[int(i)], score=float(s)))
        return PredictedResult(item_scores=tuple(out))


class ECommerceEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            ECommerceDataSource,
            ECommercePreparator,
            {"ecomm": ECommAlgorithm},
            FirstServing,
        )
