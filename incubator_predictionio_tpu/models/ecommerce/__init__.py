"""E-commerce recommendation template (implicit ALS + serve-time business
rules). Parity: examples/scala-parallel-ecommercerecommendation/.
"""

from incubator_predictionio_tpu.models.ecommerce.engine import (
    DataSourceParams,
    ECommAlgorithmParams,
    ECommerceEngine,
    ItemScore,
    PredictedResult,
    Query,
)

__all__ = [
    "DataSourceParams", "ECommAlgorithmParams", "ECommerceEngine",
    "ItemScore", "PredictedResult", "Query",
]
