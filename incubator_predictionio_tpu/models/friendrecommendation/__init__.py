from incubator_predictionio_tpu.models.friendrecommendation.engine import (
    DataSourceParams,
    FriendRecommendationEngine,
    KeywordSimilarityAlgoParams,
    Prediction,
    Query,
    SimRankAlgoParams,
)

__all__ = [
    "DataSourceParams", "FriendRecommendationEngine",
    "KeywordSimilarityAlgoParams", "Prediction", "Query",
    "SimRankAlgoParams",
]
