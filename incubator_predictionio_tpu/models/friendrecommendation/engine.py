"""Friend-recommendation engine (the KDD-2012 scenario).

Reference parity (examples/experimental/scala-local-friend-recommendation
+ scala-parallel-friend-recommendation): given ``Query(user, item)``,
predict an acceptance ``confidence`` plus a boolean ``acceptance``.

- ``keyword``  — sparse dot product of the user's and item's keyword
  term-weight maps with a trained-or-default weight/threshold pair
  (KeywordSimilarityAlgorithm.scala: findKeywordSimilarity; the
  reference ships weight=1, threshold=1).
- ``random``   — the RandomAlgorithm baseline (RandomModel.scala: a
  seeded uniform confidence and fixed acceptance threshold).
- ``simrank``  — the parallel variant's graph similarity
  (DeltaSimRankRDD.scala), recomputed exactly as dense MXU iterations
  (ops/simrank.py) over the follow/action edge graph.

Data lives in the event store: ``$set`` on user/item entities carrying a
``keywords`` map of term→weight, and directed ``follow`` (user→user) /
``action`` (user→item) events forming the SimRank graph.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    Params,
)
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    __camel_case__ = True

    user: str
    item: str


@dataclasses.dataclass(frozen=True)
class Prediction:
    __camel_case__ = True

    confidence: float
    acceptance: bool


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    __camel_case__ = True

    app_name: str
    user_entity: str = "user"
    item_entity: str = "item"
    keywords_attr: str = "keywords"
    #: directed graph edges for simrank: (entity → target) event names
    edge_events: Tuple[str, ...] = ("follow", "action")


@dataclasses.dataclass
class TrainingData:
    user_keywords: Dict[str, Dict[str, float]]
    item_keywords: Dict[str, Dict[str, float]]
    #: directed edges over the combined user+item node space, keyed
    #: "<entity_type>:<entity_id>"
    edges: List[Tuple[str, str]]
    #: the entity-type names the edges were keyed with (query resolution
    #: must use the same prefixes)
    user_entity: str = "user"
    item_entity: str = "item"

    def sanity_check(self) -> None:
        if not self.user_keywords and not self.edges:
            raise ValueError("TrainingData has no keywords and no edges")


class FriendRecommendationDataSource(DataSource):
    def __init__(self, params: DataSourceParams):
        super().__init__(params)

    def _keywords(self, entity_type: str) -> Dict[str, Dict[str, float]]:
        props = EventStore.aggregate_properties(
            app_name=self.params.app_name, entity_type=entity_type,
            required=[self.params.keywords_attr])
        out: Dict[str, Dict[str, float]] = {}
        for entity, pm in props.items():
            # opt, not get: an explicit null keywords value should mean
            # "no keywords", not a DataMapError aborting the whole train
            kw = pm.opt(self.params.keywords_attr)
            if isinstance(kw, dict):
                # weighted form (the KDD-cup data's keyword → weight map)
                out[entity] = {
                    str(k): float(v) for k, v in kw.items()
                    if isinstance(v, (int, float)) and not isinstance(v, bool)
                }
            elif isinstance(kw, (list, tuple)):
                # bare keyword list: uniform weight 1.0
                out[entity] = {str(k): 1.0 for k in kw}
            else:
                out[entity] = {}
        return out

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        edges: List[Tuple[str, str]] = []
        for ev in EventStore.find(
                app_name=self.params.app_name,
                event_names=self.params.edge_events):
            if ev.target_entity_id:
                edges.append((f"{ev.entity_type}:{ev.entity_id}",
                              f"{ev.target_entity_type}:{ev.target_entity_id}"))
        return TrainingData(
            user_keywords=self._keywords(self.params.user_entity),
            item_keywords=self._keywords(self.params.item_entity),
            edges=edges,
            user_entity=self.params.user_entity,
            item_entity=self.params.item_entity,
        )


@dataclasses.dataclass(frozen=True)
class KeywordSimilarityAlgoParams(Params):
    __camel_case__ = True

    #: the reference's (untrained) defaults
    #: (KeywordSimilarityAlgorithm.scala:36-37)
    sim_weight: float = 1.0
    sim_threshold: float = 1.0


@dataclasses.dataclass
class KeywordSimilarityModel:
    user_keywords: Dict[str, Dict[str, float]]
    item_keywords: Dict[str, Dict[str, float]]
    sim_weight: float
    sim_threshold: float


class KeywordSimilarityAlgorithm(Algorithm):
    params_class = KeywordSimilarityAlgoParams
    query_class_ = Query

    def __init__(self, params: KeywordSimilarityAlgoParams =
                 KeywordSimilarityAlgoParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext,
              td: TrainingData) -> KeywordSimilarityModel:
        return KeywordSimilarityModel(
            user_keywords=td.user_keywords,
            item_keywords=td.item_keywords,
            sim_weight=self.params.sim_weight,
            sim_threshold=self.params.sim_threshold,
        )

    def predict(self, model: KeywordSimilarityModel,
                query: Query) -> Prediction:
        u = model.user_keywords.get(query.user)
        i = model.item_keywords.get(query.item)
        confidence = 0.0
        if u and i:
            # findKeywordSimilarity: Σ w_u(t) · w_i(t)
            small, big = (u, i) if len(u) <= len(i) else (i, u)
            confidence = sum(w * big.get(t, 0.0) for t, w in small.items())
        return Prediction(
            confidence=confidence,
            acceptance=confidence * model.sim_weight
            >= model.sim_threshold,
        )


@dataclasses.dataclass(frozen=True)
class RandomAlgoParams(Params):
    __camel_case__ = True

    seed: int = 3
    acceptance_threshold: float = 0.5


@dataclasses.dataclass
class RandomModel:
    seed: int
    threshold: float


class RandomAlgorithm(Algorithm):
    """The baseline (RandomAlgorithm.scala / RandomModel.scala): a seeded
    uniform confidence, deterministic per (user, item)."""

    params_class = RandomAlgoParams
    query_class_ = Query

    def __init__(self, params: RandomAlgoParams = RandomAlgoParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, td: TrainingData) -> RandomModel:
        return RandomModel(seed=self.params.seed,
                           threshold=self.params.acceptance_threshold)

    def predict(self, model: RandomModel, query: Query) -> Prediction:
        import zlib

        # stable across processes: Python's str hash is salted per
        # interpreter, which would break the seeded-determinism contract
        key = f"{model.seed}\x00{query.user}\x00{query.item}".encode()
        rng = np.random.default_rng(zlib.crc32(key))
        confidence = float(rng.random())
        return Prediction(confidence=confidence,
                          acceptance=confidence >= model.threshold)


@dataclasses.dataclass(frozen=True)
class SimRankAlgoParams(Params):
    __camel_case__ = True

    decay: float = 0.8          # DeltaSimRankRDD.scala:31
    iterations: int = 7
    acceptance_threshold: float = 0.01


@dataclasses.dataclass
class SimRankModel:
    similarities: np.ndarray    # [N, N]
    node_index: Dict[str, int]
    threshold: float
    user_entity: str = "user"
    item_entity: str = "item"


class SimRankAlgorithm(Algorithm):
    params_class = SimRankAlgoParams
    query_class_ = Query

    def __init__(self, params: SimRankAlgoParams = SimRankAlgoParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, td: TrainingData) -> SimRankModel:
        from incubator_predictionio_tpu.ops.simrank import simrank

        nodes = sorted({n for e in td.edges for n in e})
        index = {n: k for k, n in enumerate(nodes)}
        if not nodes:
            return SimRankModel(
                similarities=np.zeros((0, 0), np.float32),
                node_index={}, threshold=self.params.acceptance_threshold,
                user_entity=td.user_entity, item_entity=td.item_entity)
        src = np.array([index[a] for a, _ in td.edges], np.int64)
        dst = np.array([index[b] for _, b in td.edges], np.int64)
        sims = simrank(src, dst, len(nodes), decay=self.params.decay,
                       iterations=self.params.iterations)
        return SimRankModel(similarities=sims, node_index=index,
                            threshold=self.params.acceptance_threshold,
                            user_entity=td.user_entity,
                            item_entity=td.item_entity)

    def predict(self, model: SimRankModel, query: Query) -> Prediction:
        a = model.node_index.get(f"{model.user_entity}:{query.user}")
        b = model.node_index.get(f"{model.item_entity}:{query.item}")
        if b is None:
            b = model.node_index.get(f"{model.user_entity}:{query.item}")
        confidence = 0.0
        if a is not None and b is not None:
            confidence = float(model.similarities[a, b])
        return Prediction(confidence=confidence,
                          acceptance=confidence >= model.threshold)


class FriendRecommendationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            FriendRecommendationDataSource,
            IdentityPreparator,
            {
                "keyword": KeywordSimilarityAlgorithm,
                "random": RandomAlgorithm,
                "simrank": SimRankAlgorithm,
            },
            FirstServing,
        )
