"""CLI command implementations.

Parity: tools/.../console/Pio.scala:42-351 and tools/.../commands/
{App,AccessKey,Engine,Management,Export,Import}.scala — app/key/channel
CRUD, engine resolution from engine.json, train/eval/deploy drivers,
events export/import, end-to-end status validation.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import logging
import os
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Channel,
    Storage,
    is_valid_channel_name,
)

logger = logging.getLogger(__name__)


class CommandError(Exception):
    """User-facing command failure (exit code 1)."""


# ---------------------------------------------------------------------------
# app / accesskey / channel (commands/App.scala, commands/AccessKey.scala)
# ---------------------------------------------------------------------------

def app_new(name: str, app_id: int = 0, description: Optional[str] = None,
            access_key: str = "") -> Dict[str, Any]:
    apps = Storage.get_meta_data_apps()
    if apps.get_by_name(name) is not None:
        raise CommandError(f"App {name} already exists. Aborting.")
    new_id = apps.insert(App(app_id, name, description))
    if new_id is None:
        raise CommandError(f"Unable to create new app: {name}")
    Storage.get_events().init(new_id)
    key = Storage.get_meta_data_access_keys().insert(
        AccessKey(access_key, new_id, ())
    )
    if key is None:
        Storage.get_events().remove(new_id)
        apps.delete(new_id)
        raise CommandError(
            f"Unable to create new access key for app {name} "
            "(duplicate key?). Aborting."
        )
    print(f"Initialized Event Store for this app ID: {new_id}.")
    print("Created new app:")
    print(f"      Name: {name}")
    print(f"        ID: {new_id}")
    print(f"Access Key: {key}")
    return {"id": new_id, "name": name, "accessKey": key}


def app_list() -> List[Dict[str, Any]]:
    apps = sorted(Storage.get_meta_data_apps().get_all(), key=lambda a: a.name)
    keys = Storage.get_meta_data_access_keys()
    out = []
    print(f"{'Name':<20}|{'ID':>6}| Access Key(s)")
    for app in apps:
        app_keys = [k.key for k in keys.get_by_appid(app.id)]
        print(f"{app.name:<20}|{app.id:>6}| {', '.join(app_keys)}")
        out.append({"name": app.name, "id": app.id, "accessKeys": app_keys})
    print(f"Finished listing {len(apps)} app(s).")
    return out


def _get_app(name: str) -> App:
    app = Storage.get_meta_data_apps().get_by_name(name)
    if app is None:
        raise CommandError(f"App {name} does not exist. Aborting.")
    return app


def app_show(name: str) -> Dict[str, Any]:
    app = _get_app(name)
    keys = Storage.get_meta_data_access_keys().get_by_appid(app.id)
    channels = Storage.get_meta_data_channels().get_by_appid(app.id)
    print(f"    App Name: {app.name}")
    print(f"      App ID: {app.id}")
    print(f" Description: {app.description or ''}")
    for k in keys:
        allowed = "(all)" if not k.events else ", ".join(k.events)
        print(f"  Access Key: {k.key} | {allowed}")
    for c in channels:
        print(f"     Channel: {c.name} (ID {c.id})")
    return {
        "name": app.name, "id": app.id, "description": app.description,
        "accessKeys": [k.key for k in keys],
        "channels": [c.name for c in channels],
    }


def app_delete(name: str) -> None:
    app = _get_app(name)
    channels = Storage.get_meta_data_channels()
    events = Storage.get_events()
    for channel in channels.get_by_appid(app.id):
        events.remove(app.id, channel.id)
        channels.delete(channel.id)
    events.remove(app.id)
    keys = Storage.get_meta_data_access_keys()
    for key in keys.get_by_appid(app.id):
        keys.delete(key.key)
    Storage.get_meta_data_apps().delete(app.id)
    print(f"App successfully deleted: {name}")


def app_data_delete(name: str, channel: Optional[str] = None) -> None:
    app = _get_app(name)
    channel_id = None
    if channel is not None:
        matches = [
            c for c in Storage.get_meta_data_channels().get_by_appid(app.id)
            if c.name == channel
        ]
        if not matches:
            raise CommandError(f"Channel {channel} does not exist.")
        channel_id = matches[0].id
    events = Storage.get_events()
    events.remove(app.id, channel_id)
    events.init(app.id, channel_id)
    print(f"Deleted all data of app {name}"
          + (f" channel {channel}" if channel else ""))


def channel_new(app_name: str, channel_name: str) -> Dict[str, Any]:
    app = _get_app(app_name)
    if not is_valid_channel_name(channel_name):
        raise CommandError(f"Invalid channel name: {channel_name}.")
    channels = Storage.get_meta_data_channels()
    channel_id = channels.insert(Channel(0, channel_name, app.id))
    if channel_id is None:
        raise CommandError(
            f"Channel {channel_name} already exists for app {app_name}."
        )
    Storage.get_events().init(app.id, channel_id)
    print(f"Created new channel {channel_name} (ID {channel_id}) "
          f"for app {app_name}.")
    return {"id": channel_id, "name": channel_name, "appId": app.id}


def channel_delete(app_name: str, channel_name: str) -> None:
    app = _get_app(app_name)
    channels = Storage.get_meta_data_channels()
    matches = [
        c for c in channels.get_by_appid(app.id) if c.name == channel_name
    ]
    if not matches:
        raise CommandError(
            f"Channel {channel_name} does not exist for app {app_name}."
        )
    Storage.get_events().remove(app.id, matches[0].id)
    channels.delete(matches[0].id)
    print(f"Deleted channel {channel_name} of app {app_name}.")


def accesskey_new(app_name: str, key: str = "",
                  events: Tuple[str, ...] = ()) -> str:
    app = _get_app(app_name)
    new_key = Storage.get_meta_data_access_keys().insert(
        AccessKey(key, app.id, tuple(events))
    )
    if new_key is None:
        raise CommandError("Unable to create access key.")
    print(f"Created new access key: {new_key}")
    return new_key


def accesskey_list(app_name: Optional[str] = None) -> List[AccessKey]:
    keys_dao = Storage.get_meta_data_access_keys()
    if app_name is not None:
        keys = keys_dao.get_by_appid(_get_app(app_name).id)
    else:
        keys = keys_dao.get_all()
    for k in sorted(keys, key=lambda k: k.key):
        allowed = "(all)" if not k.events else ", ".join(k.events)
        print(f"{k.key} | app {k.appid} | {allowed}")
    print(f"Finished listing {len(keys)} access key(s).")
    return list(keys)


def accesskey_delete(key: str) -> None:
    if not Storage.get_meta_data_access_keys().delete(key):
        raise CommandError(f"Error deleting access key {key}.")
    print(f"Deleted access key {key}.")


# ---------------------------------------------------------------------------
# engine resolution (commands/Engine.scala + WorkflowUtils.getEngine)
# ---------------------------------------------------------------------------

def load_variant(engine_json: str = "engine.json") -> Dict[str, Any]:
    path = Path(engine_json)
    if not path.exists():
        raise CommandError(
            f"{engine_json} does not exist. Aborting. (Run from your engine "
            "template directory, or pass --variant.)"
        )
    with open(path) as f:
        return json.load(f)


def resolve_engine_factory(factory_path: str) -> Any:
    """Load the engine factory class/object from ``module:Attr`` or
    ``module.Attr`` (WorkflowUtils.getEngine:64 resolves Scala objects vs
    classes the same way)."""
    if ":" in factory_path:
        module_name, _, attr = factory_path.partition(":")
    else:
        module_name, _, attr = factory_path.rpartition(".")
    if not module_name:
        raise CommandError(f"Invalid engineFactory {factory_path!r}")
    sys.path.insert(0, os.getcwd())
    try:
        module = importlib.import_module(module_name)
    except ImportError as e:
        raise CommandError(
            f"Cannot import engine factory module {module_name!r}: {e}"
        ) from e
    finally:
        sys.path.pop(0)
    try:
        factory = getattr(module, attr)
    except AttributeError as e:
        raise CommandError(
            f"Module {module_name!r} has no attribute {attr!r}"
        ) from e
    return factory() if isinstance(factory, type) else factory


def engine_identity(engine_dir: str, engine_factory: str) -> str:
    """Engine identity = (engine directory, factory), like the reference's
    manifest id (commands/Engine.scala:123-156 derives it from the engine
    directory). Keying instances on the variant's own "id" field would
    collide across engines that all ship the default variant id — deploy
    would then pick another engine's latest instance; mixing in the factory
    also keeps two different engines sharing one directory apart. The ONE
    derivation used by build manifests and train/deploy instance lookups."""
    import hashlib

    abs_dir = str(Path(engine_dir).resolve())
    return hashlib.sha1(
        f"{abs_dir}\0{engine_factory}".encode()).hexdigest()[:16]


def engine_id_for_variant_path(variant_path: str,
                               variant: Dict[str, Any]) -> str:
    return engine_identity(str(Path(variant_path).resolve().parent),
                           variant.get("engineFactory", ""))


def engine_from_variant(variant: Dict[str, Any]):
    factory_path = variant.get("engineFactory")
    if not factory_path:
        raise CommandError("engine.json is missing 'engineFactory'.")
    factory = resolve_engine_factory(factory_path)
    engine = factory.apply()
    return engine, engine.jvalue_to_engine_params(variant)


# ---------------------------------------------------------------------------
# build / register (commands/Engine.scala:158-260, RegisterEngine.scala,
# commands/Template.scala)
# ---------------------------------------------------------------------------

def verify_template_min_version(engine_dir: str = ".") -> Optional[str]:
    """template.json min-version gate (commands/Template.scala:38-83).

    Returns a warning string when ``pio.required.version`` exceeds the
    running framework version; None otherwise (including no template.json —
    the reference warns separately but proceeds either way).
    """
    from incubator_predictionio_tpu import __version__

    path = Path(engine_dir) / "template.json"
    if not path.exists():
        return None
    try:
        with open(path) as f:
            required = json.load(f).get("pio", {}).get("version", {}).get("min")
    except (json.JSONDecodeError, AttributeError):
        return None
    if not required:
        return None

    def _key(v: str) -> tuple:
        return tuple(int(p) for p in re.findall(r"\d+", v)[:3])

    if _key(str(required)) > _key(__version__):
        return (
            f"This engine template requires at least version {required}, "
            f"but you are running {__version__}. It may not work properly."
        )
    return None


def _manifest_for_engine_dir(engine_dir: str,
                             variant: Dict[str, Any]) -> "storage_base.EngineManifest":
    """manifest.json regeneration (commands/Engine.scala:123-156): the ID is
    derived from the engine directory, the version from a content hash of the
    variant (there is no JAR to fingerprint)."""
    import hashlib

    from incubator_predictionio_tpu import __version__
    from incubator_predictionio_tpu.data.storage import base as storage_base

    abs_dir = str(Path(engine_dir).resolve())
    digest = hashlib.sha1(
        json.dumps(variant, sort_keys=True).encode()
    ).hexdigest()[:16]
    files = sorted(
        str(p) for p in Path(engine_dir).glob("*.json")
        if p.name != "manifest.json"   # the output of this very build
    ) + sorted(str(p) for p in Path(engine_dir).glob("*.py"))
    return storage_base.EngineManifest(
        id=engine_identity(abs_dir, variant.get("engineFactory", "")),
        version=digest,
        name=Path(abs_dir).name,
        engine_factory=variant.get("engineFactory", ""),
        description=f"pio-tpu {__version__} engine at {abs_dir}",
        files=tuple(files),
    )


def build(engine_dir: str = ".", engine_json: str = "engine.json") -> str:
    """``pio build`` (commands/Engine.scala:158-260). There is no sbt
    compile step: "building" validates the variant resolves to an importable
    factory, checks the template version gate, writes manifest.json, and
    registers the EngineManifest."""
    warning = verify_template_min_version(engine_dir)
    if warning:
        print(f"WARNING: {warning}")
    variant = load_variant(str(Path(engine_dir) / engine_json))
    # import + params extraction = the "compile" step
    _engine, engine_params = engine_from_variant(variant)
    n_algos = len(engine_params.algorithm_params_list) or 1
    print(f"Engine {variant.get('engineFactory')} is valid "
          f"({n_algos} algorithm(s) configured).")
    manifest = _manifest_for_engine_dir(engine_dir, variant)
    with open(Path(engine_dir) / "manifest.json", "w") as f:
        json.dump(
            {
                "id": manifest.id,
                "version": manifest.version,
                "name": manifest.name,
                "engineFactory": manifest.engine_factory,
                "description": manifest.description,
                "files": list(manifest.files),
            },
            f, indent=2,
        )
    Storage.get_meta_data_engine_manifests().update(manifest, upsert=True)
    print(f"Engine {manifest.id} {manifest.version} registered "
          f"({manifest.engine_factory}).")
    return manifest.id


def unregister(engine_dir: str = ".") -> None:
    """``pio unregister`` (RegisterEngine.unregisterEngine:58)."""
    path = Path(engine_dir) / "manifest.json"
    if not path.exists():
        raise CommandError(f"{path} does not exist. Nothing to unregister.")
    with open(path) as f:
        m = json.load(f)
    if Storage.get_meta_data_engine_manifests().delete(m["id"], m["version"]):
        print(f"Engine {m['id']} {m['version']} unregistered.")
    else:
        raise CommandError(
            f"Engine {m['id']} {m['version']} is not registered."
        )


# ---------------------------------------------------------------------------
# export / import (tools/.../export/EventsToFile.scala, imprt/FileToEvents.scala)
# ---------------------------------------------------------------------------

def _appid_or_name_to_name(appid_or_name: str) -> str:
    """The reference CLI accepts either an app ID or name for export/import
    (Console.scala export/import subcommands); the EventStore facade resolves
    names, so translate a numeric ID to its app name first."""
    if appid_or_name.isdigit():
        app = Storage.get_meta_data_apps().get(int(appid_or_name))
        if app is None:
            raise CommandError(f"App ID {appid_or_name} does not exist.")
        return app.name
    return appid_or_name


#: parquet schema: scalar event fields as columns, properties as a JSON
#: string column (the reference dumps a DataFrame of the Event case class —
#: EventsToFile.scala:44,88-93; a JSON property column keeps arbitrary
#: DataMap payloads schema-stable across rows)
_PARQUET_FIELDS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "tags", "prId",
    "creationTime",
)


def export_events(app_name: str, output: str,
                  channel: Optional[str] = None,
                  format: str = "json") -> int:
    from incubator_predictionio_tpu.data.store import EventStore

    app_name = _appid_or_name_to_name(app_name)
    found = EventStore.find(app_name=app_name, channel_name=channel)
    if format == "parquet":
        n = _export_parquet(found, output)
    elif format == "json":
        n = 0
        with open(output, "w") as f:
            for event in found:
                f.write(json.dumps(event.to_jsonable()) + "\n")
                n += 1
    else:
        raise CommandError(
            f"unknown export format {format!r} (json or parquet — "
            "EventsToFile.scala:44 parity)")
    print(f"Exported {n} events to {output}.")
    return n


def _export_parquet(events, output: str, batch_rows: int = 65536) -> int:
    """EventsToFile.scala:88-93's DataFrame.write.parquet role, streamed
    in bounded row batches."""
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - baked into the image
        raise CommandError(
            "parquet export needs pyarrow, which is not installed; "
            "use --format json") from e

    schema = pa.schema([
        (name, pa.list_(pa.string()) if name == "tags" else pa.string())
        for name in _PARQUET_FIELDS
    ])
    n = 0
    writer = pq.ParquetWriter(output, schema)
    try:
        batch = {name: [] for name in _PARQUET_FIELDS}
        for event in events:
            doc = event.to_jsonable()
            for name in _PARQUET_FIELDS:
                if name == "properties":
                    batch[name].append(json.dumps(doc.get(name, {})))
                elif name == "tags":
                    batch[name].append(doc.get(name, []))
                else:
                    batch[name].append(doc.get(name))
            n += 1
            if n % batch_rows == 0:
                writer.write_table(pa.table(batch, schema=schema))
                batch = {name: [] for name in _PARQUET_FIELDS}
        if batch[_PARQUET_FIELDS[0]] or n == 0:
            writer.write_table(pa.table(batch, schema=schema))
    finally:
        writer.close()
    return n


def _iter_import_file(input_path: str, format: str):
    """Yield (location, jsonable-event-dict) from a JSON-lines or parquet
    export file."""
    if format == "parquet":
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover
            raise CommandError(
                "parquet import needs pyarrow, which is not installed"
            ) from e
        row_no = 0
        # stream row batches: a multi-million-row export never materializes
        # whole-file columns (mirrors the export side's bounded batching)
        for batch in pq.ParquetFile(input_path).iter_batches(65536):
            cols = batch.to_pydict()
            names = [n for n in _PARQUET_FIELDS if n in cols]
            for i in range(batch.num_rows):
                row_no += 1
                location = f"{input_path}:row {row_no}"
                doc = {}
                for name in names:
                    value = cols[name][i]
                    if value is None:
                        continue
                    if name == "properties":
                        try:
                            value = json.loads(value)
                        except ValueError as e:
                            raise CommandError(
                                f"{location}: invalid properties JSON: {e}"
                            ) from e
                    doc[name] = value
                yield location, doc
    else:
        with open(input_path) as f:
            for line_no, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError as e:
                    raise CommandError(
                        f"{input_path}:{line_no}: invalid event: {e}") from e
                yield f"{input_path}:{line_no}", doc


#: minimum batch size for the columnar import fast path (below it the
#: Python interning pass costs more than the per-event path saves)
_FAST_IMPORT_MIN = int(os.environ.get("PIO_IMPORT_FAST_MIN", "10000"))


def _as_uniform_interactions(events):
    """Events → (Interactions, entity_type, target_type, name, value_prop,
    times_ms) when the columnar bulk import is observably equivalent to
    per-event inserts, else None.

    The equivalence conditions live in ``base.uniform_interactions`` —
    shared with the cpplog REST batch gate so the two cannot drift.
    Export round-trips carry eventIds (upsert semantics!) and therefore
    never take this path; explicit creationTime is screened by the caller
    (the parsed Event cannot distinguish explicit from defaulted)."""
    if len(events) < _FAST_IMPORT_MIN:
        return None  # interning overhead beats the win on small files
    from incubator_predictionio_tpu.data.storage.base import (
        uniform_interactions,
    )

    return uniform_interactions(events)


def import_events(app_name: str, input_path: str,
                  channel: Optional[str] = None,
                  format: str = "json") -> int:
    from incubator_predictionio_tpu.data.event import validate_event
    from incubator_predictionio_tpu.data.store import EventStore
    from incubator_predictionio_tpu.data.storage import base as storage_base

    app_name = _appid_or_name_to_name(app_name)

    events = []
    # doc-level screen for the fast path: a parsed Event cannot tell an
    # explicit creationTime from the defaulted one, and creationTime is
    # exactly what the columnar renderer would rewrite
    plain_docs = True
    for location, doc in _iter_import_file(input_path, format):
        try:
            event = Event.from_jsonable(doc)
            validate_event(event)
            events.append(event)
        except ValueError as e:
            raise CommandError(f"{location}: invalid event: {e}") from e
        plain_docs = plain_docs and "creationTime" not in doc
    dao = Storage.get_events()
    fast = (
        _as_uniform_interactions(events)
        # only when the backend has a NATIVE columnar import — the base
        # fallback converts straight back to Events, paying twice
        if plain_docs and type(dao).import_interactions
        is not storage_base.Events.import_interactions else None)
    if fast is not None:
        from incubator_predictionio_tpu.data.store import _resolve

        inter, etype, tetype, name, vprop, times = fast
        app_id, channel_id = _resolve(app_name, channel)
        n = dao.import_interactions(
            inter, app_id, channel_id, entity_type=etype,
            target_entity_type=tetype, event_name=name, value_prop=vprop,
            times=times)
        print(f"Imported {n} events (native columnar path).")
        return n
    EventStore.write(events, app_name=app_name, channel_name=channel)
    print(f"Imported {len(events)} events.")
    return len(events)


# ---------------------------------------------------------------------------
# status (commands/Management.scala:99-178)
# ---------------------------------------------------------------------------

def upgrade(appid_or_name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Rewrite event stores in the current on-disk format — the store
    migration verb (the reference's HBase upgrade tool role,
    data/.../storage/hbase/upgrade/Upgrade.scala). Delegates to the
    backend's ``compact`` (cpplog: live-record rewrite dropping
    tombstones + adding sidecars; sqlite: VACUUM); backends without a
    migration (memory) are skipped. Covers the default channel plus
    every named channel of each selected app."""
    events = Storage.get_events()
    if not hasattr(events, "compact"):
        return []
    apps_dao = Storage.get_meta_data_apps()
    if appid_or_name is not None:
        apps = [_get_app(_appid_or_name_to_name(appid_or_name))]
    else:
        apps = apps_dao.get_all()
    results: List[Dict[str, Any]] = []
    for app in apps:
        channel_ids = [None] + [
            c.id for c in Storage.get_meta_data_channels().get_by_appid(
                app.id)
        ]
        for cid in channel_ids:
            stats = events.compact(app.id, cid)
            results.append({"app": app.name, "channel": cid or "default",
                            **stats})
    return results


def status() -> bool:
    from incubator_predictionio_tpu import __version__

    print(f"PredictionIO-TPU {__version__}")
    print("Inspecting storage backend connections...")
    try:
        Storage.verify_all_data_objects()
        print("Storage: OK (metadata, event data, model data all verified)")
    except Exception as e:
        print(f"Storage: ERROR: {e}")
        return False
    try:
        import jax

        devices = jax.devices()
        print(f"Compute: jax {jax.__version__}, {len(devices)} device(s): "
              f"{devices[0].platform}")
    except Exception as e:
        print(f"Compute: ERROR: {e}")
        return False
    print("Your system is all ready to go.")
    return True
