"""``pio`` CLI — the full verb set.

Parity: tools/.../console/Console.scala:153-600 subcommand matrix:
version / status / app {new,list,show,delete,data-delete,channel-new,
channel-delete} / accesskey {new,list,delete} / train / eval / deploy /
undeploy / eventserver / adminserver / dashboard / export / import / build /
run / template {get,list}.

Design delta from the reference: no spark-submit process hop
(Runner.runOnSpark, tools/.../Runner.scala:101-213) — train/eval/deploy run
in-process on the TPU host, so ``pio build`` has no sbt step (it validates
engine.json and importability instead).
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import os
import sys
from typing import Any, List, Optional

from incubator_predictionio_tpu import __version__
from incubator_predictionio_tpu.cli import commands
from incubator_predictionio_tpu.cli.commands import CommandError


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio",
        description="TPU-native PredictionIO-compatible machine learning server",
    )
    parser.add_argument("--version", action="version",
                        version=f"pio-tpu {__version__}")
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="show version")
    sub.add_parser("status", help="validate storage + compute configuration")

    # -- app ---------------------------------------------------------------
    app = sub.add_parser("app", help="manage apps").add_subparsers(
        dest="app_command"
    )
    p = app.add_parser("new")
    p.add_argument("name")
    p.add_argument("--id", type=int, default=0)
    p.add_argument("--description")
    p.add_argument("--access-key", default="")
    app.add_parser("list")
    p = app.add_parser("show")
    p.add_argument("name")
    p = app.add_parser("delete")
    p.add_argument("name")
    p.add_argument("-f", "--force", action="store_true")
    p = app.add_parser("data-delete")
    p.add_argument("name")
    p.add_argument("--channel")
    p.add_argument("-f", "--force", action="store_true")
    p = app.add_parser("channel-new")
    p.add_argument("name")
    p.add_argument("channel")
    p = app.add_parser("channel-delete")
    p.add_argument("name")
    p.add_argument("channel")
    p.add_argument("-f", "--force", action="store_true")

    # -- accesskey ---------------------------------------------------------
    ak = sub.add_parser("accesskey", help="manage access keys").add_subparsers(
        dest="accesskey_command"
    )
    p = ak.add_parser("new")
    p.add_argument("app_name")
    p.add_argument("--key", default="")
    p.add_argument("--events", nargs="*", default=[])
    p = ak.add_parser("list")
    p.add_argument("app_name", nargs="?")
    p = ak.add_parser("delete")
    p.add_argument("key")

    # -- engine lifecycle --------------------------------------------------
    for name, help_text in (
        ("build", "validate the engine in the current directory"),
        ("train", "train the engine in the current directory"),
        ("deploy", "deploy the latest trained engine instance"),
    ):
        p = sub.add_parser(name, help=help_text)
        p.add_argument("--variant", default="engine.json")
        if name in ("train", "deploy"):
            p.add_argument(
                "--hosts", default="",
                help="comma-separated pod hosts: launch this command on "
                     "every host with the coordinator env trio set "
                     "(parallel/launcher.py; Runner.scala:101-213 parity)")
        if name == "train":
            p.add_argument("--batch", default="")
            p.add_argument("--skip-sanity-check", action="store_true")
            p.add_argument("--stop-after-read", action="store_true")
            p.add_argument("--stop-after-prepare", action="store_true")
            p.add_argument("--seed", type=int, default=0)
            p.add_argument("--model-parallelism", type=int, default=1)
        if name == "deploy":
            p.add_argument("--ip", default="0.0.0.0")
            p.add_argument("--port", type=int, default=8000)
            p.add_argument("--engine-instance-id")
            p.add_argument("--event-server-ip", default="0.0.0.0")
            p.add_argument("--event-server-port", type=int, default=7070)
            p.add_argument("--accesskey", default=None)
            p.add_argument("--feedback", action="store_true")
            p.add_argument("--server-key", default=None)
            p.add_argument("--log-url", default=None,
                           help="POST query errors to this collector URL")
            p.add_argument("--log-prefix", default="",
                           help="prefix prepended to each shipped log line")

    sub.add_parser("unregister",
                   help="unregister the engine in the current directory")

    p = sub.add_parser("eval", help="run evaluation / hyperparameter tuning")
    p.add_argument("evaluation_class",
                   help="module:attr of the Evaluation object")
    p.add_argument("engine_params_generator_class", nargs="?",
                   help="module:attr of the EngineParamsGenerator")
    p.add_argument("--batch", default="")
    p.add_argument("--output-best", default="best.json")
    p.add_argument("--hosts", default="",
                   help="comma-separated pod hosts (see `pio train --hosts`)")

    p = sub.add_parser("undeploy", help="stop a deployed engine server")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--server-key", default=None)

    # -- servers -----------------------------------------------------------
    p = sub.add_parser("eventserver", help="start the event server")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7070)
    p.add_argument("--stats", action="store_true")
    def _positive_int(v: str) -> int:
        n = int(v)
        if n <= 0:
            raise argparse.ArgumentTypeError(
                f"must be a positive integer (got {v})")
        return n

    p.add_argument(
        "--batch-cap", type=_positive_int, default=None, metavar="N",
        help="max events per POST /batch/events.json (default 50 — the "
             "reference's wire contract; raise for columnar bulk loaders)")
    p = sub.add_parser("adminserver", help="start the admin API server")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7071)
    p = sub.add_parser("dashboard", help="start the evaluation dashboard")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9000)
    p = sub.add_parser(
        "storageserver",
        help="export this box's storage source to other boxes "
             "(point their PIO_STORAGE_SOURCES_<N>_TYPE=remote at it)")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7077)
    p.add_argument("--source", default=None,
                   help="export ONE PIO_STORAGE_SOURCES_<NAME>; default "
                        "routes by repository (metadata/eventdata/"
                        "modeldata each to its configured source)")
    p.add_argument("--auth-key", default=None,
                   help="shared key clients must send (X-Pio-Storage-Key)")

    # -- data --------------------------------------------------------------
    p = sub.add_parser("export",
                       help="export app events to JSON lines or parquet")
    p.add_argument("--appid-or-name", dest="app_name", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--channel")
    p.add_argument("--format", choices=("json", "parquet"), default="json")
    p = sub.add_parser("import", help="import exported events into an app")
    p.add_argument("--appid-or-name", dest="app_name", required=True)
    p.add_argument("--input", required=True)
    p.add_argument("--channel")
    p.add_argument("--format", choices=("json", "parquet"), default="json")

    # -- misc --------------------------------------------------------------
    p = sub.add_parser("run", help="run an arbitrary main in the engine env")
    p.add_argument("main_class")
    p.add_argument("args", nargs="*")
    tpl = sub.add_parser("template", help="(deprecated)").add_subparsers(
        dest="template_command"
    )
    tpl.add_parser("get")
    tpl.add_parser("list")
    # `pio upgrade` (Console.scala upgrade subcommand → the HBase upgrade
    # tool's role): rewrite event stores in the current on-disk format —
    # drops tombstoned records, adds sidecars to pre-sidecar records
    # (cpplog), VACUUMs the JDBC store (sqlite)
    p = sub.add_parser(
        "upgrade", help="rewrite event stores in the current format")
    p.add_argument("app", nargs="?", default=None,
                   help="app name or id (default: every app)")

    return parser


def _confirm(prompt: str, force: bool) -> bool:
    if force:
        return True
    answer = input(f"{prompt} (YES to confirm): ")
    return answer == "YES"


#: verbs that never need the accelerator. On single-tenant devices (one
#: TPU chip per box/tunnel) an ingest or metadata process that lazily
#: initializes the device backend CLAIMS the chip — and then `pio train`
#: on the same box blocks forever waiting for it. Pin these verbs to the
#: CPU platform before any backend can initialize. (The env var alone is
#: not enough: platform plugins may re-pin jax.config at interpreter
#: start, so this must be a config update.)
_STORAGE_ONLY_VERBS = frozenset({
    "eventserver", "adminserver", "dashboard", "storageserver",
    "app", "accesskey", "export", "import", "upgrade", "unregister",
    "template", "undeploy", "build",
})


def _ensure_accelerator(timeout_s: float) -> None:
    """Fail fast — with an actionable message — when the accelerator
    cannot initialize.

    On single-tenant devices a chip claimed by another process makes the
    PJRT client constructor block *indefinitely* with no output; a `pio
    train` that sits silent forever reads as a hang, not a diagnosis. The
    probe runs device init on a daemon thread and gives up after
    ``timeout_s`` (PIO_ACCEL_INIT_TIMEOUT_S, default 180 — first contact
    through a tunnel can legitimately take tens of seconds).

    Lease-safety contract for the timeout path: the blocked daemon thread
    cannot be cancelled and may sit mid-PJRT-construction holding a
    partial chip claim, so the CommandError raised here MUST propagate to
    a normal interpreter exit — never ``os._exit`` and never SIGKILL from
    a wrapper — so the process teardown closes the client's sockets and
    the relay sees a clean disconnect. An abrupt kill at this point is
    exactly what wedges the single-tenant lease for the next process
    (observed: hours-long wedge). A blocked probe is a *waiter*, not a
    holder; letting the process exit normally releases nothing it owns
    and cannot wedge the chip."""
    import threading

    done = threading.Event()
    err: list = []

    def probe() -> None:
        try:
            import jax

            jax.devices()
        except Exception as e:  # surfaced as the real failure below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True, name="pio-accel-probe")
    t.start()
    if not done.wait(timeout_s):
        raise CommandError(
            f"accelerator did not initialize within {timeout_s:.0f}s — on "
            "a single-tenant device this usually means another process "
            "holds the chip (a deployed engine server, a stuck run, or a "
            "stale lease). Stop it (`pio undeploy`, kill the process) and "
            "retry, or raise PIO_ACCEL_INIT_TIMEOUT_S if first contact is "
            "genuinely slow on this platform.")
    if err:
        raise CommandError(f"accelerator initialization failed: {err[0]}")


def _backends_initialized() -> bool:
    """Whether any JAX backend has already been constructed (private-API
    probe, single copy — main() and dispatch() both need it)."""
    try:
        from jax._src import xla_bridge as _xb

        return bool(getattr(_xb, "_backends", None))
    except Exception:
        return False


def _accel_timeout_s() -> float:
    raw = os.environ.get("PIO_ACCEL_INIT_TIMEOUT_S", "180")
    try:
        return float(raw)
    except ValueError:
        print(f"warning: PIO_ACCEL_INIT_TIMEOUT_S={raw!r} is not a "
              "number; using 180", file=sys.stderr)
        return 180.0


def dispatch(args: argparse.Namespace) -> int:  # noqa: C901
    cmd = args.command
    if cmd is None:
        build_parser().print_help()
        return 1
    if cmd == "status":
        # train/eval/deploy run their watchdog AFTER the pod relaunch
        # branch (the launcher must never claim the chip its own workers
        # need) and after jax.distributed joins — see below
        _ensure_accelerator(_accel_timeout_s())
    if cmd in _STORAGE_ONLY_VERBS:
        # PIO_STORAGE_VERB_PLATFORM overrides the cpu pin for users who
        # genuinely want a storage verb on the device (the plain
        # JAX_PLATFORMS env cannot express that intent here — the image
        # itself pins it globally)
        platform = os.environ.get("PIO_STORAGE_VERB_PLATFORM", "cpu")
        try:
            import jax

            if not _backends_initialized():
                jax.config.update("jax_platforms", platform)
        except Exception:
            print("warning: could not pin the storage-only verb to the "
                  f"{platform} platform; this process may claim the "
                  "accelerator", file=sys.stderr)
    if cmd in ("deploy", "eventserver", "adminserver", "dashboard",
               "storageserver"):
        # long-running server verbs emit the per-request JSON span log
        # out of the box (one line per request on stderr, trace-ID
        # correlated; PIO_TRACE_LOG=off disables — docs/observability.md)
        from incubator_predictionio_tpu.obs.trace import enable_span_logging

        enable_span_logging()
    if cmd == "version":
        print(f"pio-tpu {__version__}")
        return 0

    if cmd == "status":
        return 0 if commands.status() else 1

    if cmd == "app":
        ac = args.app_command
        if ac == "new":
            commands.app_new(args.name, args.id, args.description,
                             args.access_key)
        elif ac == "list":
            commands.app_list()
        elif ac == "show":
            commands.app_show(args.name)
        elif ac == "delete":
            if not _confirm(f"Delete app {args.name} and ALL its data?",
                            args.force):
                print("Aborted.")
                return 1
            commands.app_delete(args.name)
        elif ac == "data-delete":
            if not _confirm(f"Delete ALL data of app {args.name}?", args.force):
                print("Aborted.")
                return 1
            commands.app_data_delete(args.name, args.channel)
        elif ac == "channel-new":
            commands.channel_new(args.name, args.channel)
        elif ac == "channel-delete":
            if not _confirm(
                f"Delete channel {args.channel} of app {args.name}?",
                args.force,
            ):
                print("Aborted.")
                return 1
            commands.channel_delete(args.name, args.channel)
        else:
            print("Usage: pio app {new,list,show,delete,data-delete,"
                  "channel-new,channel-delete}")
            return 1
        return 0

    if cmd == "accesskey":
        kc = args.accesskey_command
        if kc == "new":
            commands.accesskey_new(args.app_name, args.key,
                                   tuple(args.events))
        elif kc == "list":
            commands.accesskey_list(args.app_name)
        elif kc == "delete":
            commands.accesskey_delete(args.key)
        else:
            print("Usage: pio accesskey {new,list,delete}")
            return 1
        return 0

    if cmd == "build":
        commands.build(engine_json=args.variant)
        print("No compilation step is needed; your engine is ready to train.")
        return 0

    # pod launch (Runner.runOnSpark parity, Runner.scala:101-213): when
    # --hosts is given and we are NOT already a launched worker, re-run
    # this exact command once per host with the coordinator trio set —
    # each worker then joins the multi-controller runtime via
    # parallel.distributed.ensure_initialized.
    if cmd in ("train", "eval", "deploy") and getattr(args, "hosts", "") \
            and "PIO_PROCESS_ID" not in os.environ:
        from incubator_predictionio_tpu.parallel.launcher import (
            relaunch_over_hosts,
        )

        hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
        return relaunch_over_hosts(
            hosts, argv=getattr(args, "_invocation_argv", None))

    # a launched worker (or an externally-provisioned pod process) joins
    # the multi-controller runtime before any engine code builds a mesh
    if cmd in ("train", "eval", "deploy"):
        if os.environ.get("PIO_COORDINATOR_ADDRESS"):
            from incubator_predictionio_tpu.parallel.distributed import (
                ensure_initialized,
            )

            ensure_initialized()
        # watchdog AFTER the relaunch branch (the launcher returned above
        # without ever touching the device) and AFTER distributed init
        # (backend construction must follow jax.distributed.initialize)
        _ensure_accelerator(_accel_timeout_s())

    if cmd == "unregister":
        commands.unregister()
        return 0

    if cmd == "train":
        from incubator_predictionio_tpu.core.params import WorkflowParams
        from incubator_predictionio_tpu.workflow import CoreWorkflow

        variant = commands.load_variant(args.variant)
        engine, engine_params = commands.engine_from_variant(variant)
        params = WorkflowParams(
            batch=args.batch,
            skip_sanity_check=args.skip_sanity_check,
            stop_after_read=args.stop_after_read,
            stop_after_prepare=args.stop_after_prepare,
            runtime_conf={
                "seed": str(args.seed),
                "model_parallelism": str(args.model_parallelism),
            },
        )
        instance_id = CoreWorkflow.run_train(
            engine,
            engine_params,
            engine_id=commands.engine_id_for_variant_path(args.variant, variant),
            engine_version=variant.get("version", "NOT_VERSIONED"),
            engine_variant=variant.get("id", "default"),
            engine_factory=variant.get("engineFactory", ""),
            params=params,
        )
        if instance_id:
            print(f"Training completed. Engine instance ID: {instance_id}")
        else:
            print("Training shard completed (pod worker; process 0 "
                  "persists the engine instance).")
        return 0

    if cmd == "eval":
        from incubator_predictionio_tpu.workflow import CoreWorkflow

        evaluation = commands.resolve_engine_factory(args.evaluation_class)
        if args.engine_params_generator_class:
            generator = commands.resolve_engine_factory(
                args.engine_params_generator_class
            )
            params_list = generator.engine_params_list
        else:
            params_list = getattr(evaluation, "engine_params_list", None)
            if not params_list:
                raise CommandError(
                    "Provide an EngineParamsGenerator class or set "
                    "engine_params_list on the Evaluation."
                )
        evaluator = evaluation.evaluator
        if args.output_best and hasattr(evaluator, "output_path"):
            evaluator.output_path = args.output_best
        from incubator_predictionio_tpu.core.params import WorkflowParams

        instance_id, result = CoreWorkflow.run_evaluation(
            evaluation, params_list,
            evaluation_class=args.evaluation_class,
            engine_params_generator_class=(
                args.engine_params_generator_class or ""
            ),
            params=WorkflowParams(batch=args.batch),
        )
        if instance_id:
            print(result.to_one_liner())
            print(f"Evaluation completed. Instance ID: {instance_id}")
        else:
            print("Evaluation shard completed (pod worker; process 0 "
                  "persists the result).")
        return 0

    if cmd == "deploy":
        from incubator_predictionio_tpu.servers.prediction_server import (
            PredictionServer,
            ServerConfig,
        )

        variant = commands.load_variant(args.variant)
        engine, _params = commands.engine_from_variant(variant)
        server = PredictionServer(engine, ServerConfig(
            ip=args.ip,
            port=args.port,
            engine_instance_id=args.engine_instance_id,
            engine_id=commands.engine_id_for_variant_path(args.variant, variant),
            engine_version=variant.get("version", "NOT_VERSIONED"),
            engine_variant=variant.get("id", "default"),
            event_server_ip=args.event_server_ip,
            event_server_port=args.event_server_port,
            access_key=args.accesskey,
            feedback=args.feedback,
            server_key=args.server_key,
            log_url=args.log_url,
            log_prefix=args.log_prefix,
        ))
        print(f"Deploying on http://{args.ip}:{args.port} ...")
        asyncio.run(server.serve_forever())
        return 0

    if cmd == "undeploy":
        from incubator_predictionio_tpu.servers.prediction_server import undeploy

        if undeploy(args.ip, args.port, args.server_key):
            print("Undeployed.")
            return 0
        print("Nothing at the given address responded to /stop.")
        return 1

    if cmd == "eventserver":
        from incubator_predictionio_tpu.servers.event_server import (
            EventServer,
            EventServerConfig,
        )

        conf_kw = {}
        if getattr(args, "batch_cap", None) is not None:
            conf_kw["max_batch"] = args.batch_cap
        server = EventServer(EventServerConfig(
            ip=args.ip, port=args.port, stats=args.stats, **conf_kw,
        ))
        print(f"Event Server running on http://{args.ip}:{args.port}")
        asyncio.run(server.serve_forever())
        return 0

    if cmd == "adminserver":
        from incubator_predictionio_tpu.servers.admin import AdminServer

        server = AdminServer(args.ip, args.port)
        print(f"Admin API running on http://{args.ip}:{args.port}")
        asyncio.run(server.serve_forever())
        return 0

    if cmd == "dashboard":
        from incubator_predictionio_tpu.servers.dashboard import DashboardServer

        server = DashboardServer(args.ip, args.port)
        print(f"Dashboard running on http://{args.ip}:{args.port}")
        asyncio.run(server.serve_forever())
        return 0

    if cmd == "storageserver":
        from incubator_predictionio_tpu.data.storage.server import (
            StorageServer,
        )

        server = StorageServer.from_env(
            source=args.source, host=args.ip, port=args.port,
            auth_key=args.auth_key)

        def announce(port: int) -> None:
            # announced AFTER the bind with the KERNEL-assigned port:
            # `--port 0` (ephemeral bind) makes parents stop racing the
            # box for a pre-picked "free" port — they parse this line
            print(f"Storage Server running on http://{args.ip}:{port}",
                  flush=True)

        asyncio.run(server.serve_forever(on_started=announce))
        return 0

    if cmd == "export":
        commands.export_events(args.app_name, args.output, args.channel,
                               format=args.format)
        return 0

    if cmd == "import":
        commands.import_events(args.app_name, args.input, args.channel,
                               format=args.format)
        return 0

    if cmd == "run":
        target = commands.resolve_engine_factory(args.main_class)
        result = target(*args.args) if callable(target) else None
        if result is not None:
            print(result)
        return 0

    if cmd == "template":
        print("The template command is deprecated; browse the template "
              "gallery instead (reference: commands/Template.scala:38-83).")
        return 0

    if cmd == "upgrade":
        results = commands.upgrade(args.app)
        if not results:
            print("Nothing to upgrade: the configured event backend has "
                  "no store-level migration/compaction (memory backend), "
                  "or no apps exist.")
            return 0
        for r in results:
            saved = r["bytes_before"] - r["bytes_after"]
            print(f"  app {r['app']} channel {r['channel']}: "
                  f"{r['events']} live events rewritten, "
                  f"{r['bytes_before']} -> {r['bytes_after']} bytes "
                  f"({saved:+d} reclaimed)")
        print("Upgrade complete: stores rewritten in the current format.")
        return 0

    print(f"Unknown command {cmd!r}")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    # device verbs may hold the chip: SIGTERM must exit via normal
    # interpreter shutdown or the single-tenant lease wedges (see
    # utils/lease.py and the _ensure_accelerator docstring)
    install_sigterm_exit()
    # honor the user's JAX_PLATFORMS even on images whose site
    # customization pre-imports jax and pins the platform config at
    # interpreter start (env vars are read only at import time, so the
    # pin would otherwise silently override the user's choice)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", plat)
        # the config update only takes effect if no backend has
        # initialized; a site customization that already called
        # jax.devices() would still win — say so instead of silently
        # running on the wrong platform
        if _backends_initialized():
            print(
                f"warning: JAX_PLATFORMS={plat} set but JAX backends "
                "were already initialized at interpreter start; the "
                "platform pin may not take effect", file=sys.stderr)
    args = build_parser().parse_args(argv)
    # the true invocation argv, for pod relaunch (programmatic main(argv)
    # must not fall back to the host process's sys.argv — e.g. pytest's)
    args._invocation_argv = list(argv) if argv is not None else sys.argv[1:]
    # persistent XLA cache: every pio process after the first skips the
    # multi-second compile (the TPU analogue of the reference's JVM/Spark
    # startup cost per spark-submit)
    from incubator_predictionio_tpu.utils.compile_cache import enable
    enable()
    try:
        return dispatch(args)
    except CommandError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
