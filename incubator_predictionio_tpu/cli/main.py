"""``pio`` CLI entry point — subcommands land as subsystems are built.

Reference verb inventory (tools/.../console/Console.scala:153-600): version,
status, app {new,list,show,delete,data-delete,channel-new,channel-delete},
accesskey {new,list,delete}, train, eval, deploy, undeploy, eventserver,
adminserver, dashboard, export, import, build, run, template.
"""

from __future__ import annotations

import argparse
import sys

from incubator_predictionio_tpu import __version__


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio",
        description="TPU-native PredictionIO-compatible machine learning server",
    )
    parser.add_argument("--version", action="version", version=f"pio-tpu {__version__}")
    parser.add_subparsers(dest="command")
    return parser


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "command", None):
        parser.print_help()
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
