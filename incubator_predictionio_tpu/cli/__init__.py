"""The ``pio`` command line (reference: tools/.../console/Console.scala)."""
