"""incubator_predictionio_tpu — a TPU-native machine learning server.

A brand-new framework with the capabilities of Apache PredictionIO
(reference: /root/reference, bizreach/incubator-predictionio), rebuilt
idiomatically on JAX/XLA for TPU:

- ``data``     — event model, property aggregation, pluggable event/metadata/
                 model storage (reference: data/src/main/scala/.../data/).
- ``core``     — the DASE abstraction (DataSource / Preparator / Algorithm(s) /
                 Serving), engine composition, metrics and evaluation
                 (reference: core/src/main/scala/.../controller/).
- ``workflow`` — train / evaluate runners and pytree checkpointing
                 (reference: core/src/main/scala/.../workflow/).
- ``servers``  — asyncio REST event server and prediction server
                 (reference: data/.../api/EventServer.scala,
                 core/.../workflow/CreateServer.scala).
- ``parallel`` — device mesh / sharding / collective helpers (replaces Spark's
                 cluster runtime with jax.sharding over TPU ICI/DCN).
- ``ops``      — the JAX/XLA/Pallas compute kernels (ALS sweeps, top-k,
                 naive bayes statistics) that replace Spark MLlib.
- ``models``   — engine templates (recommendation, classification,
                 similarproduct, ecommerce) mirroring the reference's
                 examples/scala-parallel-* template families.
- ``speed``    — the Lambda-architecture speed leg: log-tail cursor
                 subscriber + batched device fold-in overlay serving
                 fresh users/items between retrains (no reference
                 counterpart — PredictionIO documents the architecture,
                 this implements its third leg).
- ``e2``       — standalone engine-building library (CategoricalNaiveBayes,
                 MarkovChain, BinaryVectorizer, CrossValidation) mirroring
                 the reference's e2/ module.
- ``cli``      — the ``pio`` command line (reference: tools/.../Console.scala).
"""

__version__ = "0.1.0"

BUILD_INFO = {
    "name": "incubator-predictionio-tpu",
    "version": __version__,
    "compute_backend": "jax/xla (tpu-first)",
}
