"""Capacity + regression model over the repo's bench trajectory.

The driver leaves one ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json`` per
round at the repo root; ROADMAP item 1 asks for those walls to become a
**capacity model** — rows/chip at fixed staleness — and the serving
numbers to become a sizing rule (QPS per worker), the way ALX (arxiv
2112.02194) sizes sharded-MF deployments from measured per-chip
throughput and the pjit/TPUv4 scaling work (arxiv 2204.06514) treats
continuously-measured MFU as the regression gate.

Three jobs, all offline and dependency-free:

1. **Normalize** the trajectory. Records come in three shapes (the
   driver wrapper ``{n, cmd, rc, tail, parsed}``, the flat builder
   record, the MULTICHIP ``{n_devices, rc, ok, skipped, tail}``); every
   one normalizes to a :class:`NormalizedRecord`, and a record with no
   parsed payload gets a STRUCTURED ``skipped_reason`` classified from
   its tail/rc (the BENCH_r04 "accelerator init still blocked" rc=3 and
   BENCH_r05 rc=124 driver-kill classes) — no record in the trajectory
   is ever unexplainable.
2. **Fit capacity**: rows-per-chip-per-second from the newest
   non-degraded training wall, projected to
   rows-per-chip-at-fixed-staleness (the retrain bound from
   ``PIO_SLO_STALENESS_S``), plus QPS-per-worker from the measured
   concurrent serving rate — with worker/chip projections for target
   loads.
3. **Regression verdict**: key-by-key tolerance compare of the newest
   parsed record against the pinned baseline
   (``CAPACITY_BASELINE.json`` at the repo root), skipping keys whose
   record value is null; keys are classified lower-is-better
   (walls, latencies, RMSE) vs higher-is-better (QPS, MFU, rates), and
   shape keys (nnz/rank/sweeps) must match or the compare is honestly
   ``incomparable_shape`` rather than silently green.

``scripts/capacity_report.py`` is the CLI; ``--check`` gates CI.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: the pinned regression baseline at the repo root
BASELINE_FILENAME = "CAPACITY_BASELINE.json"

#: trajectory record globs, repo-root relative
RECORD_GLOBS = ("BENCH_*.json", "MULTICHIP_*.json")

#: keys that define the measured shape — a compare across different
#: shapes is not a regression signal, it is a different experiment
SHAPE_KEYS = ("nnz", "rank", "sweeps")

#: key-direction classification for the tolerance compare. First match
#: wins; keys matching neither class are informational and skipped.
_LOWER_IS_BETTER_RE = re.compile(
    r"(_wall_s$|_s$|_ms$|rmse|^value$|_ns$|staleness)")
_HIGHER_IS_BETTER_RE = re.compile(
    r"(qps|eps$|_eps_|mfu|precision|vs_baseline|hit_rate|speedup|"
    r"flops)")


def key_direction(key: str) -> Optional[str]:
    """"lower" | "higher" | None (informational)."""
    if key in SHAPE_KEYS:
        return None
    if _HIGHER_IS_BETTER_RE.search(key):
        return "higher"
    if _LOWER_IS_BETTER_RE.search(key):
        return "lower"
    return None


# ---------------------------------------------------------------------------
# record normalization + failure classification
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NormalizedRecord:
    name: str                       # file stem, e.g. "BENCH_r04"
    kind: str                       # "bench" | "multichip"
    round: Optional[int]            # rNN from the filename when present
    rc: Optional[int]
    parsed: Optional[Dict[str, Any]]
    degraded: Optional[bool]
    bench_env: Optional[Dict[str, Any]]
    skipped_reason: Optional[Dict[str, Any]]
    ok: Optional[bool] = None       # multichip pass/fail
    path: Optional[str] = None

    def summary(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "kind": self.kind,
            "round": self.round,
            "rc": self.rc,
            "degraded": self.degraded,
            "parsed": self.parsed is not None,
            "bench_env": self.bench_env,
            "skipped_reason": self.skipped_reason,
        }


#: (regex over the tail, failure class, human detail) — first match that
#: survives the rc-priority rules below names the class
_TAIL_CLASSES: Tuple[Tuple[re.Pattern, str, str], ...] = (
    (re.compile(r"accelerator init still blocked|"
                r"accelerator unavailable|"
                r"did not claim|no accelerator claim"),
     "accelerator_unavailable",
     "the accelerator never became claimable (stale chip lease class)"),
    (re.compile(r"Traceback \(most recent call last\)"),
     "harness_exception",
     "the run died on an unhandled exception"),
)


def classify_failure(tail: str, rc: Optional[int]) -> Dict[str, Any]:
    """Structured reason for a record with no parsed payload. Never
    returns None: the whole point is that every unparsed record carries
    an explanation (the acceptance contract of this module)."""
    tail = tail or ""
    matched: List[str] = []
    cls: Optional[str] = None
    detail: Optional[str] = None
    for pattern, klass, why in _TAIL_CLASSES:
        m = pattern.search(tail)
        if m:
            matched.append(m.group(0))
            if cls is None:
                cls, detail = klass, why
    if rc == 124:
        # the driver's timeout kill pre-empts everything else: whatever
        # was going wrong, the record is null because the kill landed
        # before the emit point (the BENCH_r05 class)
        return {
            "class": "driver_deadline",
            "detail": "driver timeout (rc=124) killed the run before a "
                      "record was emitted"
                      + (f"; while: {detail}" if detail else ""),
            "rc": rc,
            "matched": matched,
        }
    if cls is not None:
        return {"class": cls, "detail": detail, "rc": rc,
                "matched": matched}
    if rc not in (0, None):
        last = next((ln for ln in reversed(tail.splitlines())
                     if ln.strip()), "")
        return {"class": "error_exit",
                "detail": f"nonzero exit ({rc}); last line: {last[-200:]}",
                "rc": rc, "matched": matched}
    return {"class": "no_record",
            "detail": "exited clean but emitted no parsed record",
            "rc": rc, "matched": matched}


_ROUND_RE = re.compile(r"_r(\d+)")


def normalize_record(path: str) -> NormalizedRecord:
    """One trajectory file → :class:`NormalizedRecord`, whatever its
    era's shape. Unreadable/unparseable files normalize to a
    ``skipped_reason`` of class ``unreadable`` — the trajectory walker
    must never die on one bad file."""
    name = os.path.splitext(os.path.basename(path))[0]
    kind = "multichip" if name.upper().startswith("MULTICHIP") else "bench"
    m = _ROUND_RE.search(name)
    rnd = int(m.group(1)) if m else None
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return NormalizedRecord(
            name=name, kind=kind, round=rnd, rc=None, parsed=None,
            degraded=None, bench_env=None,
            skipped_reason={"class": "unreadable", "detail": str(e),
                            "rc": None, "matched": []},
            path=path)
    if not isinstance(raw, dict):
        return NormalizedRecord(
            name=name, kind=kind, round=rnd, rc=None, parsed=None,
            degraded=None, bench_env=None,
            skipped_reason={"class": "unreadable",
                            "detail": "not a JSON object", "rc": None,
                            "matched": []},
            path=path)

    if kind == "multichip":
        ok = raw.get("ok")
        rc = raw.get("rc")
        reason = None
        if not ok:
            reason = classify_failure(raw.get("tail", ""), rc)
        return NormalizedRecord(
            name=name, kind=kind, round=rnd, rc=rc, parsed=None,
            degraded=None, bench_env=raw.get("bench_env"),
            skipped_reason=reason, ok=bool(ok), path=path)

    if "parsed" in raw or "tail" in raw or "cmd" in raw:
        # driver wrapper shape
        parsed = raw.get("parsed")
        rc = raw.get("rc")
        reason = None
        if parsed is None:
            reason = classify_failure(raw.get("tail", ""), rc)
        elif isinstance(parsed, dict) and parsed.get("skipped_reason"):
            # the bench itself emitted a structured reason (post-PR-9
            # degraded rounds)
            reason = parsed["skipped_reason"]
        return NormalizedRecord(
            name=name, kind=kind, round=rnd, rc=rc,
            parsed=parsed if isinstance(parsed, dict) else None,
            degraded=(parsed or {}).get("degraded")
            if isinstance(parsed, dict) else None,
            bench_env=(parsed or {}).get("bench_env")
            if isinstance(parsed, dict) else None,
            skipped_reason=reason, path=path)

    # flat builder-style record: the parsed payload IS the file
    return NormalizedRecord(
        name=name, kind=kind, round=rnd, rc=raw.get("rc"),
        parsed=raw, degraded=raw.get("degraded"),
        bench_env=raw.get("bench_env"),
        skipped_reason=raw.get("skipped_reason"), path=path)


def load_trajectory(repo_dir: str) -> List[NormalizedRecord]:
    """Every trajectory record under ``repo_dir``, normalized, in
    (kind, round, name) order — benches first, oldest first."""
    paths: List[str] = []
    for pat in RECORD_GLOBS:
        paths.extend(glob.glob(os.path.join(repo_dir, pat)))
    records = [normalize_record(p) for p in sorted(set(paths))]
    records.sort(key=lambda r: (r.kind, r.round if r.round is not None
                                else -1, r.name))
    return records


# ---------------------------------------------------------------------------
# capacity fit
# ---------------------------------------------------------------------------

def staleness_bound_s() -> float:
    try:
        return float(os.environ.get("PIO_SLO_STALENESS_S", "") or 3600.0)
    except ValueError:
        return 3600.0


def _num(parsed: Optional[Dict], key: str) -> Optional[float]:
    v = (parsed or {}).get(key)
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def parse_tenant_demands(spec: Optional[str] = None) -> Dict[str, float]:
    """``PIO_TENANT_DEMAND_QPS`` grammar: ``tenant=qps;tenant=qps``.
    Malformed entries are dropped, not fatal — a sizing report must
    never die on a typo'd env var."""
    raw = (spec if spec is not None
           else os.environ.get("PIO_TENANT_DEMAND_QPS", ""))
    demands: Dict[str, float] = {}
    for part in raw.split(";"):
        name, sep, val = part.strip().partition("=")
        if not sep:
            continue
        try:
            q = float(val)
        except ValueError:
            continue
        if name.strip() and q > 0:
            demands[name.strip()] = q
    return demands


def bin_pack_tenants(demands: Dict[str, float],
                     qps_per_worker: float) -> Dict[str, Any]:
    """First-fit pack of tenant QPS demands onto workers of capacity
    ``qps_per_worker``. A tenant bigger than one worker is split into
    worker-sized chunks; each chunk lands in the first worker with
    room (insertion order — deterministic). Returns the per-tenant
    worker assignment and the packed fleet size, which is ≥ the naive
    ``ceil(sum/qps)`` because co-residency never splits a chunk."""
    cap = float(qps_per_worker)
    if cap <= 0:
        return {"workers": 0, "assignment": {}}
    free: List[float] = []          # remaining capacity per worker
    assignment: Dict[str, List[int]] = {}
    for tenant, demand in demands.items():
        placed: List[int] = []
        remaining = float(demand)
        while remaining > 1e-9:
            chunk = min(remaining, cap)
            for i, room in enumerate(free):
                if room >= chunk - 1e-9:
                    free[i] = room - chunk
                    placed.append(i)
                    break
            else:
                free.append(cap - chunk)
                placed.append(len(free) - 1)
            remaining -= chunk
        assignment[tenant] = sorted(set(placed))
    return {"workers": len(free), "assignment": assignment}


def fit_capacity(records: Sequence[NormalizedRecord],
                 staleness_s: Optional[float] = None) -> Dict[str, Any]:
    """The rows/chip + QPS/worker model, fitted from the newest records
    that actually measured each quantity (degraded rounds measured a
    CPU fallback, not a chip — they never feed the chip-rate fit).
    Every estimate names its source record; absent inputs yield null
    estimates, never fabricated ones."""
    S = staleness_s if staleness_s is not None else staleness_bound_s()
    out: Dict[str, Any] = {
        "staleness_bound_s": S,
        "rows_per_chip_per_s": None,
        "rows_per_chip_at_staleness": None,
        "train_source_record": None,
        "qps_per_worker": None,
        "qps_source_record": None,
        "qps_source_key": None,
        "serve_p99_ms": None,
        "mfu": None,
        "shard": None,
        "fleet": None,
        "mips": None,
        "mips_big": None,
        "tenants": None,
        "projections": {},
    }
    benches = [r for r in records if r.kind == "bench"
               and r.parsed is not None]
    # newest-first for "the current capability"
    for rec in reversed(benches):
        if out["train_source_record"] is None and not rec.degraded:
            nnz = _num(rec.parsed, "nnz")
            wall = _num(rec.parsed, "value")
            if nnz and wall and wall > 0:
                rate = nnz / wall  # single-chip training leg
                out["rows_per_chip_per_s"] = round(rate, 1)
                out["rows_per_chip_at_staleness"] = round(rate * S)
                out["train_source_record"] = rec.name
                out["mfu"] = _num(rec.parsed, "mfu")
        # degraded rounds serve a reduced-nnz CPU fallback — their QPS
        # would size the fleet from a measurement no production worker
        # resembles (same guard as the train-rate fit above)
        if out["qps_source_record"] is None and not rec.degraded:
            # prefer the fleet leg's per-worker goodput (bench_fleet's
            # no-floor burst: real kernels through the scheduler across
            # N worker processes — the figure a fleet is actually sized
            # from); the single-process serve_qps_concurrent remains
            # the fallback for records predating the leg
            # measured goodput first (fleet leg, then the single-
            # process concurrent rate); the two-stage MIPS device
            # bound (1000 / per-query wall at the 256k planted
            # catalogue) is the projection of last resort — a device
            # ceiling, not a measured worker rate, and qps_source_key
            # says so
            fleet_qps = _num(rec.parsed, "fleet_qps_per_worker")
            qps = (fleet_qps
                   or _num(rec.parsed, "serve_qps_concurrent")
                   or _num(rec.parsed, "mips_serve_qps"))
            if qps and qps > 0:
                out["qps_per_worker"] = round(qps, 1)
                out["qps_source_record"] = rec.name
                out["qps_source_key"] = (
                    "fleet_qps_per_worker" if fleet_qps
                    else ("serve_qps_concurrent"
                          if _num(rec.parsed, "serve_qps_concurrent")
                          else "mips_serve_qps"))
                out["serve_p99_ms"] = _num(rec.parsed, "serve_p99_ms")
        if out.get("mips") is None and not rec.degraded:
            mq = _num(rec.parsed, "mips_two_stage_per_query_ms")
            if mq:
                out["mips"] = {
                    "source_record": rec.name,
                    "items": _num(rec.parsed, "mips_items"),
                    "two_stage_per_query_ms": mq,
                    "exhaustive_per_query_ms": _num(
                        rec.parsed, "mips_exhaustive_per_query_ms"),
                    "speedup": _num(rec.parsed, "mips_speedup"),
                    "candidates_frac": _num(
                        rec.parsed, "mips_candidates_frac"),
                    "recall_at_20": _num(
                        rec.parsed, "mips_recall_at_20"),
                    "serve_qps_bound": _num(
                        rec.parsed, "mips_serve_qps"),
                }
        # catalogue-at-scale leg (≥10M items under PQ + background
        # rebuild): the sizing figures a tens-of-millions catalogue is
        # planned from — device bytes/item and the flat-p99-through-
        # rebuild ratio (docs/performance.md "Catalogue at tens of
        # millions")
        if out.get("mips_big") is None and not rec.degraded:
            bi = _num(rec.parsed, "mips_big_items")
            if bi:
                out["mips_big"] = {
                    "source_record": rec.name,
                    "items": int(bi),
                    "recall_at_20": _num(
                        rec.parsed, "mips_big_recall_at_20"),
                    "two_stage_per_query_ms": _num(
                        rec.parsed, "mips_big_two_stage_p50_ms"),
                    "rebuild_p99_flat_x": _num(
                        rec.parsed, "mips_rebuild_p99_flat_x"),
                    "index_age_max_s": _num(
                        rec.parsed, "mips_index_age_max_s"),
                    "device_bytes_per_item": _num(
                        rec.parsed, "mips_device_bytes_per_item"),
                }
        # same degraded-round guard as the qps fit above: a degraded
        # round's fleet leg ran on a box no production worker resembles
        if out.get("fleet") is None and not rec.degraded:
            fw = _num(rec.parsed, "fleet_workers")
            if fw:
                out["fleet"] = {
                    "source_record": rec.name,
                    "workers": int(fw),
                    "qps": _num(rec.parsed, "fleet_qps"),
                    "p99_s": _num(rec.parsed, "fleet_p99_s"),
                    "batch_p50": _num(rec.parsed, "fleet_batch_p50"),
                    "shed_rate": _num(rec.parsed, "fleet_shed_rate"),
                    "p99_flat_x": _num(rec.parsed, "fleet_p99_flat_x"),
                    "dispatch_floor_ms": _num(
                        rec.parsed, "fleet_dispatch_floor_ms"),
                }
        if out["shard"] is None:
            devs = _num(rec.parsed, "shard_devices")
            if devs:
                out["shard"] = {
                    "source_record": rec.name,
                    "devices": int(devs),
                    "mesh_shape": rec.parsed.get("shard_mesh_shape"),
                    "train_wall_s": _num(rec.parsed,
                                         "shard_train_wall_s"),
                    "nnz": _num(rec.parsed, "shard_nnz"),
                    "mfu": _num(rec.parsed, "shard_mfu_train"),
                    "gather_modes": rec.parsed.get("shard_gather_modes"),
                }
    # measured ceilings for the self-tuning serving knobs
    # (obs/knobs.py capacity_caps_fn): how far the effort knobs may
    # climb before capacity — not tuning — becomes binding. Derived
    # only from real measurements, with the same honesty rule as every
    # other estimate: no usable input, no ceiling (an absent knob is
    # simply unguarded, never guarded by a fabricated number).
    knobs: Dict[str, int] = {}
    mips = out.get("mips")
    if mips and mips.get("items") and mips.get("candidates_frac") \
            and mips.get("two_stage_per_query_ms"):
        items = float(mips["items"])
        measured_cand = items * float(mips["candidates_frac"])
        per_ms = float(mips["two_stage_per_query_ms"])
        if measured_cand > 0 and per_ms > 0:
            # stage-2 wall scales ~linearly with the candidate count;
            # the ceiling is the count at which the measured per-query
            # wall would eat the whole serving objective, clamped to
            # the catalogue itself
            slo_ms = 1000.0 * float(
                os.environ.get("PIO_SLO_SERVE_P99_S", "") or 0.25)
            cap = measured_cand * (slo_ms / per_ms)
            knobs["mips_candidates"] = int(min(cap, items))
    big = out.get("mips_big")
    if big and big.get("items") and big.get("two_stage_per_query_ms"):
        # PQ exact-rerank width ceiling: the big leg measures the
        # per-query wall at the DEFAULT PQ width (2048), and the
        # stage-2 wall scales ~linearly with it — same model as
        # mips_candidates, but measured at catalogue scale under PQ
        per_ms = float(big["two_stage_per_query_ms"])
        if per_ms > 0:
            slo_ms = 1000.0 * float(
                os.environ.get("PIO_SLO_SERVE_P99_S", "") or 0.25)
            cap = 2048.0 * (slo_ms / per_ms)
            knobs["mips_pq_candidates"] = int(
                min(cap, float(big["items"])))
    fleet = out.get("fleet")
    if fleet and fleet.get("qps") and fleet.get("workers"):
        # Little's law: a batch larger than one worker's arrivals per
        # objective window can never fill before its deadline
        slo_s = float(os.environ.get("PIO_SLO_SERVE_P99_S", "") or 0.25)
        per_worker = float(fleet["qps"]) / max(int(fleet["workers"]), 1)
        cap = per_worker * slo_s
        if cap >= 1:
            knobs["max_batch"] = int(cap)
    out["knobs"] = knobs or None

    rate = out["rows_per_chip_per_s"]
    qps = out["qps_per_worker"]
    projections: Dict[str, Any] = {}
    if rate:
        projections["chips_for_rows_at_staleness"] = {
            str(rows): math.ceil(rows / (rate * S))
            for rows in (100_000_000, 1_000_000_000, 10_000_000_000)
        }
    if qps:
        projections["workers_for_qps"] = {
            str(q): math.ceil(q / qps)
            for q in (10_000, 100_000, 1_000_000)
        }
    out["projections"] = projections
    # multi-tenant sizing: per-tenant worker counts plus a first-fit
    # bin-pack of the declared tenant demands onto the fleet. Demands
    # come from PIO_TENANT_DEMAND_QPS ("tenant=qps;..."); no declared
    # demand or no measured per-worker rate → null block, same honesty
    # rule as every other estimate.
    demands = parse_tenant_demands()
    if demands and qps:
        out["tenants"] = {
            "source_record": out["qps_source_record"],
            "qps_per_worker": qps,
            "demand_qps": demands,
            "workers_for_qps": {
                t: math.ceil(d / qps) for t, d in demands.items()
            },
            "binpack": bin_pack_tenants(demands, qps),
        }
    return out


# ---------------------------------------------------------------------------
# regression verdicts
# ---------------------------------------------------------------------------

#: default relative tolerance for the key-by-key compare; walls on
#: shared CI boxes are noisy, so the gate is a tripwire for real
#: regressions (2x walls, halved QPS), not a 5% perf police
DEFAULT_TOLERANCE = 0.25


def load_baseline(repo_dir: str,
                  path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The pinned baseline: ``{"record": name, "tolerance": float,
    "keys": {key: value}}``. None when the file is absent (the compare
    then pins against the OLDEST fully-parsed bench record, honestly
    labeled)."""
    p = path or os.path.join(repo_dir, BASELINE_FILENAME)
    try:
        with open(p, encoding="utf-8") as f:
            base = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return base if isinstance(base, dict) and "keys" in base else None


def compare_record(parsed: Dict[str, Any],
                   baseline_keys: Dict[str, Any],
                   tolerance: float = DEFAULT_TOLERANCE
                   ) -> Dict[str, Any]:
    """Key-by-key tolerance compare. Keys whose record value is null
    (or missing, or non-numeric) are SKIPPED — a degraded round's
    honest nulls are not regressions. Shape keys must agree or the
    whole compare is ``incomparable_shape``."""
    for k in SHAPE_KEYS:
        b, v = baseline_keys.get(k), parsed.get(k)
        if b is not None and v is not None and b != v:
            return {"status": "incomparable_shape",
                    "detail": f"{k}: baseline {b} vs record {v}",
                    "compared": 0, "skipped": [], "regressed": [],
                    "improved": []}
    regressed: List[Dict[str, Any]] = []
    improved: List[str] = []
    skipped: List[str] = []
    compared = 0
    for key, base_v in baseline_keys.items():
        direction = key_direction(key)
        if direction is None or not isinstance(
                base_v, (int, float)) or isinstance(base_v, bool):
            continue
        v = parsed.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            skipped.append(key)
            continue
        compared += 1
        if base_v == 0:
            continue  # a zero baseline has no relative band
        ratio = v / base_v
        if direction == "lower":
            if ratio > 1.0 + tolerance:
                regressed.append({"key": key, "baseline": base_v,
                                  "value": v,
                                  "ratio": round(ratio, 3)})
            elif ratio < 1.0 - tolerance:
                improved.append(key)
        else:
            if ratio < 1.0 - tolerance:
                regressed.append({"key": key, "baseline": base_v,
                                  "value": v,
                                  "ratio": round(ratio, 3)})
            elif ratio > 1.0 + tolerance:
                improved.append(key)
    return {
        "status": "regressed" if regressed else "ok",
        "compared": compared,
        "skipped": skipped,
        "regressed": regressed,
        "improved": improved,
    }


def record_verdicts(records: Sequence[NormalizedRecord],
                    baseline: Optional[Dict[str, Any]],
                    tolerance: float = DEFAULT_TOLERANCE
                    ) -> List[Dict[str, Any]]:
    """One NON-NULL verdict per record: parsed bench records compare
    against the baseline, unparsed ones carry their structured
    ``skipped_reason``, MULTICHIP records report pass/fail."""
    base_keys = (baseline or {}).get("keys") or {}
    base_name = (baseline or {}).get("record")
    tol = float((baseline or {}).get("tolerance", tolerance))
    out: List[Dict[str, Any]] = []
    for rec in records:
        entry = rec.summary()
        if rec.kind == "multichip":
            if rec.ok:
                entry["verdict"] = {"status": "ok"}
            else:
                entry["verdict"] = {"status": "skipped",
                                    "reason": rec.skipped_reason}
        elif rec.parsed is None:
            entry["verdict"] = {"status": "skipped",
                                "reason": rec.skipped_reason}
        elif rec.name == base_name:
            entry["verdict"] = {"status": "baseline"}
        elif not base_keys:
            entry["verdict"] = {"status": "no_baseline"}
        else:
            v = compare_record(rec.parsed, base_keys, tol)
            v["baseline"] = base_name
            entry["verdict"] = v
        out.append(entry)
    return out


def capacity_report(repo_dir: str,
                    baseline_path: Optional[str] = None,
                    staleness_s: Optional[float] = None
                    ) -> Dict[str, Any]:
    """The whole ``capacity.json`` payload: normalized trajectory with
    per-record verdicts, the fitted capacity model, and the newest
    record's regression compare."""
    records = load_trajectory(repo_dir)
    baseline = load_baseline(repo_dir, baseline_path)
    if baseline is None:
        # honest fallback: pin against the oldest fully-parsed bench
        oldest = next((r for r in records
                       if r.kind == "bench" and r.parsed is not None),
                      None)
        if oldest is not None:
            baseline = {"record": oldest.name,
                        "tolerance": DEFAULT_TOLERANCE,
                        "keys": oldest.parsed,
                        "provenance": "fallback:oldest_parsed"}
    verdicts = record_verdicts(records, baseline)
    newest = next((r for r in reversed(records)
                   if r.kind == "bench" and r.parsed is not None), None)
    regression: Dict[str, Any] = {
        "baseline": (baseline or {}).get("record"),
        "baseline_provenance": (baseline or {}).get(
            "provenance", "pinned"),
        "newest": newest.name if newest else None,
        "status": "no_data",
    }
    if newest is not None and baseline is not None:
        cmp = compare_record(
            newest.parsed, baseline.get("keys") or {},
            float(baseline.get("tolerance", DEFAULT_TOLERANCE)))
        regression.update(cmp)
        if newest.name == baseline.get("record"):
            regression["status"] = "baseline"
    return {
        "staleness_bound_s": (staleness_s if staleness_s is not None
                              else staleness_bound_s()),
        "records": verdicts,
        "capacity": fit_capacity(records, staleness_s),
        "regression": regression,
    }


__all__ = [
    "BASELINE_FILENAME", "DEFAULT_TOLERANCE", "NormalizedRecord",
    "RECORD_GLOBS", "capacity_report", "classify_failure",
    "compare_record", "fit_capacity", "key_direction", "load_baseline",
    "load_trajectory", "normalize_record", "record_verdicts",
    "staleness_bound_s", "parse_tenant_demands", "bin_pack_tenants",
]
