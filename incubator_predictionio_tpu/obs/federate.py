"""Metrics federation — one scrape for the whole serving fleet.

A multi-worker deployment (N prediction servers behind a balancer, a
storage box, an event server) has N registries and therefore N truths:
no single ``/metrics`` answer carries the fleet p99 or the summed queue
depth the load-shedder (ROADMAP-2) and the freshness controller
(ROADMAP-3) need. This module gives the admin server that one answer:

- ``PIO_FLEET_TARGETS`` names the worker ``/metrics`` endpoints
  (comma-separated ``host:port``, full URLs, or ``name=host:port`` to
  pick the instance label);
- :func:`federate` scrapes them all, parses each exposition with the
  promoted grammar parser (obs/expofmt.py) and merges the families
  under an added ``instance`` label;
- ``GET /federate`` on the admin server re-exposes the merged families
  as one exposition — ``pio_query_latency_seconds`` fleet p99 is then
  one bucket-sum away for any consumer, and this module's
  :class:`FederatedMetric` does that math directly for in-process
  consumers;
- :class:`FleetRegistry` is a Registry-shaped view over a (re-scraped,
  age-bounded) snapshot, so the SLO burn-rate engine evaluates its
  objectives over the FLEET exactly as it does over one process
  (``GET /slo?fleet=1``).

Instance label semantics: the value is the configured target (or its
``name=`` alias) — a BOUNDED, operator-declared set, one per worker.
Scrape failures never fail the federation: a down worker is reported as
``pio_federate_up{instance}`` 0 and its series are simply absent, which
is itself the signal.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from incubator_predictionio_tpu.obs import expofmt
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace

logger = logging.getLogger(__name__)

#: the label federation adds to every merged series
INSTANCE_LABEL = "instance"

#: families the federator itself synthesizes about the scrape
_UP_NAME = "pio_federate_up"
_SCRAPE_SECONDS_NAME = "pio_federate_scrape_seconds"

#: the admin process's OWN record of scrape health over time (the
#: /federate output only shows the LAST pass; this counter accumulates,
#: so a flapping worker is visible from the admin's /metrics). The
#: instance label value comes from the operator's PIO_FLEET_TARGETS —
#: bounded by fleet size, not wire data (metric-label-cardinality
#: baseline entry records the justification).
_SCRAPES_TOTAL = obs_metrics.REGISTRY.counter(
    "pio_federate_scrapes_total",
    "federation scrapes by instance and outcome",
    labels=("instance", "outcome"))


@dataclasses.dataclass(frozen=True)
class Target:
    """One worker endpoint: the instance label value + scrape URL."""

    instance: str
    url: str


def parse_targets(spec: str) -> List[Target]:
    """``PIO_FLEET_TARGETS`` grammar: comma-separated entries, each a
    ``host:port``, a full ``http://...`` URL (path defaults to
    ``/metrics``), optionally prefixed ``name=`` to choose the instance
    label. Whitespace around entries is ignored."""
    out: List[Target] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name: Optional[str] = None
        # a "name=" prefix can only sit BEFORE any scheme or authority
        # (URLs never carry "=" before "://")
        eq = entry.find("=")
        scheme = entry.find("://")
        if eq != -1 and (scheme == -1 or eq < scheme):
            name, entry = entry.split("=", 1)
            name = name.strip()
            entry = entry.strip()
        if "://" not in entry:
            url = f"http://{entry}"
        else:
            url = entry
        # default path: the shared /metrics route
        scheme_rest = url.split("://", 1)
        if "/" not in scheme_rest[1]:
            url = url + "/metrics"
        out.append(Target(instance=name or scheme_rest[1].split("/")[0],
                          url=url))
    return out


def fleet_targets() -> List[Target]:
    """The configured fleet, re-read per call so a live admin can be
    retargeted without a restart."""
    return parse_targets(os.environ.get("PIO_FLEET_TARGETS", ""))


def scrape_timeout_s() -> float:
    try:
        return float(os.environ.get("PIO_FLEET_SCRAPE_TIMEOUT_S", "") or 5.0)
    except ValueError:
        return 5.0


@dataclasses.dataclass
class ScrapeResult:
    target: Target
    ok: bool
    wall_s: float
    families: Dict[str, expofmt.Family]
    error: Optional[str] = None


def scrape_target(target: Target,
                  timeout: Optional[float] = None) -> ScrapeResult:
    """One worker scrape → parsed families. Never raises: a down or
    malformed worker comes back ``ok=False`` with the error string (the
    federation must degrade per-instance, not per-fleet). The request
    forwards the ambient trace headers, so an operator's traced
    ``GET /federate`` shows up in every worker's span log as a child
    hop (admin → workers)."""
    t0 = time.perf_counter()
    try:
        req = urllib.request.Request(
            target.url, headers=dict(obs_trace.client_headers()))
        with urllib.request.urlopen(
                req, timeout=timeout if timeout is not None
                else scrape_timeout_s()) as resp:
            text = resp.read().decode("utf-8")
        families = expofmt.parse_families(text)
        _SCRAPES_TOTAL.labels(instance=target.instance,
                              outcome="ok").inc()
        return ScrapeResult(target=target, ok=True,
                            wall_s=time.perf_counter() - t0,
                            families=families)
    except Exception as e:  # noqa: BLE001 — per-instance degradation
        logger.warning("federate scrape of %s (%s) failed: %s",
                       target.instance, target.url, e)
        _SCRAPES_TOTAL.labels(instance=target.instance,
                              outcome="error").inc()
        return ScrapeResult(target=target, ok=False,
                            wall_s=time.perf_counter() - t0,
                            families={}, error=str(e))


class FederatedMetric:
    """One metric family merged across instances — Registry-metric-
    shaped (``kind``/``total``/``max_value``/``has_samples``/
    ``cumulative_below``/``quantile``), so the SLO engine and the
    dashboard helpers evaluate fleet state through the same protocol
    they use on the process registry."""

    def __init__(self, name: str, kind: str, help: str = "") -> None:
        self.name = name
        self.kind = kind
        self.help = help
        #: (instance, labelset) → value, counter/gauge families
        self.values: Dict[Tuple[str, expofmt.LabelSet], float] = {}
        #: (instance, labelset) → HistogramChild
        self.histograms: Dict[Tuple[str, expofmt.LabelSet],
                              expofmt.HistogramChild] = {}
        #: raw exemplar annotations on counter/gauge samples, carried
        #: for re-exposition (histogram exemplars ride their
        #: HistogramChild). Pass-through: never interpreted here.
        self.value_exemplars: Dict[Tuple[str, expofmt.LabelSet],
                                   str] = {}

    # -- merge ------------------------------------------------------------
    def absorb(self, instance: str, fam: expofmt.Family) -> None:
        for labels, v in fam.values.items():
            self.values[(instance, labels)] = v
        for labels, child in fam.histograms.items():
            self.histograms[(instance, labels)] = child
        for labels, raw in fam.exemplars.items():
            self.value_exemplars[(instance, labels)] = raw

    def exemplar_trace_ids(self) -> List[Tuple[str, float, str]]:
        """``(instance, le, trace_id)`` across every instance's
        histogram children — an exemplar-free old worker simply
        contributes nothing (clean degradation, pinned in tests)."""
        out: List[Tuple[str, float, str]] = []
        for (inst, _labels), child in sorted(self.histograms.items()):
            for le, tid in child.exemplar_trace_ids():
                out.append((inst, le, tid))
        return out

    # -- counter/gauge math ------------------------------------------------
    def total(self) -> float:
        """Sum over every instance and labelset (the fleet-summed
        reading: total queue depth, total requests)."""
        if self.kind == "histogram":
            raise ValueError("total() is for counter/gauge")
        return sum(self.values.values())

    def max_value(self) -> float:
        """Max over instances/labelsets — the worst-of reading gauge
        SLOs need (fleet staleness = the stalest worker, not the sum)."""
        if self.kind == "histogram":
            raise ValueError("max_value() is for counter/gauge")
        return max(self.values.values()) if self.values else 0.0

    def has_samples(self) -> bool:
        """Exposition shows no touched/untouched bit, so any exposed
        child counts as a sample — a worker that registered a gauge
        without writing it reads 0.0 here. Document, don't guess."""
        return bool(self.values)

    # -- histogram math ----------------------------------------------------
    def _merged_buckets(
            self, labels: Optional[Dict[str, str]] = None
    ) -> Tuple[List[Tuple[float, float]], float, float]:
        """(ascending per-bucket [(le, count)], overflow, total) summed
        over every instance/child — the fleet histogram. ``labels``
        restricts the merge to children carrying every given label
        pair (the per-tenant SLO slice; a pre-tenancy worker's
        unlabeled children simply don't match)."""
        want = frozenset((k, str(v)) for k, v in labels.items()) \
            if labels else None
        by_le: Dict[float, float] = {}
        overflow = 0.0
        total = 0.0
        for (_inst, ls), child in self.histograms.items():
            if want is not None and not want <= ls:
                continue
            for le, c in child.per_bucket():
                by_le[le] = by_le.get(le, 0.0) + c
            overflow += child.overflow()
            total += child.count
        return sorted(by_le.items()), overflow, total

    def cumulative_below(
            self, bound: float,
            labels: Optional[Dict[str, str]] = None) -> Tuple[int, int]:
        """(observations ≤ the largest bucket bound ≤ ``bound``, total)
        over the merged fleet buckets — same round-DOWN contract as
        ``obs.metrics._Metric.cumulative_below`` (never overstate the
        good count). ``labels`` slices to matching children."""
        if self.kind != "histogram":
            raise ValueError("cumulative_below() is for histograms")
        buckets, _overflow, total = self._merged_buckets(labels)
        below = 0.0
        for le, c in buckets:
            if le <= bound:
                below += c
        return int(below), int(total)

    def quantile(self, q: float) -> Optional[float]:
        """Fleet quantile from the merged buckets (linear interpolation
        within a bucket, Prometheus ``histogram_quantile`` style; the
        overflow clamps to the last finite bound). None when empty."""
        if self.kind != "histogram":
            raise ValueError("quantile() is for histograms")
        buckets, _overflow, total = self._merged_buckets()
        if total <= 0:
            return None
        rank = q * total
        cum = 0.0
        prev_le = 0.0
        for le, c in buckets:
            if c > 0 and cum + c >= rank:
                return prev_le + (le - prev_le) * max(
                    rank - cum, 0.0) / c
            cum += c
            prev_le = le
        return buckets[-1][0] if buckets else None

    # dashboard parity: the process registry's cross-child quantile
    quantile_over_children = quantile

    @property
    def count(self) -> float:
        if self.kind != "histogram":
            raise ValueError("count is for histograms")
        return sum(c.count for c in self.histograms.values())

    @property
    def sum(self) -> float:
        if self.kind != "histogram":
            raise ValueError("sum is for histograms")
        return sum(c.sum for c in self.histograms.values())


class FederatedSnapshot:
    """One federation pass: per-instance scrape outcomes + the merged
    metric families. ``get(name)`` is Registry-shaped."""

    def __init__(self, results: Sequence[ScrapeResult]) -> None:
        self.results = list(results)
        self.taken_at = time.monotonic()
        self._metrics: Dict[str, FederatedMetric] = {}
        for res in self.results:
            for name, fam in res.families.items():
                merged = self._metrics.get(name)
                if merged is None:
                    merged = FederatedMetric(name, fam.kind, fam.help)
                    self._metrics[name] = merged
                elif merged.kind != fam.kind:
                    # two workers disagree on a family's kind: merging
                    # would produce a lying series — keep the first
                    # kind, drop the dissenter's children, say so
                    logger.warning(
                        "federate: %s is %s on %s but %s elsewhere; "
                        "dropping the mismatched instance's series",
                        name, fam.kind, res.target.instance, merged.kind)
                    continue
                merged.absorb(res.target.instance, fam)

    def get(self, name: str) -> Optional[FederatedMetric]:
        return self._metrics.get(name)

    def up_instances(self) -> List[str]:
        return [r.target.instance for r in self.results if r.ok]

    # -- re-exposition -----------------------------------------------------
    def expose(self) -> str:
        """The fleet as ONE exposition: every merged series re-emitted
        with the ``instance`` label prepended, plus the federation's
        own ``pio_federate_up{instance}`` / scrape-wall series. The
        output round-trips through the same grammar parser that read
        the inputs (pinned in tests/test_federation.py)."""
        esc = obs_metrics._escape_label
        out: List[str] = []

        def label_str(instance: str, labels: expofmt.LabelSet,
                      extra: str = "") -> str:
            parts = [f'{INSTANCE_LABEL}="{esc(instance)}"']
            parts.extend(f'{k}="{esc(v)}"'
                         for k, v in sorted(labels))
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}"

        out.append(f"# HELP {_UP_NAME} 1 when the instance's /metrics "
                   "scrape succeeded, else 0")
        out.append(f"# TYPE {_UP_NAME} gauge")
        for res in self.results:
            out.append(
                f"{_UP_NAME}{label_str(res.target.instance, frozenset())} "
                f"{1 if res.ok else 0}")
        out.append(f"# HELP {_SCRAPE_SECONDS_NAME} wall of the "
                   "instance's /metrics scrape")
        out.append(f"# TYPE {_SCRAPE_SECONDS_NAME} gauge")
        for res in self.results:
            out.append(
                f"{_SCRAPE_SECONDS_NAME}"
                f"{label_str(res.target.instance, frozenset())} "
                f"{obs_metrics._fmt(round(res.wall_s, 6))}")

        for name in sorted(self._metrics):
            m = self._metrics[name]
            out.append(f"# HELP {name} "
                       f"{obs_metrics._escape_help(m.help)}")
            out.append(f"# TYPE {name} {m.kind}")
            if m.kind in ("counter", "gauge"):
                for (inst, labels), v in sorted(m.values.items()):
                    line = (f"{name}{label_str(inst, labels)} "
                            f"{obs_metrics._fmt(v)}")
                    raw_ex = m.value_exemplars.get((inst, labels))
                    if raw_ex is not None:
                        line += " " + raw_ex
                    out.append(line)
            else:
                for (inst, labels), child in sorted(m.histograms.items()):
                    for le, cum in child.buckets:
                        if le == float("inf"):
                            continue
                        le_s = 'le="' + obs_metrics._fmt(le) + '"'
                        line = (
                            f"{name}_bucket"
                            f"{label_str(inst, labels, le_s)} "
                            f"{obs_metrics._fmt(cum)}")
                        # exemplars ride federation VERBATIM — the raw
                        # annotation string, understood or not, so a
                        # fleet /federate scrape still names the
                        # breaching workers' trace IDs byte-stable
                        raw_ex = child.exemplars.get(le)
                        if raw_ex is not None:
                            line += " " + raw_ex
                        out.append(line)
                    inf_s = 'le="+Inf"'
                    line = (
                        f"{name}_bucket"
                        f"{label_str(inst, labels, inf_s)} "
                        f"{obs_metrics._fmt(child.count)}")
                    raw_ex = child.exemplars.get(float("inf"))
                    if raw_ex is not None:
                        line += " " + raw_ex
                    out.append(line)
                    out.append(f"{name}_sum{label_str(inst, labels)} "
                               f"{obs_metrics._fmt(child.sum)}")
                    out.append(f"{name}_count{label_str(inst, labels)} "
                               f"{obs_metrics._fmt(child.count)}")
        return "\n".join(out) + "\n"


def federate(targets: Optional[Sequence[Target]] = None,
             timeout: Optional[float] = None) -> FederatedSnapshot:
    """Scrape every target (sequentially — fleets this serves are tens
    of workers, and the admin's /federate handler already runs on the
    executor) and merge. Raises ValueError when no targets are
    configured: an empty federation is a misconfiguration, not a
    healthy empty fleet."""
    targets = list(targets if targets is not None else fleet_targets())
    if not targets:
        raise ValueError(
            "no federation targets: set PIO_FLEET_TARGETS "
            "(comma-separated host:port of worker /metrics endpoints)")
    return FederatedSnapshot(
        [scrape_target(t, timeout=timeout) for t in targets])


class FleetRegistry:
    """Registry-shaped view over an age-bounded federated snapshot.

    ``get(name)`` re-scrapes the fleet when the cached snapshot is
    older than ``max_age_s`` — the SLO engine's per-tick ``get`` calls
    then cost one fleet scrape per evaluation burst, not one per
    objective."""

    def __init__(self, targets_fn: Callable[[], Sequence[Target]]
                 = fleet_targets,
                 max_age_s: float = 5.0,
                 timeout: Optional[float] = None) -> None:
        self._targets_fn = targets_fn
        self.max_age_s = max_age_s
        self._timeout = timeout
        self._snapshot: Optional[FederatedSnapshot] = None

    def refresh(self, force: bool = False) -> FederatedSnapshot:
        snap = self._snapshot
        if (force or snap is None
                or time.monotonic() - snap.taken_at > self.max_age_s):
            snap = federate(self._targets_fn(), timeout=self._timeout)
            self._snapshot = snap
        return snap

    def get(self, name: str) -> Optional[FederatedMetric]:
        return self.refresh().get(name)


# ---------------------------------------------------------------------------
# fleet SLO engine (the "evaluate objectives over the federation" mode)
# ---------------------------------------------------------------------------

_fleet_engine = None
_fleet_engine_lock = threading.Lock()


def fleet_slo_engine():
    """Process-wide SLO engine whose registry IS the federation: same
    objectives, same burn-rate math, evaluated over the merged fleet
    series (``GET /slo?fleet=1`` on the admin server). Lazy — nothing
    scrapes until the first evaluation. Does NOT export to the admin's
    own ``pio_slo_burn_rate`` gauges (``export_gauges=False``): the
    fleet and process engines evaluate different populations, and
    sharing the series would let whichever ran last overwrite the
    other's meaning — fleet burn lives in the ``/slo?fleet=1`` JSON."""
    from incubator_predictionio_tpu.obs import slo as obs_slo

    global _fleet_engine
    with _fleet_engine_lock:
        if _fleet_engine is None:
            _fleet_engine = obs_slo.SLOEngine(registry=FleetRegistry(),
                                              export_gauges=False)
        return _fleet_engine


def reset_fleet_engine() -> None:
    """Drop the fleet engine (tests re-read PIO_FLEET_TARGETS/PIO_SLO_*
    on next use)."""
    global _fleet_engine
    with _fleet_engine_lock:
        _fleet_engine = None


__all__ = [
    "FederatedMetric", "FederatedSnapshot", "FleetRegistry", "Target",
    "INSTANCE_LABEL", "federate", "fleet_slo_engine", "fleet_targets",
    "parse_targets", "reset_fleet_engine", "scrape_target",
]
