"""obs — unified telemetry: metrics registry, exposition, trace IDs.

The reference leaned on the implicit Spark UI plus ad-hoc bookkeeping
(per-query latency in CreateServer.scala:426-428, per-app hourly ingest
counters in Stats.scala:51-80); the rebuild had reproduced those
fragments piecemeal (``utils/tracing.py`` phase walls, ``servers/
stats.py`` counters, native group-commit/scan counters only the bench
read). This package is the one coherent layer over all of them:

- :mod:`.metrics` — a process-wide registry of Counter / Gauge /
  Histogram metrics, thread-safe and cheap enough for the serving hot
  path (one uncontended lock + int add per observation, no host syncs,
  never called from inside traced code — the ``metric-in-trace`` lint
  rule enforces that last invariant repo-wide);
- :mod:`.exposition` (via :func:`metrics.Registry.expose`) —
  Prometheus text format, served at ``GET /metrics`` on every server
  (:func:`.http.add_metrics_route`);
- :mod:`.trace` — per-request trace IDs and span parenting: accepted
  from an incoming ``X-PIO-Trace-Id`` header, generated otherwise,
  propagated into the structured JSON span log and echoed on the
  response; in-repo client hops forward ``X-PIO-Parent-Span`` so span
  lines from multiple processes link into one tree
  (``scripts/trace_stitch.py``);
- :mod:`.expofmt` — the exposition grammar parser (promoted from the
  test oracle) that :mod:`.federate` uses to scrape and merge worker
  ``/metrics`` under an ``instance`` label (admin ``GET /federate``,
  fleet-mode SLOs);
- :mod:`.capacity` — the offline capacity/regression model over the
  checked-in bench trajectory (``scripts/capacity_report.py``);
- :mod:`.controller` — the self-driving freshness controller: consumes
  the fleet SLO burn rates, projects error-budget exhaustion, and
  autonomously triggers continuation retrain + rolling hot swap with a
  trace-linked decision audit trail (admin ``GET/POST /controller``);
- :mod:`.recorder` — the flight recorder: a bounded delta-encoded
  metric-history ring on every server (``GET /recorder``), histogram
  trace exemplars, and SLO-breach-triggered incident bundles that
  freeze the fleet-merged pre-breach window + exemplar trace IDs +
  scheduler state + controller decisions under ``PIO_INCIDENT_DIR``
  (admin ``GET /incidents`` / ``POST /incident``).

See ``docs/observability.md`` for the metric catalog and the scrape /
trace-propagation / fleet contracts.
"""

from incubator_predictionio_tpu.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_LATENCY_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from incubator_predictionio_tpu.obs.trace import (  # noqa: F401
    TRACE_HEADER,
    accept_trace_id,
    current_trace_id,
    new_trace_id,
)
