"""SLO burn-rate engine over the process-wide histogram state.

The system makes three quantitative promises — serving p99 latency,
end-to-end freshness p95, and a model-staleness bound — and this module
evaluates them **as promises**: each declared objective splits its
metric's observations into good/bad against a threshold, and the engine
computes multi-window **burn rates** (how fast the error budget is
being consumed relative to the rate the target allows) the way the SRE
workbook prescribes: a fast window that pages quickly on a hard breach
and a slow window that confirms a sustained one.

Mechanics: the registry's histograms are *cumulative*, so the engine
keeps a bounded ring of timestamped ``(good, bad)`` snapshots (one per
``tick()``, rate-limited) and derives a window's bad fraction from the
snapshot nearest the window start. Burn rate = bad_fraction /
(1 − target); burn 1.0 means "consuming budget exactly as fast as the
objective allows", >1 is a breach in that window. Error budget
remaining is ``1 − burn(slow window)``, clamped at 0.

Objectives default in code and are overridable via ``PIO_SLO_*`` env
knobs (see :func:`default_specs`). Evaluation happens lazily — at
``GET /slo`` and at scrape time via the registry collector — so an idle
process pays nothing. Exported series:

- ``pio_slo_burn_rate{slo,window="fast"|"slow"}``
- ``pio_slo_error_budget_remaining{slo}``

This is exactly the signal the ROADMAP-3 autonomous retrain controller
consumes next: *trigger when the staleness/freshness burn rate exceeds
1 in the fast window*.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.utils import times

logger = logging.getLogger(__name__)

BURN_RATE = obs_metrics.REGISTRY.gauge(
    "pio_slo_burn_rate",
    "error-budget burn rate (bad fraction / allowed bad fraction) over "
    "the window; >1 = consuming budget faster than the objective "
    "allows", labels=("slo", "window"))
BUDGET_REMAINING = obs_metrics.REGISTRY.gauge(
    "pio_slo_error_budget_remaining",
    "fraction of the error budget left over the slow window "
    "(1 - slow burn rate, clamped at 0)", labels=("slo",))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declared objective.

    ``kind="histogram"``: good = observations ≤ ``threshold`` of the
    named histogram family (children summed — cross-engine objectives
    collapse their label). ``kind="gauge"``: the engine synthesizes one
    observation per tick, good when the gauge ≤ ``threshold`` (the
    staleness bound has no per-event stream to count).

    ``labels`` (a frozen tuple of (name, value) pairs, so the spec
    stays hashable) SLICES a labeled family to the matching children —
    the per-tenant burn engines (:func:`tenant_specs`) are the serve
    objective with ``labels=(("tenant", <id>),)``: a tenant can burn
    its own budget while the fleet-wide objective stays green."""

    name: str
    metric: str
    threshold: float          # seconds
    target: float             # required good fraction, e.g. 0.99
    kind: str = "histogram"   # "histogram" | "gauge"
    description: str = ""
    labels: Tuple[Tuple[str, str], ...] = ()


def tenant_specs() -> Tuple[SLOSpec, ...]:
    """One serve_p99 objective per registered tenant (empty registry →
    none). Spec names are ``serve_p99@<tenant>`` — the ``@`` grammar is
    what tenant-labels incident-capture trigger dedup for free (the
    capture engine dedups on entry name) and what the bundle's tenant
    block parses back out. Each spec slices the shared latency family
    to the tenant's own child, so one tenant's burn never reads a
    neighbor's traffic."""
    from incubator_predictionio_tpu.serving import tenancy

    serve_threshold = _env_float("PIO_SLO_SERVE_P99_S", 0.25)
    serve_target = min(max(
        _env_float("PIO_SLO_SERVE_P99_TARGET", 0.99), 0.0), 0.9999)
    return tuple(
        SLOSpec(
            name=f"serve_p99@{tid}",
            metric="pio_query_latency_seconds",
            threshold=serve_threshold,
            target=serve_target,
            description=f"tenant {tid} per-query serving wall under "
                        "the bound",
            labels=(("tenant", tid),),
        )
        for tid in tenancy.get_registry().tenant_ids()
    )


def default_specs() -> Tuple[SLOSpec, ...]:
    """The shipped objectives; every number has a PIO_SLO_* override so
    operators declare THEIR promise without a code change. With a
    tenant registry configured (PIO_TENANTS), the per-tenant serve
    objectives (:func:`tenant_specs`) ride along — same burn engine,
    same breach-listener seam, tenant-named entries."""
    fleet = _fleet_specs()
    tenants = tenant_specs()
    return fleet + tenants


def _fleet_specs() -> Tuple[SLOSpec, ...]:
    return (
        SLOSpec(
            name="serve_p99",
            metric="pio_query_latency_seconds",
            threshold=_env_float("PIO_SLO_SERVE_P99_S", 0.25),
            target=min(max(
                _env_float("PIO_SLO_SERVE_P99_TARGET", 0.99), 0.0),
                0.9999),
            description="per-query serving wall under the bound"),
        SLOSpec(
            name="freshness_p95",
            metric="pio_freshness_seconds",
            threshold=_env_float("PIO_SLO_FRESHNESS_P95_S", 10.0),
            target=min(max(
                _env_float("PIO_SLO_FRESHNESS_TARGET", 0.95), 0.0),
                0.9999),
            description="event append -> first folded serve under the "
                        "bound"),
        SLOSpec(
            name="staleness",
            metric="pio_model_staleness_seconds",
            threshold=_env_float("PIO_SLO_STALENESS_S", 3600.0),
            target=min(max(
                _env_float("PIO_SLO_STALENESS_TARGET", 0.99), 0.0),
                0.9999),
            kind="gauge",
            description="deployed model age under the retrain bound"),
        SLOSpec(
            name="repl_lag",
            metric="pio_replication_lag_events",
            # threshold is in EVENTS, not seconds: the worst follower
            # of any shard may trail the primary by at most this many
            # acked events before the promise is breached
            threshold=_env_float("PIO_SLO_REPL_LAG", 10000.0),
            target=min(max(
                _env_float("PIO_SLO_REPL_LAG_TARGET", 0.99), 0.0),
                0.9999),
            kind="gauge",
            description="worst-of-shard follower replication lag "
                        "under the bound"),
    )


class SLOEngine:
    """Burn-rate evaluation over one registry. Thread-safe; cheap when
    idle (ticks are rate-limited, nothing runs between evaluations)."""

    def __init__(self, specs: Optional[Tuple[SLOSpec, ...]] = None,
                 registry: Optional[obs_metrics.Registry] = None,
                 clock: Optional[Callable[[], float]] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 min_tick_interval_s: float = 1.0,
                 max_snapshots: int = 8192,
                 export_gauges: bool = True) -> None:
        self.specs = tuple(specs if specs is not None else default_specs())
        self.registry = registry if registry is not None \
            else obs_metrics.REGISTRY
        self._clock = clock if clock is not None else times.monotonic
        self.fast_window_s = (fast_window_s if fast_window_s is not None
                              else _env_float("PIO_SLO_FAST_WINDOW_S",
                                              300.0))
        self.slow_window_s = (slow_window_s if slow_window_s is not None
                              else _env_float("PIO_SLO_SLOW_WINDOW_S",
                                              3600.0))
        #: whether evaluate() refreshes the process-registry burn/budget
        #: gauges. The FLEET engine (obs/federate.py) passes False: it
        #: evaluates a different population over the same gauge names,
        #: and letting it write pio_slo_burn_rate{slo,window} would make
        #: the exported series flip meaning between fleet and process
        #: depending on which endpoint ran last
        self._export_gauges = bool(export_gauges)
        self._min_tick = float(min_tick_interval_s)
        self._lock = threading.Lock()
        #: ring of (t, {slo_name: (good, bad)}) CUMULATIVE counts
        self._snaps: "deque[Tuple[float, Dict[str, Tuple[int, int]]]]" = \
            deque(maxlen=int(max_snapshots))
        #: gauge SLOs have no native event stream — the engine counts
        #: its own per-tick good/bad observations here
        self._gauge_counts: Dict[str, Tuple[int, int]] = {}
        #: fast-burn-crossing hooks (the incident-capture seam,
        #: obs/recorder.py): called with the breaching objective's
        #: evaluation entry on EVERY breached evaluation — listeners own
        #: their own dedup/cooldown, and they must never block (the
        #: capture engine enqueues to its own thread)
        self._breach_listeners: List[Callable[[Dict], None]] = []

    def add_breach_listener(self, fn: Callable[[Dict], None]) -> None:
        """Register a fast-burn-breach hook (idempotent per callable).
        This is the same signal the freshness controller consumes —
        ``breached`` = fast-window burn rate > 1."""
        with self._lock:
            if fn not in self._breach_listeners:
                self._breach_listeners.append(fn)

    def remove_breach_listener(self, fn: Callable[[Dict], None]) -> None:
        with self._lock:
            if fn in self._breach_listeners:
                self._breach_listeners.remove(fn)

    # -- sampling -----------------------------------------------------------
    def _counts_now(self) -> Dict[str, Tuple[int, int]]:
        out: Dict[str, Tuple[int, int]] = {}
        for spec in self.specs:
            metric = self.registry.get(spec.metric)
            if spec.kind == "histogram":
                if metric is None or metric.kind != "histogram":
                    continue  # not registered yet: no data, not a breach
                below, total = metric.cumulative_below(
                    spec.threshold,
                    labels=dict(spec.labels) if spec.labels else None)
                out[spec.name] = (below, total - below)
            else:
                if metric is None or metric.kind != "gauge" \
                        or not metric.has_samples():
                    # registered-but-never-set gauges are NO DATA, not
                    # health: a server whose deploy failed must not
                    # report a green staleness budget
                    continue
                good, bad = self._gauge_counts.get(spec.name, (0, 0))
                # worst-of, not sum-of: a gauge objective holds only
                # when EVERY child (and, on a federated registry, every
                # instance) is under the bound — the stalest worker
                # governs the fleet's staleness SLO
                if metric.max_value() <= spec.threshold:
                    good += 1
                else:
                    bad += 1
                self._gauge_counts[spec.name] = (good, bad)
                out[spec.name] = (good, bad)
        return out

    def tick(self, force: bool = False) -> None:
        """Append one cumulative snapshot (rate-limited to one per
        ``min_tick_interval_s`` unless forced)."""
        now = self._clock()
        with self._lock:
            if (not force and self._snaps
                    and now - self._snaps[-1][0] < self._min_tick):
                return
            self._snaps.append((now, self._counts_now()))

    def _window_delta(self, name: str, window_s: float,
                      now: float) -> Tuple[int, int, float]:
        """(good_delta, bad_delta, covered_seconds) for the trailing
        window, from the newest snapshot at/before the window start (or
        the oldest available — a young engine reports over what it has,
        honestly labeled by covered_seconds). Caller holds the lock."""
        if not self._snaps:
            return 0, 0, 0.0
        cutoff = now - window_s
        base = self._snaps[0]
        for snap in reversed(self._snaps):
            if snap[0] <= cutoff:
                base = snap
                break
        head = self._snaps[-1]
        g0, b0 = base[1].get(name, (0, 0))
        g1, b1 = head[1].get(name, (0, 0))
        return max(g1 - g0, 0), max(b1 - b0, 0), max(now - base[0], 0.0)

    # -- evaluation ---------------------------------------------------------
    def evaluate(self) -> List[Dict]:
        """Tick, then evaluate every objective → list of JSON-ready
        dicts (the /slo payload). Also refreshes the exported burn-rate
        and budget gauges."""
        self.tick()
        now = self._clock()
        out: List[Dict] = []
        with self._lock:
            snaps = bool(self._snaps)
            latest = self._snaps[-1][1] if snaps else {}
            windows = {}
            for spec in self.specs:
                windows[spec.name] = {
                    "fast": self._window_delta(spec.name,
                                               self.fast_window_s, now),
                    "slow": self._window_delta(spec.name,
                                               self.slow_window_s, now),
                }
        for spec in self.specs:
            allowed = max(1.0 - spec.target, 1e-9)
            totals = latest.get(spec.name)
            entry: Dict = {
                "name": spec.name,
                "objective": {
                    "metric": spec.metric,
                    "kind": spec.kind,
                    "thresholdSeconds": spec.threshold,
                    "target": spec.target,
                    "description": spec.description,
                    "labels": dict(spec.labels),
                },
                "noData": totals is None,
                "totalObservations": (None if totals is None
                                      else totals[0] + totals[1]),
                "windows": {},
            }
            burns = {}
            for wname, wsecs in (("fast", self.fast_window_s),
                                 ("slow", self.slow_window_s)):
                good, bad, covered = windows[spec.name][wname]
                seen = good + bad
                bad_frac = bad / seen if seen else 0.0
                burn = bad_frac / allowed
                burns[wname] = burn
                entry["windows"][wname] = {
                    "seconds": wsecs,
                    "coveredSeconds": round(covered, 3),
                    "observations": seen,
                    "badFraction": round(bad_frac, 6),
                    "burnRate": round(burn, 4),
                }
                if self._export_gauges:
                    BURN_RATE.labels(slo=spec.name, window=wname).set(burn)
            remaining = max(1.0 - burns["slow"], 0.0)
            entry["errorBudgetRemaining"] = round(remaining, 4)
            # page-worthy breach: budget burning faster than allowed in
            # the fast window (the slow window confirms sustained burns
            # via errorBudgetRemaining)
            entry["breached"] = bool(burns["fast"] > 1.0)
            if self._export_gauges:
                BUDGET_REMAINING.labels(slo=spec.name).set(remaining)
            out.append(entry)
        with self._lock:
            listeners = list(self._breach_listeners)
        if listeners:
            for entry in out:
                if not entry["breached"]:
                    continue
                for fn in listeners:
                    try:
                        fn(entry)
                    except Exception:
                        logger.exception(
                            "SLO breach listener failed for %s",
                            entry["name"])
        return out


# ---------------------------------------------------------------------------
# process-wide engine (lazy: env knobs are read at first use, and tests
# can reset to pick up monkeypatched objectives)
# ---------------------------------------------------------------------------

_engine: Optional[SLOEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SLOEngine:
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SLOEngine()
            obs_metrics.REGISTRY.register_collector("slo", _collect)
        return _engine


def reset_engine() -> None:
    """Drop the process engine (tests re-read PIO_SLO_* on next use)."""
    global _engine
    with _engine_lock:
        _engine = None


def _collect() -> None:
    """Scrape-time hook: every /metrics scrape refreshes the burn-rate
    and budget gauges (and advances the snapshot ring)."""
    engine = _engine
    if engine is not None:
        engine.evaluate()
