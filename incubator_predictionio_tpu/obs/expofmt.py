"""Prometheus text-exposition parsing — the grammar the registry emits.

Promoted from the ``tests/test_obs.py`` conformance oracle when the
federation layer (obs/federate.py) needed to *consume* worker
``/metrics`` scrapes, not just emit them: one strict mini-parser is now
both the test oracle and the production ingest path, so the emitter and
the parser can never drift apart silently — a malformed scrape fails
the federating admin exactly as loudly as it fails the test suite.

Two views of the same text:

- :func:`parse_exposition` — the flat oracle view ``(types, samples)``
  the conformance tests assert against;
- :func:`parse_families` — the structured view federation merges:
  per-family kind/help plus per-labelset values, with histogram
  children reassembled into (bounds, cumulative counts, sum, count).

Malformed input raises :class:`MalformedExposition` — an
``AssertionError`` subclass, so callers that treated the oracle's
``assert`` failures as the malformed-scrape signal keep working, while
the raise survives ``python -O``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Tuple

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    # optional label set; quoted values may hold ANY escaped content,
    # including braces (route patterns like /cmd/app/{name})
    r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})?'
    r" (-?(?:[0-9]*\.?[0-9]+(?:e[+-]?[0-9]+)?)|[+-]Inf|NaN)"
    # optional OpenMetrics-style exemplar annotation. Captured RAW and
    # passed through verbatim: a future emitter's richer annotation
    # must survive a federate round trip byte-stable even when this
    # parser cannot interpret it (docs/observability.md exemplars)
    r"(?: (# \{.*))?$")
_LABEL_ITEM_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: the annotation grammar THIS repo emits (obs/metrics.format_exemplar):
#: ``# {labels} value [timestamp]`` — anything else stays raw-only
_EXEMPLAR_RE = re.compile(
    r'^# (\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})'
    r" (-?(?:[0-9]*\.?[0-9]+(?:e[+-]?[0-9]+)?)|[+-]Inf|NaN)"
    r"(?: ([0-9]+(?:\.[0-9]+)?))?$")

_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")

#: one labeled sample: (name, frozenset of (label, value) items) → float
Samples = Dict[Tuple[str, FrozenSet[Tuple[str, str]]], float]


class MalformedExposition(AssertionError):
    """A line violated the text-format grammar (or a histogram lost an
    invariant). AssertionError subclass: the test oracle's callers
    catch AssertionError; production callers catch this by name."""


def _require(cond: bool, message: str) -> None:
    if not cond:
        raise MalformedExposition(message)


_ESCAPE_RE = re.compile(r"\\(.)")


def unescape_label_value(v: str) -> str:
    """Undo the exposition escaping (``\\\\``, ``\\"``, ``\\n``) so a
    re-exposed federated series does not double-escape. One
    left-to-right pass — sequential ``str.replace`` calls would corrupt
    a value like ``C:\\\\network`` (the unescaped backslash would feed
    the later ``\\n`` replacement)."""
    return _ESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def parse_exposition(text: str) -> Tuple[Dict[str, str], Samples]:
    """Validate + parse: returns ``(types, samples)`` where samples maps
    ``(name, frozenset(label items))`` → float. Raises
    :class:`MalformedExposition` on any line that violates the
    text-format grammar. Label values stay in their ESCAPED wire form
    (oracle compatibility); :func:`parse_families` unescapes. Exemplar
    annotations are accepted and dropped here (the flat oracle view);
    :func:`parse_families` carries them."""
    types, _helps, samples, _ex = _parse(text)
    return types, samples


#: raw exemplar annotations by sample: (name, ESCAPED labelset) → raw
RawExemplars = Dict[Tuple[str, FrozenSet[Tuple[str, str]]], str]


def _parse(text: str) -> Tuple[Dict[str, str], Dict[str, str], Samples,
                               RawExemplars]:
    """The one line-level pass: ``(types, helps, samples, exemplars)``."""
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    samples: Samples = {}
    exemplars: RawExemplars = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, h = rest.partition(" ")
            helps[name] = h
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, t = rest.partition(" ")
            _require(t in ("counter", "gauge", "histogram"), line)
            types[name] = t
            continue
        _require(not line.startswith("#"), f"unknown comment: {line}")
        m = _SAMPLE_RE.match(line)
        _require(m is not None, f"malformed sample line: {line!r}")
        name, labelblob, value, raw_ex = m.groups()
        labels = frozenset(_LABEL_ITEM_RE.findall(labelblob or ""))
        v = float("inf") if value == "+Inf" else float(value)
        samples[(name, labels)] = v
        if raw_ex is not None:
            # stored RAW, understood or not — pass-through is the
            # contract (an exemplar this parser cannot interpret must
            # still survive re-exposition byte-stable)
            exemplars[(name, labels)] = raw_ex
    # every sample's family must be declared (histogram children map to
    # their family name)
    for (name, _), _v in samples.items():
        family = _SUFFIX_RE.sub("", name)
        _require(name in types or family in types, name)
    return types, helps, samples, exemplars


def parse_exemplar(raw: str) -> Optional[Tuple[Dict[str, str], float,
                                               Optional[float]]]:
    """Structured view of one raw exemplar annotation:
    ``({label: value}, exemplar value, wall ts or None)`` when it
    matches the grammar this repo emits, None otherwise (the caller
    keeps the raw string either way — pass-through survives)."""
    m = _EXEMPLAR_RE.match(raw)
    if m is None:
        return None
    labelblob, value, ts = m.groups()
    labels = {k: unescape_label_value(v)
              for k, v in _LABEL_ITEM_RE.findall(labelblob)}
    v = float("inf") if value == "+Inf" else float(value)
    return labels, v, (float(ts) if ts is not None else None)


def histogram_series(
    samples: Samples, name: str,
    labels: FrozenSet[Tuple[str, str]] = frozenset(),
) -> Tuple[List[Tuple[float, float]], float, float]:
    """``(sorted [(le, cumulative)], sum, count)`` for one histogram
    child (the oracle helper, unchanged semantics)."""
    buckets = []
    for (n, ls), v in samples.items():
        if n == f"{name}_bucket" and labels <= ls:
            le = dict(ls)["le"]
            buckets.append((float("inf") if le == "+Inf" else float(le), v))
    buckets.sort()
    total = samples[(f"{name}_count", labels)]
    s = samples[(f"{name}_sum", labels)]
    return buckets, s, total


# ---------------------------------------------------------------------------
# structured family view (the federation ingest shape)
# ---------------------------------------------------------------------------

#: a labelset with unescaped values, le stripped for histogram children
LabelSet = FrozenSet[Tuple[str, str]]


@dataclasses.dataclass
class HistogramChild:
    """One histogram time series: ascending ``(le, cumulative)`` pairs
    (the +Inf bucket implied by ``count``), plus sum and count.
    ``exemplars`` maps a bucket's ``le`` bound to the RAW annotation
    string that rode its exposition line (pass-through contract);
    :func:`parse_exemplar` gives the structured view of each."""

    buckets: List[Tuple[float, float]]
    sum: float
    count: float
    exemplars: Dict[float, str] = dataclasses.field(default_factory=dict)

    def exemplar_trace_ids(self) -> List[Tuple[float, str]]:
        """``(le, trace_id)`` for every exemplar whose annotation this
        repo's grammar understands — the incident bundle's "which
        queries were the p99" linkage."""
        out: List[Tuple[float, str]] = []
        for le, raw in sorted(self.exemplars.items()):
            parsed = parse_exemplar(raw)
            if parsed is not None and "trace_id" in parsed[0]:
                out.append((le, parsed[0]["trace_id"]))
        return out

    def per_bucket(self) -> List[Tuple[float, float]]:
        """De-cumulated ``(le, count-in-bucket)`` pairs, finite bounds
        only; the overflow bucket is ``count - cum(last bound)``."""
        out: List[Tuple[float, float]] = []
        prev = 0.0
        for le, cum in self.buckets:
            if le == float("inf"):
                continue
            out.append((le, cum - prev))
            prev = cum
        return out

    def overflow(self) -> float:
        finite = [c for le, c in self.buckets if le != float("inf")]
        return self.count - (finite[-1] if finite else 0.0)


@dataclasses.dataclass
class Family:
    """One parsed metric family."""

    name: str
    kind: str
    help: str = ""
    #: counter/gauge children: labelset → value
    values: Dict[LabelSet, float] = dataclasses.field(default_factory=dict)
    #: histogram children: labelset (without ``le``) → HistogramChild
    histograms: Dict[LabelSet, HistogramChild] = dataclasses.field(
        default_factory=dict)
    #: raw exemplar annotations on counter/gauge samples (labelset →
    #: raw) — nothing in-repo emits these today, but a foreign scrape's
    #: annotations must pass through, not crash the federation
    exemplars: Dict[LabelSet, str] = dataclasses.field(
        default_factory=dict)


def _unescaped(labels: FrozenSet[Tuple[str, str]]) -> LabelSet:
    return frozenset((k, unescape_label_value(v)) for k, v in labels)


def parse_families(text: str) -> Dict[str, Family]:
    """The structured view: families with kind/help and reassembled
    histogram children. Raises :class:`MalformedExposition` like
    :func:`parse_exposition`; additionally requires every histogram
    child to carry its ``_sum``/``_count`` series."""
    types, helps, samples, raw_ex = _parse(text)

    out: Dict[str, Family] = {}
    for name, kind in types.items():
        out[name] = Family(name=name, kind=kind, help=helps.get(name, ""))
    # histogram assembly state: family → child labelset → {le: cum}
    hist_buckets: Dict[str, Dict[LabelSet, Dict[float, float]]] = {}
    hist_sums: Dict[str, Dict[LabelSet, float]] = {}
    hist_counts: Dict[str, Dict[LabelSet, float]] = {}
    hist_ex: Dict[str, Dict[LabelSet, Dict[float, str]]] = {}
    for (name, labels), v in samples.items():
        if name in types:
            fam = out[name]
            if fam.kind == "histogram":
                # a bare sample under a histogram family name is not
                # part of the text format
                raise MalformedExposition(
                    f"bare sample {name!r} under histogram family")
            fam.values[_unescaped(labels)] = v
            ex = raw_ex.get((name, labels))
            if ex is not None:
                fam.exemplars[_unescaped(labels)] = ex
            continue
        family = _SUFFIX_RE.sub("", name)
        suffix = name[len(family) + 1:]
        _require(out.get(family) is not None
                 and out[family].kind == "histogram",
                 f"sample {name!r} without a histogram family")
        if suffix == "bucket":
            le_raw = dict(labels).get("le")
            _require(le_raw is not None, f"bucket without le: {name}")
            le = float("inf") if le_raw == "+Inf" else float(le_raw)
            child = _unescaped(frozenset(
                (k, v2) for k, v2 in labels if k != "le"))
            hist_buckets.setdefault(family, {}).setdefault(
                child, {})[le] = v
            ex = raw_ex.get((name, labels))
            if ex is not None:
                hist_ex.setdefault(family, {}).setdefault(
                    child, {})[le] = ex
        elif suffix == "sum":
            hist_sums.setdefault(family, {})[_unescaped(labels)] = v
        else:  # count
            hist_counts.setdefault(family, {})[_unescaped(labels)] = v
    for family, children in hist_buckets.items():
        for child, by_le in children.items():
            s = hist_sums.get(family, {}).get(child)
            c = hist_counts.get(family, {}).get(child)
            _require(s is not None and c is not None,
                     f"histogram {family!r} child missing _sum/_count")
            buckets = sorted(by_le.items())
            # cumulative monotonicity — a torn scrape must fail loudly
            cums = [cum for _le, cum in buckets]
            _require(all(a <= b for a, b in zip(cums, cums[1:])),
                     f"non-monotone buckets in {family!r}")
            out[family].histograms[child] = HistogramChild(
                buckets=buckets, sum=s, count=c,
                exemplars=hist_ex.get(family, {}).get(child, {}))
    return out


__all__ = [
    "Family", "HistogramChild", "MalformedExposition", "Samples",
    "histogram_series", "parse_exemplar", "parse_exposition",
    "parse_families", "unescape_label_value",
]
