"""Request trace IDs + structured JSON span logs.

The propagation contract (docs/observability.md): every request to any
of the servers gets a trace ID — accepted from an incoming
``X-PIO-Trace-Id`` header when it is well-formed (1-128 chars of
``[A-Za-z0-9._:-]``), freshly generated otherwise — which is

- echoed back on the response in the same header,
- installed in a contextvar for the duration of the handler (the HTTP
  layer copies the context into the executor for sync handlers), and
- emitted in one structured JSON span line per request on the
  ``pio.trace`` logger (level INFO; silence it with
  ``logging.getLogger("pio.trace").setLevel(logging.WARNING)``).

A client that stamps its POST /events.json and POST /queries.json with
the same trace ID can therefore join the ingest span, the serving span
and any operator-side logs on one key — the distributed-tracing
contract at log-line cost, with no collector dependency.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import re
import secrets
import time
from typing import Any, Optional, Tuple

#: the propagation header, request and response side
TRACE_HEADER = "X-PIO-Trace-Id"
#: the cross-process PARENT link: an in-repo HTTP client stamps its own
#: span ID here so the downstream server's span line carries
#: ``parentSpanId`` and the two processes' spans join into one tree
#: (scripts/trace_stitch.py reconstructs the timeline)
PARENT_SPAN_HEADER = "X-PIO-Parent-Span"
#: response-side: the span ID the server assigned to THIS request, so
#: an external client can reference the server-side span in its own logs
SPAN_HEADER = "X-PIO-Span-Id"

_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")
#: span IDs share the trace-ID charset (locally generated ones are 8
#: hex chars, but a foreign tracer's IDs must survive the hop too)
_SPAN_ID_RE = _TRACE_ID_RE

_current: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_trace_id", default=None
)
_current_span: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("pio_span_id", default=None)

#: one JSON object per line; operators point this at their log shipper
span_logger = logging.getLogger("pio.trace")


def new_trace_id() -> str:
    """16 hex chars — collision-safe for log correlation windows."""
    return secrets.token_hex(8)


def accept_trace_id(incoming: Optional[str]) -> str:
    """The incoming header value when well-formed, else a fresh ID.
    Malformed values are REPLACED, not rejected: a trace header must
    never be able to fail a request (or smuggle log-breaking bytes)."""
    if incoming and _TRACE_ID_RE.match(incoming):
        return incoming
    return new_trace_id()


def current_trace_id() -> Optional[str]:
    """The ambient request's trace ID (None outside a request)."""
    return _current.get()


def set_current(trace_id: Optional[str]) -> contextvars.Token:
    return _current.set(trace_id)


def reset_current(token: contextvars.Token) -> None:
    _current.reset(token)


def new_span_id() -> str:
    """8 hex chars — unique within one trace's fan-out."""
    return secrets.token_hex(4)


def accept_parent_span(incoming: Optional[str]) -> Optional[str]:
    """The incoming parent-span header when well-formed, else None.
    Unlike trace IDs a malformed parent is DROPPED, not replaced: a
    fabricated parent would invent linkage that never happened."""
    if incoming and _SPAN_ID_RE.match(incoming):
        return incoming
    return None


def current_span_id() -> Optional[str]:
    """The ambient request's server-side span ID (None outside one)."""
    return _current_span.get()


def set_current_span(span_id: Optional[str]) -> contextvars.Token:
    return _current_span.set(span_id)


def reset_current_span(token: contextvars.Token) -> None:
    _current_span.reset(token)


def client_headers() -> dict:
    """Headers an in-repo HTTP client attaches to a downstream hop
    (prediction/event server → storage server, admin → workers,
    bench → servers): the ambient trace ID plus this request's span ID
    as the downstream parent. Empty outside a request — a client with
    no ambient trace forwards nothing and the server starts a fresh
    trace, exactly as before."""
    tid = _current.get()
    if tid is None:
        return {}
    out = {TRACE_HEADER: tid}
    sid = _current_span.get()
    if sid is not None:
        out[PARENT_SPAN_HEADER] = sid
    return out


def enable_span_logging() -> None:
    """Give the span logger a real sink: one bare-JSON line per request
    on stderr. The CLI server verbs call this so `pio eventserver` /
    `pio deploy` emit spans out of the box; library embedders configure
    logging themselves and never pay for it (an unconfigured logger
    fails the ``isEnabledFor`` gate). ``PIO_TRACE_LOG=off`` disables.
    Idempotent; propagation stays on so pytest caplog and operator root
    handlers keep seeing the records."""
    if os.environ.get("PIO_TRACE_LOG", "").lower() in (
            "off", "0", "false", "disable"):
        return
    if any(isinstance(h, logging.StreamHandler)
           for h in span_logger.handlers):
        return
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter("%(message)s"))
    span_logger.addHandler(handler)
    span_logger.setLevel(logging.INFO)


#: last parsed PIO_TRACE_SAMPLE value, keyed by the raw env string so a
#: runtime change re-parses but the steady state pays one dict-free
#: string compare per request (no float() on the hot path)
_sample_cache: Tuple[Optional[str], float] = (None, 1.0)


def sample_rate() -> float:
    """The span sampling rate from ``PIO_TRACE_SAMPLE`` (default 1.0 —
    every request emits its span line). Clamped to [0, 1]; read per call
    so operators can retune a live server, with the parse cached on the
    raw string value."""
    global _sample_cache
    raw = os.environ.get("PIO_TRACE_SAMPLE")
    cached_raw, cached = _sample_cache
    if raw == cached_raw:
        return cached
    try:
        rate = min(max(float(raw), 0.0), 1.0) if raw else 1.0
    except ValueError:
        rate = 1.0
    _sample_cache = (raw, rate)
    return rate


def span_sampled() -> bool:
    """Coin flip for THIS request's span line. Sampled-out requests
    still carry (and echo) their trace IDs — sampling drops only the
    JSON log line, which at bench QPS is the per-request hot-path cost;
    the propagation contract is unconditional."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    return rate > 0.0 and random.random() < rate


def log_span(server: str, method: str, route: str, status: int,
             duration_s: float, trace_id: str,
             span_id: Optional[str] = None,
             parent_span_id: Optional[str] = None,
             **extra: Any) -> None:
    """Emit the per-request JSON span line. Pre-gated on the logger
    level so a silenced logger costs one attribute read per request.
    ``span_id``/``parent_span_id`` carry the cross-process parenting
    contract: the downstream hop's line names the upstream span, so
    span lines from multiple processes link into one request tree."""
    if not span_logger.isEnabledFor(logging.INFO):
        return
    record = {
        "span": "http.request",
        "server": server,
        "method": method,
        "route": route,
        "status": status,
        # wall stamp (epoch s, ms precision): cross-PROCESS span lines
        # have no shared log stream, so the stitcher orders them by
        # wall clock — NTP-grade skew is fine at request granularity
        "ts": round(time.time(), 3),
        "durationMs": round(duration_s * 1e3, 3),
        "traceId": trace_id,
    }
    if span_id is not None:
        record["spanId"] = span_id
    if parent_span_id is not None:
        record["parentSpanId"] = parent_span_id
    if extra:
        record.update(extra)
    span_logger.info("%s", json.dumps(record, separators=(",", ":")))


def log_stage_span(span: str, trace_id: str, duration_s: float,
                   **extra: Any) -> None:
    """Emit a non-HTTP pipeline-stage span (the speed layer's freshness
    chain: ``speed.poll`` → ``speed.foldin`` → ``speed.serve``) on the
    same ``pio.trace`` logger and with the same shape as the request
    spans, so one trace ID joins an event's whole journey across log
    lines. Pre-gated like :func:`log_span`."""
    if not span_logger.isEnabledFor(logging.INFO):
        return
    record = {
        "span": span,
        "ts": round(time.time(), 3),
        "durationMs": round(duration_s * 1e3, 3),
        "traceId": trace_id,
    }
    if extra:
        record.update(extra)
    span_logger.info("%s", json.dumps(record, separators=(",", ":")))
