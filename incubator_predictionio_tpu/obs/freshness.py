"""End-to-end freshness tracing: event append → first serve.

The speed layer's promise is "an event influences served scores within
seconds", but until now that figure was only *inferred* from
``modelStalenessSec`` plus the cursor-lag gauge. This module measures
the promise directly: the storage tail read carries each event's
**append wall stamp** (``read_interactions_since`` fifth column), the
overlay threads the oldest unserved stamp through dirty-marking and
fold-in, and the first overlay HIT that serves the folded vector closes
the loop — one ``pio_freshness_seconds{engine}`` observation of
*event-appended → visible-in-a-prediction*.

Per-stage decomposition (gauges, last-batch values) localizes a
regression without a log dive:

- ``pio_freshness_poll_lag_seconds{engine}`` — append → tail-poll
  pickup (storage lag + poll interval),
- ``pio_freshness_fold_seconds{engine}`` — the batched fold-in wall the
  key rode (history read + device solve),
- ``pio_freshness_serve_pickup_seconds{engine}`` — vector published →
  first query that used it (traffic-dependent: an unqueried key sits).

One sampled journey per poll cycle additionally emits a linked span
chain (``speed.poll`` → ``speed.foldin`` → ``speed.serve``) on the
``pio.trace`` logger under a single generated trace ID — the same span
machinery the HTTP layer uses, so an operator can join an event's whole
path on one key.

Hot-path contract: :meth:`FreshnessTracker.on_serve_hit` runs on
serving threads — it is a dict pop + one histogram observe when the key
has a pending journey, and a single dict probe otherwise. Everything
else runs on the overlay's poller thread. The ``engine`` label comes
from the algorithm's declared engine name — a BOUNDED set, never a key
or entity id.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.utils import times

#: freshness spans milliseconds (hot poll loop) to HOURS (wedged poller
#: — exactly the regime an SLO must resolve), so this histogram gets its
#: own ladder instead of the serving-latency default, whose ~13.1 s cap
#: would saturate the headline metric precisely when freshness goes bad:
#: 10 ms doubling to ~23 h.
FRESHNESS_BUCKETS = tuple(0.01 * (2.0 ** i) for i in range(24))

#: the end-to-end promise: event append wall → first serve that used
#: the folded vector (docs/observability.md; the freshness_p95 SLO and
#: the bench's obs_freshness_p95_s both read this family)
FRESHNESS_SECONDS = obs_metrics.REGISTRY.histogram(
    "pio_freshness_seconds",
    "end-to-end freshness: event appended to the log -> first "
    "prediction served from the folded-in vector", labels=("engine",),
    buckets=FRESHNESS_BUCKETS)
POLL_LAG_SECONDS = obs_metrics.REGISTRY.gauge(
    "pio_freshness_poll_lag_seconds",
    "freshness stage 1 (last poll batch): event append -> tail-poll "
    "pickup", labels=("engine",))
FOLD_SECONDS = obs_metrics.REGISTRY.gauge(
    "pio_freshness_fold_seconds",
    "freshness stage 2 (last fold): batched fold-in wall the dirty "
    "keys rode", labels=("engine",))
SERVE_PICKUP_SECONDS = obs_metrics.REGISTRY.gauge(
    "pio_freshness_serve_pickup_seconds",
    "freshness stage 3 (last served key): vector published -> first "
    "query that used it", labels=("engine",))

#: append stamps older than this are treated as a historical backfill,
#: not live traffic, and skipped — a bulk import of last year's events
#: must not report year-long freshness (docs/observability.md)
MAX_PLAUSIBLE_AGE_S = 6 * 3600.0


class FreshnessTracker:
    """Per-overlay freshness bookkeeping. One instance per
    :class:`~incubator_predictionio_tpu.speed.overlay.SpeedOverlay`;
    the metric families are shared process-wide (label = engine)."""

    def __init__(self, engine: str = "default",
                 max_pending: int = 1 << 16) -> None:
        self.engine = str(engine)
        self._lock = threading.Lock()
        #: key -> oldest append wall (ms) among its not-yet-served events
        self._pending_append: Dict[str, int] = {}
        #: key -> (append_ms, publish_wall_ms, fold_wall_s) for folded
        #: keys whose first serve has not happened yet
        self._await_serve: Dict[str, Tuple[int, int, float]] = {}
        self._max_pending = int(max_pending)
        #: at most ONE sampled journey in flight: (key, trace_id,
        #: append_ms, poll_lag_s) set at poll time, extended at fold
        self._journey: Optional[Tuple[str, str, int, float]] = None
        self._journey_spans: Dict[str, float] = {}
        self._hist = FRESHNESS_SECONDS.labels(engine=self.engine)
        self._poll_lag = POLL_LAG_SECONDS.labels(engine=self.engine)
        self._fold = FOLD_SECONDS.labels(engine=self.engine)
        self._pickup = SERVE_PICKUP_SECONDS.labels(engine=self.engine)

    # -- poller-thread side -------------------------------------------------
    def on_poll_batch(self, append_ms_by_key: Dict[str, int]) -> None:
        """A tail poll dirtied ``keys`` with their oldest append stamps
        (epoch ms; stamps <= 0 mean the backend could not attribute an
        append wall and the key is skipped). Books the poll-lag stage
        and opens the sampled journey for this cycle."""
        if not append_ms_by_key:
            return
        now_ms = times.wall_millis()
        worst_lag = 0.0
        sample: Optional[Tuple[str, int]] = None
        with self._lock:
            # reclaim a stale sampled journey (its key was discarded or
            # evicted without ever serving) so sampling never wedges
            j = self._journey
            if j is not None and j[0] not in self._pending_append \
                    and j[0] not in self._await_serve:
                self._journey = None
                self._journey_spans = {}
            for key, append_ms in append_ms_by_key.items():
                if append_ms <= 0:
                    continue
                age_s = (now_ms - append_ms) / 1e3
                if not 0.0 <= age_s <= MAX_PLAUSIBLE_AGE_S:
                    continue  # historical backfill or clock skew
                prev = self._pending_append.get(key)
                if prev is None and len(self._pending_append) \
                        >= self._max_pending:
                    continue  # bounded: drop tracking, never memory
                self._pending_append[key] = (
                    append_ms if prev is None else min(prev, append_ms))
                worst_lag = max(worst_lag, age_s)
                if sample is None:
                    sample = (key, append_ms)
            if sample is not None and self._journey is None:
                key, append_ms = sample
                self._journey = (key, obs_trace.new_trace_id(), append_ms,
                                 (now_ms - append_ms) / 1e3)
        if worst_lag > 0.0:
            self._poll_lag.set(worst_lag)

    def on_folded(self, keys, fold_wall_s: float) -> None:
        """``keys`` were just published by one batched fold-in that took
        ``fold_wall_s``. Moves their pending stamps into the
        awaiting-first-serve set."""
        now_ms = times.wall_millis()
        published = 0
        with self._lock:
            for key in keys:
                append_ms = self._pending_append.pop(key, None)
                if append_ms is None:
                    continue
                if len(self._await_serve) >= self._max_pending:
                    continue
                self._await_serve[key] = (append_ms, now_ms, fold_wall_s)
                published += 1
            j = self._journey
            if j is not None and j[0] in self._await_serve:
                self._journey_spans = {"pollLagS": j[3],
                                       "foldS": fold_wall_s}
        if published:
            self._fold.set(fold_wall_s)

    def discard(self, keys) -> None:
        """Stop tracing ``keys`` (folded with nothing publishable — no
        vector can ever serve their events before the next retrain)."""
        if not keys:
            return
        with self._lock:
            for key in keys:
                self._pending_append.pop(key, None)

    def invalidate(self) -> None:
        """Cursor reset / overlay teardown: in-flight journeys are no
        longer measurable (their vectors are gone)."""
        with self._lock:
            self._pending_append.clear()
            self._await_serve.clear()
            self._journey = None
            self._journey_spans = {}

    # -- serving-thread side ------------------------------------------------
    def on_serve_hit(self, key: str) -> None:
        """An overlay lookup HIT served ``key``'s folded vector. First
        hit after a fold closes the end-to-end loop; later hits are one
        dict probe and return."""
        with self._lock:
            entry = self._await_serve.pop(key, None)
            if entry is None:
                return
            journey = self._journey
            spans = self._journey_spans
            if journey is not None and journey[0] == key:
                self._journey = None
                self._journey_spans = {}
            else:
                journey = None
        append_ms, publish_ms, fold_wall_s = entry
        now_ms = times.wall_millis()
        freshness_s = max((now_ms - append_ms) / 1e3, 0.0)
        pickup_s = max((now_ms - publish_ms) / 1e3, 0.0)
        self._hist.observe(freshness_s)
        self._pickup.set(pickup_s)
        if journey is not None:
            _key, trace_id, _append, poll_lag_s = journey
            obs_trace.log_stage_span(
                "speed.poll", trace_id, spans.get("pollLagS", poll_lag_s),
                engine=self.engine)
            obs_trace.log_stage_span(
                "speed.foldin", trace_id, spans.get("foldS", fold_wall_s),
                engine=self.engine)
            obs_trace.log_stage_span(
                "speed.serve", trace_id, pickup_s, engine=self.engine,
                freshnessS=round(freshness_s, 3))

    # -- introspection ------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pendingAppend": len(self._pending_append),
                    "awaitingServe": len(self._await_serve)}
