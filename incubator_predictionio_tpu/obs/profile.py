"""Device-time / MFU attribution for the hot dispatch entry points.

The bench computes MFU offline (analytic FLOPs over the measured warm
wall); production had no live equivalent — device time was invisible.
This module wraps the training, retrain, fold-in and serving dispatches
with **block-until-ready wall deltas** plus known-FLOP counters, off by
default and enabled with ``PIO_PROFILE=1``:

- ``pio_device_seconds{op}`` — attributed device+dispatch wall,
- ``pio_device_dispatches_total{op}`` — dispatches attributed,
- ``pio_device_flops_total{op}`` — analytic useful FLOPs (padding waste
  is *not* counted — it shows up as lower MFU, the honest convention
  the bench uses),
- ``pio_mfu{phase}`` — the LAST dispatch's model-FLOP utilization in
  that phase against the fp32 peak (``PIO_BENCH_PEAK_FLOPS``, same
  convention as the bench record's ``mfu``), so the live gauge and the
  bench's offline figure are directly comparable.

Op labels are a BOUNDED set chosen by the call sites: ``als_train``
(XLA-assembly training), ``als_fused`` (training through the fused
Gram+solve Pallas kernel path — its own label so the kernel's measured
trajectory is separable in /metrics, while ``als.train_flops`` stays
the ONE FLOP formula for both, keeping ``pio_mfu{phase="train"}``
comparable across the split), ``als_retrain`` (continuation retrain on
the XLA path), ``foldin_solve`` (speed-layer fold-in buckets — same
label on both its XLA and fused-kernel solve paths) and the serving
``serve_topk``/``serve_topk_batch`` entries.

OFF is the contract: with ``PIO_PROFILE`` unset, a call site pays one
``t0()`` env read returning None and one ``record()`` None-check —
no block_until_ready, no metrics, no jax import. The profiler is the
ONLY module allowed to call ``block_until_ready`` on a serve-reachable
path (the ``blocking-profiler`` pio-lint rule enforces this): when ON,
every attributed dispatch becomes synchronous, which is exactly what a
wall measurement means — never leave it on for latency-critical
production serving, use a canary.

``capture_trace`` is the on-demand ``jax.profiler`` xplane capture
behind the admin server's ``POST /profile?seconds=N`` — the raw input
for the ROADMAP-5 kernel work.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

from incubator_predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

DEVICE_SECONDS = obs_metrics.REGISTRY.counter(
    "pio_device_seconds",
    "device+dispatch wall attributed by the PIO_PROFILE=1 profiler, "
    "by op", labels=("op",))
DEVICE_DISPATCHES = obs_metrics.REGISTRY.counter(
    "pio_device_dispatches_total",
    "dispatches attributed by the profiler, by op", labels=("op",))
DEVICE_FLOPS = obs_metrics.REGISTRY.counter(
    "pio_device_flops_total",
    "analytic useful FLOPs attributed by the profiler, by op",
    labels=("op",))
MFU = obs_metrics.REGISTRY.gauge(
    "pio_mfu",
    "last attributed dispatch's model-FLOP utilization vs the fp32 "
    "peak (PIO_BENCH_PEAK_FLOPS), by phase", labels=("phase",))


def enabled() -> bool:
    """True when the dispatch profiler is on (``PIO_PROFILE=1``). Read
    per call — a live process can be toggled — and cheap enough for the
    serving hot path (one env dict lookup)."""
    return os.environ.get("PIO_PROFILE", "0").lower() not in (
        "0", "", "false", "off")


def peak_flops() -> float:
    """The fp32 peak the MFU gauge divides by — the SAME knob the bench
    uses (``PIO_BENCH_PEAK_FLOPS``, default TPU v5e ~98.5 TF/s fp32) so
    ``pio_mfu{phase="train"}`` and the record's ``mfu`` are the same
    convention by construction."""
    try:
        return float(os.environ.get("PIO_BENCH_PEAK_FLOPS", "") or 98.5e12)
    except ValueError:
        return 98.5e12


def t0() -> Optional[float]:
    """Dispatch-entry stamp: ``time.perf_counter()`` when profiling is
    on, None otherwise. The None is the whole off-path cost — callers
    hand it straight back to :func:`record`."""
    if not enabled():
        return None
    return time.perf_counter()


def record(start: Optional[float], phase: str, op: str,
           flops: float = 0.0, result: Any = None,
           flops_fn: Any = None) -> None:
    """Close one attributed dispatch: block until ``result`` is device-
    complete, book the wall under ``op`` and refresh ``pio_mfu{phase}``.
    No-op when ``start`` is None (profiling was off at :func:`t0`).

    ``flops_fn`` (a zero-arg callable) defers a FLOP count whose
    computation itself touches the device (e.g. nnz from tree mask
    sums) until AFTER ``dt`` is captured — otherwise its dispatches and
    fetches would contaminate the measured wall. Plain ``flops`` is for
    host-arithmetic counts.

    This is the one sanctioned ``block_until_ready`` on serve-reachable
    paths (pio-lint ``blocking-profiler``): a wall measurement *is* a
    sync point. Telemetry must never fail the dispatch — any error here
    logs and returns."""
    if start is None:
        return
    try:
        if result is not None:
            import jax

            jax.block_until_ready(result)
        dt = time.perf_counter() - start
        if flops_fn is not None:
            flops = float(flops_fn())
        DEVICE_SECONDS.labels(op=op).inc(dt)
        DEVICE_DISPATCHES.labels(op=op).inc()
        if flops > 0:
            DEVICE_FLOPS.labels(op=op).inc(flops)
            if dt > 0:
                MFU.labels(phase=phase).set(flops / dt / peak_flops())
    except Exception:
        logger.exception("dispatch profiler record failed (op=%s)", op)


# ---------------------------------------------------------------------------
# on-demand jax.profiler capture (admin POST /profile?seconds=N)
# ---------------------------------------------------------------------------

#: serializes captures: jax.profiler supports one active trace per
#: process, and a second start_trace would raise mid-capture
_capture_lock = threading.Lock()

MAX_CAPTURE_SECONDS = 120.0


def capture_trace(seconds: float, out_dir: Optional[str] = None) -> dict:
    """Capture ``seconds`` of ``jax.profiler`` trace into ``out_dir``
    (default ``$PIO_PROFILE_DIR`` or a per-capture temp dir) and return
    ``{"traceDir", "seconds"}``. Blocks the caller for the capture
    window — the admin route runs it on the executor, so the server
    keeps serving. Raises RuntimeError when a capture is already
    running (the route maps it to 409) and ValueError on a bad window.
    """
    seconds = float(seconds)
    if not 0.0 < seconds <= MAX_CAPTURE_SECONDS:
        raise ValueError(
            f"seconds must be in (0, {MAX_CAPTURE_SECONDS:.0f}]")
    if out_dir is None:
        out_dir = os.environ.get("PIO_PROFILE_DIR")
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        # dir created only once the capture is actually ours to run (a
        # rejected 409 must not leak an empty temp dir per request)
        if out_dir is None:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="pio_profile_")
        import jax

        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        _capture_lock.release()
    return {"traceDir": out_dir, "seconds": seconds}
