"""Flight recorder — bounded metric history + automatic incident capture.

A ``/metrics`` scrape is a point-in-time truth: when a fleet SLO
breaches, nothing on any server can answer "what did p99, queue depth
and shed rate look like in the 60 s *before* the breach, and which
queries were the p99". The controller's decision ring (obs/controller)
proved the shape that fixes this — bounded in-memory history plus trace
linkage — and this module generalizes it to the whole observability
plane:

- :class:`FlightRecorder` — an always-on background sampler snapshots
  every registry metric at ``PIO_RECORDER_HZ`` (default 1 Hz) into a
  fixed-size **delta-encoded ring** covering ``PIO_RECORDER_WINDOW_S``
  (default 600 s). A tick costs one ``Registry.run_collectors()`` plus
  a lock-free ring append (single-writer slot store under the GIL;
  readers validate the per-entry sample index instead of taking a lock
  the serving path could ever contend on). ``GET /recorder`` on every
  server serves the reconstructed window as JSON.

- **Incident capture** — :class:`IncidentCapture` hooks the SLO
  burn-rate engine's fast-burn crossing (the same signal the freshness
  controller consumes — ``SLOEngine.add_breach_listener``) and
  ``POST /incident``, and freezes one self-contained JSON bundle under
  ``PIO_INCIDENT_DIR``: the fleet-merged recorder window (the admin
  pulls each worker's ``/recorder``, instance-labeled like
  ``/federate``), the breaching SLO's exemplar trace IDs (the
  histogram exemplars obs/metrics.py reservoir-samples), each worker's
  scheduler state block, and the in-window controller decisions.
  Dedup + cooldown (``PIO_INCIDENT_COOLDOWN_S``) make a sustained burn
  yield ONE bundle, not hundreds; ``GET /incidents`` lists them.

Serve-path contract (the ``recorder-in-serve-path`` pio-lint rule):
snapshot/capture entry points (``sample_now``/``dump``/``window``/
``capture_now``) run only on this module's own threads and the
admin/debug HTTP handlers — never anywhere a ``predict``/
``batch_predict``/scheduler dispatch can reach. The serving hot path's
total exposure to this module is the one histogram-exemplar reservoir
write it already pays in ``observe()``.

Exported series (docs/observability.md):

- ``pio_recorder_samples_total``
- ``pio_recorder_ring_bytes`` (rough in-memory estimate)
- ``pio_incidents_total{trigger}``
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.utils import times

logger = logging.getLogger(__name__)

#: keyframe cadence: every K-th sample stores the FULL flat snapshot so
#: any retained window start is reachable from a keyframe at most K-1
#: deltas back (the ring over-allocates by K slots to guarantee it)
KEYFRAME_EVERY = 60


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def recorder_enabled() -> bool:
    """``PIO_RECORDER`` kill switch, default on. Off means NO sampler
    thread exists and ``/recorder`` answers 503 — zero overhead, pinned
    by tests/test_recorder.py."""
    return os.environ.get("PIO_RECORDER", "1").strip().lower() not in (
        "0", "off", "false")


def recorder_hz() -> float:
    hz = _env_float("PIO_RECORDER_HZ", 1.0)
    return hz if hz > 0 else 1.0


def recorder_window_s() -> float:
    w = _env_float("PIO_RECORDER_WINDOW_S", 600.0)
    return w if w > 0 else 600.0


def incident_dir() -> Optional[str]:
    """Capture destination; unset/empty disables incident capture (the
    recorder itself stays on — history without capture is still
    diagnosis)."""
    return os.environ.get("PIO_INCIDENT_DIR", "").strip() or None


def incident_cooldown_s() -> float:
    return _env_float("PIO_INCIDENT_COOLDOWN_S", 300.0)


# ---------------------------------------------------------------------------
# state providers — subsystems publish a snapshot callable (the
# scheduler's queue/rung/shed state) that rides the recorder dump and
# every incident bundle. Named replace semantics like registry
# collectors, so re-created subsystems never accumulate dead hooks.
# ---------------------------------------------------------------------------

_state_providers: Dict[str, Callable[[], Any]] = {}
_state_lock = threading.Lock()


def register_state_provider(name: str, fn: Callable[[], Any]) -> None:
    with _state_lock:
        _state_providers[name] = fn


def unregister_state_provider(name: str) -> None:
    with _state_lock:
        _state_providers.pop(name, None)


def collect_state() -> Dict[str, Any]:
    """Every registered provider's snapshot; a failing (or garbage-
    collected) provider reports its error string instead of failing
    the dump."""
    with _state_lock:
        providers = list(_state_providers.items())
    out: Dict[str, Any] = {}
    for name, fn in providers:
        try:
            value = fn()
        except Exception as e:  # noqa: BLE001 — per-provider degradation
            out[name] = {"error": str(e)}
            continue
        if value is not None:
            out[name] = value
    return out


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------

#: flat snapshot key: (metric name, sorted label items tuple)
_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _quantile_from_buckets(bounds: Sequence[float],
                           counts: Sequence[float],
                           q: float) -> Optional[float]:
    """Quantile by linear interpolation over per-bucket counts (the
    registry's own rule; ``counts`` aligned with ``bounds`` + overflow).
    None when empty; overflow clamps to the last finite bound."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            if i >= len(bounds):
                return float(bounds[-1]) if bounds else None
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * max(rank - cum, 0.0) / c
        cum += c
    return float(bounds[-1]) if bounds else None


class FlightRecorder:
    """Bounded delta-encoded metric history over one registry.

    Single-writer: only the sampler thread (or a test driving
    :meth:`sample_now`) appends. The ring is a plain slot list — the
    writer stores an immutable entry tuple and bumps the head index;
    readers validate each entry's embedded sample index against the
    position they expected, so a concurrently overwritten slot is
    detected and skipped rather than guarded by a lock the hot path
    could contend on.
    """

    def __init__(self, registry: Optional[obs_metrics.Registry] = None,
                 hz: Optional[float] = None,
                 window_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 wall: Optional[Callable[[], float]] = None,
                 keyframe_every: int = KEYFRAME_EVERY) -> None:
        self.registry = registry if registry is not None \
            else obs_metrics.REGISTRY
        self.hz = float(hz) if hz is not None else recorder_hz()
        self.window_s = (float(window_s) if window_s is not None
                         else recorder_window_s())
        self._clock = clock if clock is not None else times.monotonic
        self._wall = wall if wall is not None else time.time
        self._keyframe_every = max(int(keyframe_every), 1)
        #: retained samples the window needs, + keyframe slack so a
        #: reachable keyframe always precedes the oldest window sample
        self.slots = int(self.window_s * self.hz) + self._keyframe_every + 1
        #: ring entries: (idx, wall_ts, kind, data, byte_est) — kind
        #: "key" (full snapshot) or "delta" (changed series only).
        #: All four ring fields below are single-writer (the sampler
        #: loop) immutable-publishes read lock-free by window()/index();
        #: the annotations are VERIFIED by pio-lint's
        #: unguarded-shared-state pass (docs/lint.md).
        self._ring: List[Optional[tuple]] = [None] * self.slots  # pio-lint: publish-only
        self._head = 0  # pio-lint: publish-only — next sample index (monotonic)
        self._ring_bytes = 0  # pio-lint: publish-only
        self._last: Dict[_SeriesKey, Any] = {}
        #: family meta discovered at snapshot time: name → (kind, bounds)
        # pio-lint: publish-only
        self._meta: Dict[str, Tuple[str, Optional[Tuple[float, ...]]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples_total = self.registry.counter(
            "pio_recorder_samples_total",
            "flight-recorder ring samples appended")
        self._ring_bytes_g = self.registry.gauge(
            "pio_recorder_ring_bytes",
            "rough in-memory size of the flight-recorder ring "
            "(delta-encoded; climbing steadily = label-cardinality "
            "audit time, see the runbook)")

    # -- snapshotting -------------------------------------------------------
    def _flat_snapshot(self) -> Dict[_SeriesKey, Any]:
        """Every registry series as a flat {(name, labels): value} map.
        Counter/gauge values are floats; histogram children are
        ``(counts tuple incl. overflow, sum, count)``."""
        out: Dict[_SeriesKey, Any] = {}
        with self.registry._lock:
            metrics_list = list(self.registry._metrics.values())
        for m in metrics_list:
            with m._lock:
                children = list(m._children.items())
            if m.kind == "histogram":
                self._meta[m.name] = (m.kind, m._buckets)
                for key, child in children:
                    counts, csum, count = child.snapshot()
                    out[(m.name, tuple(zip(m.labelnames, key)))] = (
                        tuple(counts), csum, count)
            else:
                self._meta[m.name] = (m.kind, None)
                for key, child in children:
                    out[(m.name, tuple(zip(m.labelnames, key)))] = \
                        child.value
        return out

    @staticmethod
    def _entry_bytes(data: Dict[_SeriesKey, Any]) -> int:
        """Rough per-entry footprint for pio_recorder_ring_bytes: key
        strings + 8 bytes per scalar, 8 per histogram bucket count."""
        est = 64
        for (name, labels), v in data.items():
            est += len(name) + 16 * (len(labels) + 1)
            est += 8 * (len(v[0]) + 2) if isinstance(v, tuple) else 8
        return est

    def sample_now(self) -> int:
        """Append one sample (the sampler tick; tests drive it with a
        FakeClock). Returns the sample's index."""
        self.registry.run_collectors()
        snap = self._flat_snapshot()
        idx = self._head
        keyframe = idx % self._keyframe_every == 0
        if keyframe:
            data: Dict[_SeriesKey, Any] = snap
        else:
            last = self._last
            data = {k: v for k, v in snap.items()
                    if last.get(k) != v}
        est = self._entry_bytes(data)
        entry = (idx, self._wall(), "key" if keyframe else "delta",
                 data, est)
        slot = idx % self.slots
        evicted = self._ring[slot]
        # single-writer slot store: entry tuples are immutable, the
        # head bump is a plain int assignment — readers validate the
        # embedded idx instead of locking
        self._ring[slot] = entry
        self._head = idx + 1
        self._last = snap
        self._ring_bytes += est - (evicted[4] if evicted else 0)
        self._ring_bytes_g.set(float(self._ring_bytes))
        self._samples_total.inc()
        return idx

    # -- reading ------------------------------------------------------------
    def _live_entries(self) -> List[tuple]:
        """Consistent ascending entry list: each slot's entry is kept
        only if its embedded index matches the position implied by the
        head snapshot (an entry overwritten mid-read self-identifies
        and is dropped)."""
        head = self._head
        lo = max(head - self.slots, 0)
        out: List[tuple] = []
        for idx in range(lo, head):
            e = self._ring[idx % self.slots]
            if e is not None and e[0] == idx:
                out.append(e)
        return out

    def window(self, series: Optional[Sequence[str]] = None,
               window_s: Optional[float] = None) -> Dict[str, Any]:
        """Reconstruct the trailing window → JSON-ready dict.

        ``series=None`` returns every recorded family. Histogram points
        carry per-interval quantiles (the "what did p99 look like"
        answer): each point's p50/p99 is computed over the bucket
        DELTAS since the previous sample, so the series shows the tail
        of that second's observations, not the cumulative-forever
        distribution."""
        want_s = min(window_s if window_s is not None else self.window_s,
                     self.window_s)
        n_want = int(want_s * self.hz) + 1
        entries = self._live_entries()
        out: Dict[str, Any] = {
            "hz": self.hz,
            "windowS": want_s,
            "samples": 0,
            "series": {},
        }
        if not entries:
            return out
        # the sampler overwrites the OLDEST slots while we read, so the
        # entry list can have holes at its old end — anything before a
        # gap is unreplayable (a delta chain with a missing link would
        # reconstruct silently-wrong values). Keep only the longest
        # contiguous suffix.
        suffix = len(entries) - 1
        while suffix > 0 and entries[suffix - 1][0] == \
                entries[suffix][0] - 1:
            suffix -= 1
        entries = entries[suffix:]
        head = entries[-1][0] + 1
        start_idx = max(head - n_want, 0)
        # newest KEYFRAME at/before the window start (ring slack makes
        # one exist among the retained entries in steady state); when a
        # concurrent wrap ate it, fall forward to the first retained
        # keyframe — an honestly narrower window, never a broken chain.
        # No keyframe in the suffix at all (a young or heavily-raced
        # ring) = nothing reconstructable: return empty, not wrong.
        key_pos = None
        for i, e in enumerate(entries):
            if e[2] != "key":
                continue
            if e[0] <= start_idx or key_pos is None:
                key_pos = i
            if e[0] > start_idx:
                break
        if key_pos is None:
            return out
        state: Dict[_SeriesKey, Any] = {}
        selected = set(series) if series else None
        points: Dict[_SeriesKey, List[list]] = {}
        prev_hist: Dict[_SeriesKey, tuple] = {}
        emitted = 0
        for e in entries[key_pos:]:
            idx, ts, kind, data, _est = e
            if kind == "key":
                state = dict(data)
            else:
                state.update(data)
            if idx < start_idx:
                # pre-window replay still tracks histogram state so the
                # FIRST in-window point's interval delta has a base
                for k, v in state.items():
                    if isinstance(v, tuple):
                        prev_hist[k] = v
                continue
            emitted += 1
            for k, v in state.items():
                name = k[0]
                if selected is not None and name not in selected:
                    continue
                pts = points.setdefault(k, [])
                if isinstance(v, tuple):
                    counts, csum, count = v
                    prev = prev_hist.get(k)
                    if prev is not None:
                        dcounts = [a - b for a, b in
                                   zip(counts, prev[0])]
                        dcount = count - prev[2]
                    else:
                        dcounts, dcount = list(counts), count
                    bounds = self._meta.get(name, ("", None))[1] or ()
                    pts.append([
                        round(ts, 3), count, round(csum, 6), dcount,
                        _quantile_from_buckets(bounds, dcounts, 0.5),
                        _quantile_from_buckets(bounds, dcounts, 0.99),
                    ])
                    prev_hist[k] = v
                else:
                    pts.append([round(ts, 3), v])
        for (name, labels), pts in points.items():
            kind, _bounds = self._meta.get(name, ("gauge", None))
            fam = out["series"].setdefault(name, {
                "kind": kind, "children": []})
            fam["children"].append({"labels": dict(labels),
                                    "points": pts})
        out["samples"] = emitted
        return out

    def index(self) -> Dict[str, Any]:
        """The cheap no-args ``GET /recorder`` answer: what is recorded,
        at what cadence, how big."""
        entries = self._live_entries()
        return {
            "hz": self.hz,
            "windowS": self.window_s,
            "samples": len(entries),
            "ringBytes": self._ring_bytes,
            "series": sorted(self._meta),
        }

    def exemplars(self) -> List[Dict[str, Any]]:
        """Current exemplars of every histogram family on the registry
        (live state, not ring history — exemplar windows are shorter
        than the ring)."""
        with self.registry._lock:
            metrics_list = list(self.registry._metrics.values())
        out: List[Dict[str, Any]] = []
        for m in metrics_list:
            if m.kind != "histogram":
                continue
            for ex in m.exemplars():
                ex["metric"] = m.name
                out.append(ex)
        return out

    def dump(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """The full self-describing snapshot an incident bundle (or
        ``GET /recorder?all=1``) freezes: the whole-series window plus
        current exemplars and every registered state-provider block."""
        out = self.window(series=None, window_s=window_s)
        out["wallTs"] = round(self._wall(), 3)
        out["exemplars"] = self.exemplars()
        out["state"] = collect_state()
        return out

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Start the background sampler (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(self._stop,),
            name="pio-flight-recorder", daemon=True)
        self._thread.start()

    def _loop(self, stop: threading.Event) -> None:
        period = 1.0 / self.hz
        while not stop.is_set():
            try:
                self.sample_now()
            except Exception:
                logger.exception("flight-recorder sample failed")
            stop.wait(period)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None


# ---------------------------------------------------------------------------
# incident capture
# ---------------------------------------------------------------------------

def _peek_controller_decisions(limit: int = 256) -> List[Dict[str, Any]]:
    """The controller ring WITHOUT creating a controller: an incident
    bundle on a process that never ran one records an empty audit
    trail, not a fresh controller as a side effect."""
    from incubator_predictionio_tpu.obs import controller as obs_controller

    return obs_controller.peek_decisions(limit=limit)


def _peek_knob_decisions(limit: int = 256) -> List[Dict[str, Any]]:
    """Same contract for the knob controller's ring (obs/knobs.py):
    peek, never instantiate."""
    from incubator_predictionio_tpu.obs import knobs as obs_knobs

    return obs_knobs.peek_knob_decisions(limit=limit)


def _peek_tenants() -> Optional[Dict[str, Any]]:
    """The tenant block WITHOUT side effects: None in single-tenant
    mode (the bundle key stays absent-by-value, pre-tenancy bundles
    unchanged in spirit)."""
    from incubator_predictionio_tpu.serving import tenancy

    return tenancy.export_tenants_fn()()


def _recorder_url(metrics_url: str) -> str:
    """A federation target's ``/metrics`` URL → its ``/recorder`` full
    dump (same host/port; the route rides every server)."""
    scheme, _, rest = metrics_url.partition("://")
    authority = rest.split("/", 1)[0]
    return f"{scheme}://{authority}/recorder?all=1"


class IncidentCapture:
    """Breach-triggered bundle freezer. Triggers are non-blocking —
    they enqueue onto this engine's own worker thread, so the SLO
    evaluation (and anything that runs it: scrapes, the controller
    loop, the recorder tick) never waits on bundle I/O."""

    #: non-SLO trigger label values (SLO triggers use the bounded
    #: declared-objective names)
    MANUAL_TRIGGER = "manual"

    def __init__(self,
                 directory: Optional[str] = None,
                 recorder: Optional[FlightRecorder] = None,
                 cooldown_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 wall: Optional[Callable[[], float]] = None,
                 targets_fn: Optional[Callable[[], Sequence[Any]]] = None,
                 decisions_fn: Optional[
                     Callable[[], List[Dict[str, Any]]]] = None,
                 knobs_fn: Optional[
                     Callable[[], List[Dict[str, Any]]]] = None,
                 tenants_fn: Optional[
                     Callable[[], Optional[Dict[str, Any]]]] = None,
                 registry: Optional[obs_metrics.Registry] = None) -> None:
        d = directory if directory is not None else incident_dir()
        if not d:
            raise ValueError(
                "incident capture needs a directory: set PIO_INCIDENT_DIR")
        self.directory = d
        # created eagerly: an unwritable destination must fail HERE
        # (loudly, at install time), not at the first breach — the
        # "breach with no bundle" runbook row's first check
        os.makedirs(self.directory, exist_ok=True)
        self._recorder = recorder
        self.cooldown_s = (float(cooldown_s) if cooldown_s is not None
                           else incident_cooldown_s())
        self.window_s = (float(window_s) if window_s is not None
                         else recorder_window_s())
        self._clock = clock if clock is not None else times.monotonic
        self._wall = wall if wall is not None else time.time
        if targets_fn is None:
            from incubator_predictionio_tpu.obs import federate

            targets_fn = federate.fleet_targets
        self._targets_fn = targets_fn
        self.decisions_fn = (decisions_fn if decisions_fn is not None
                             else _peek_controller_decisions)
        #: the knob controller's ring (obs/knobs.py) — a second audit
        #: trail the bundle freezes; the admin server rebinds it to its
        #: hosted instance exactly like decisions_fn
        self.knobs_fn = (knobs_fn if knobs_fn is not None
                         else _peek_knob_decisions)
        #: the tenant block seam (serving/tenancy.export_tenants_fn):
        #: registry policy + per-tenant SLO entries frozen into the
        #: bundle so it answers "which tenant breached, and was the
        #: fleet healthy" offline. Rebound by the admin like
        #: decisions_fn; the default peeks the process registry.
        self.tenants_fn = (tenants_fn if tenants_fn is not None
                           else _peek_tenants)
        reg = registry if registry is not None else obs_metrics.REGISTRY
        self._incidents_total = reg.counter(
            "pio_incidents_total",
            "incident bundles captured, by trigger (declared SLO names "
            "+ manual)", labels=("trigger",))
        self._lock = threading.Lock()
        #: trigger key → last capture wall (the dedup/cooldown state)
        self._last_capture: Dict[str, float] = {}
        self._pending: "queue.Queue[Tuple[str, Optional[Dict]]]" = \
            queue.Queue()
        self._queued: set = set()
        self._thread: Optional[threading.Thread] = None

    # -- triggering ---------------------------------------------------------
    def install(self, *engines: Any) -> None:
        """Register the breach hook on SLO engines (process and/or
        fleet) and start the worker thread."""
        for engine in engines:
            engine.add_breach_listener(self.on_breach)
        self._ensure_worker()

    def on_breach(self, entry: Dict[str, Any]) -> None:
        """SLOEngine breach listener: fast-burn crossed 1 for this
        objective. Never blocks — dedup/cooldown decide inline, the
        bundle is built on the worker thread."""
        self.trigger(entry["name"], entry)

    def trigger(self, reason: str,
                slo_entry: Optional[Dict[str, Any]] = None) -> bool:
        """Enqueue one capture unless the reason is cooling down or
        already queued. Returns whether a capture was enqueued."""
        now = self._clock()
        with self._lock:
            last = self._last_capture.get(reason)
            if last is not None and now - last < self.cooldown_s:
                return False
            if reason in self._queued:
                return False
            # cooldown stamped at TRIGGER time: a sustained burn fires
            # the listener on every evaluation, and the dedup must hold
            # even while the first bundle is still being written
            self._last_capture[reason] = now
            self._queued.add(reason)
        self._ensure_worker()
        self._pending.put((reason, slo_entry))
        return True

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._worker, name="pio-incident-capture",
                daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        while True:
            reason, slo_entry = self._pending.get()
            if reason is None:  # stop sentinel
                return
            try:
                self.capture_now(reason, slo_entry)
            except Exception:
                logger.exception("incident capture failed (trigger=%s)",
                                 reason)
                # a FAILED capture must not consume the cooldown: the
                # stamp was taken at trigger time (dedup while this
                # bundle was in flight), but a transient write failure
                # (disk full, dir deleted) would otherwise blind the
                # capture plane for the whole cooldown while the
                # incident's ring evidence ages out — roll it back so
                # the next breached evaluation retries
                with self._lock:
                    self._last_capture.pop(reason, None)
            finally:
                with self._lock:
                    self._queued.discard(reason)

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the worker thread (pending captures drain first)."""
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            self._pending.put((None, None))
            t.join(timeout=timeout)

    # -- bundle building ----------------------------------------------------
    def _pull_instance(self, url: str) -> Dict[str, Any]:
        import urllib.request

        from incubator_predictionio_tpu.obs import trace as obs_trace

        req = urllib.request.Request(
            url, headers=dict(obs_trace.client_headers()))
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _instances(self) -> Tuple[str, Dict[str, Any]]:
        """(scope, {instance: recorder dump | {"error": ...}}) —
        fleet-first like /federate, per-instance degradation, local
        recorder otherwise. Pulls fan out concurrently: the capture
        wall is bounded by the SLOWEST worker, not the sum — during an
        incident (when workers ARE slow or down) a sequential walk
        would freeze the last instances' windows tens of seconds
        staler than the first."""
        targets = list(self._targets_fn() or ())
        if targets:
            import concurrent.futures

            out: Dict[str, Any] = {}
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(len(targets), 8),
                    thread_name_prefix="pio-incident-pull") as pool:
                futs = {t.instance: pool.submit(
                    self._pull_instance, _recorder_url(t.url))
                    for t in targets}
                for instance, fut in futs.items():
                    try:
                        out[instance] = fut.result()
                    except Exception as e:  # noqa: BLE001 — per worker
                        out[instance] = {"error": str(e)}
            return "fleet", out
        rec = self._recorder if self._recorder is not None \
            else get_recorder()
        if rec is None:
            return "process", {"local": {
                "error": "recorder disabled (PIO_RECORDER=0)"}}
        return "process", {"local": rec.dump(window_s=self.window_s)}

    @staticmethod
    def _breach_exemplars(instances: Dict[str, Any],
                          metric: Optional[str],
                          threshold: Optional[float]) -> Dict[str, Any]:
        """The breaching histogram's exemplar trace IDs across the
        pulled instances: above-threshold buckets first (those ARE the
        p99 queries), everything else as context."""
        above: List[Dict[str, Any]] = []
        below: List[Dict[str, Any]] = []
        for inst, dump in instances.items():
            for ex in (dump.get("exemplars") or []):
                if metric is not None and ex.get("metric") != metric:
                    continue
                rec = dict(ex)
                rec["instance"] = inst
                le = rec.get("le")
                le_f = math.inf if le == "+Inf" else float(le)
                if threshold is not None and le_f > threshold:
                    above.append(rec)
                else:
                    below.append(rec)
        return {
            "metric": metric,
            "traceIds": sorted({e["traceId"] for e in above}
                               or {e["traceId"] for e in below}),
            "aboveThreshold": above,
            "others": below,
        }

    def capture_now(self, reason: str,
                    slo_entry: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """Build + write one bundle synchronously (the worker thread's
        body; also ``POST /incident``'s). Returns ``{"id", "path"}``."""
        wall = self._wall()
        scope, instances = self._instances()
        metric = threshold = None
        if slo_entry is not None:
            metric = slo_entry.get("objective", {}).get("metric")
            threshold = slo_entry.get("objective", {}).get(
                "thresholdSeconds")
        decisions = []
        try:
            decisions = list(self.decisions_fn() or [])
        except Exception:
            logger.exception("incident capture: decision ring "
                             "unavailable")
        in_window = [d for d in decisions
                     if isinstance(d.get("ts"), (int, float))
                     and d["ts"] >= wall - self.window_s]
        knob_decisions = []
        try:
            knob_decisions = list(self.knobs_fn() or [])
        except Exception:
            logger.exception("incident capture: knob ring unavailable")
        knobs_in_window = [d for d in knob_decisions
                           if isinstance(d.get("ts"), (int, float))
                           and d["ts"] >= wall - self.window_s]
        tenants_block = None
        try:
            tenants_block = self.tenants_fn()
        except Exception:
            logger.exception("incident capture: tenant block "
                             "unavailable")
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(wall))
        inc_id = f"inc-{stamp}-{reason}"
        # the stamp has second resolution: two captures of one trigger
        # inside a second (double-POSTed /incident) must land as TWO
        # artifacts, never a silent os.replace clobber of the first
        os.makedirs(self.directory, exist_ok=True)
        n = 2
        while os.path.exists(os.path.join(self.directory,
                                          f"{inc_id}.json")):
            inc_id = f"inc-{stamp}-{reason}-{n}"
            n += 1
        bundle = {
            "schema": "pio-incident-v1",
            "id": inc_id,
            "ts": round(wall, 3),
            "trigger": reason,
            "scope": scope,
            "windowS": self.window_s,
            "slo": slo_entry,
            "recorder": {"instances": instances},
            "exemplars": self._breach_exemplars(
                {k: v for k, v in instances.items()
                 if isinstance(v, dict) and "error" not in v},
                metric, threshold),
            "decisions": in_window,
            "decisionsTotal": len(decisions),
            # the knob controller's audit trail (obs/knobs.py): what
            # the self-tuner did in the pre-breach window — the first
            # thing to read when a rollback fired
            "knobs": knobs_in_window,
            "knobsTotal": len(knob_decisions),
            # per-tenant registry policy + SLO entries at capture time
            # (serving/tenancy.py) — None in single-tenant mode
            "tenants": tenants_block,
        }
        path = os.path.join(self.directory, f"{inc_id}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, separators=(",", ":"))
        os.replace(tmp, path)  # readers never see a torn bundle
        self._incidents_total.labels(trigger=reason).inc()
        logger.warning("incident bundle captured: %s (trigger=%s, "
                       "scope=%s)", path, reason, scope)
        return {"id": inc_id, "path": path}

    # -- listing ------------------------------------------------------------
    def list_incidents(self) -> List[Dict[str, Any]]:
        """Newest-first bundle summaries from the incident directory."""
        out: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(self.directory), reverse=True)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("inc-") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            entry: Dict[str, Any] = {"id": name[:-5], "file": name}
            try:
                entry["bytes"] = os.path.getsize(path)
                with open(path, encoding="utf-8") as f:
                    meta = json.load(f)
                entry.update({
                    "ts": meta.get("ts"),
                    "trigger": meta.get("trigger"),
                    "scope": meta.get("scope"),
                    "instances": sorted(
                        (meta.get("recorder") or {})
                        .get("instances", {})),
                    "exemplarTraceIds": (meta.get("exemplars") or {})
                    .get("traceIds", []),
                })
            except Exception as e:  # noqa: BLE001 — a corrupt bundle lists
                entry["error"] = str(e)
            out.append(entry)
        return out

    def read_incident(self, inc_id: str) -> Optional[Dict[str, Any]]:
        if "/" in inc_id or "\\" in inc_id or ".." in inc_id:
            return None
        path = os.path.join(self.directory, f"{inc_id}.json")
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except OSError:
            return None


# ---------------------------------------------------------------------------
# process-wide singletons (every server shares one recorder; capture
# engages only when PIO_INCIDENT_DIR names a destination)
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_capture: Optional[IncidentCapture] = None
_singleton_lock = threading.Lock()


def get_recorder() -> Optional[FlightRecorder]:
    """The process recorder, started on first use; None when
    ``PIO_RECORDER=0`` (no thread exists — the off position is free)."""
    global _recorder
    if not recorder_enabled():
        return None
    with _singleton_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
            _recorder.start()
        return _recorder


def get_capture() -> Optional[IncidentCapture]:
    """The process capture engine, breach-hooked to the SLO plane on
    first use; None when ``PIO_INCIDENT_DIR`` is unset. Fleet-first
    like the controller: with ``PIO_FLEET_TARGETS`` configured the
    fleet burn engine's breaches trigger (and the bundle pulls every
    worker's ``/recorder``); the process engine's breaches always
    trigger, so a lone worker still captures its own incidents."""
    global _capture
    if incident_dir() is None:
        return None
    # resolved BEFORE taking the singleton lock (get_recorder takes it
    # too, and the lock is deliberately not reentrant)
    rec = get_recorder()
    with _singleton_lock:
        if _capture is None:
            from incubator_predictionio_tpu.obs import slo as obs_slo

            capture = IncidentCapture(recorder=rec)
            engines = [obs_slo.get_engine()]
            if os.environ.get("PIO_FLEET_TARGETS", "").strip():
                from incubator_predictionio_tpu.obs import federate

                engines.append(federate.fleet_slo_engine())
            capture.install(*engines)
            _capture = capture
        return _capture


def reset_recorder() -> None:
    """Drop (and stop) the process recorder + capture — tests re-read
    the PIO_RECORDER*/PIO_INCIDENT_* env on next use."""
    global _recorder, _capture
    with _singleton_lock:
        if _recorder is not None:
            _recorder.stop(timeout=2.0)
        if _capture is not None:
            _capture.stop(timeout=2.0)
        _recorder = None
        _capture = None


__all__ = [
    "FlightRecorder", "IncidentCapture", "collect_state", "get_capture",
    "get_recorder", "incident_cooldown_s", "incident_dir",
    "recorder_enabled", "recorder_hz", "recorder_window_s",
    "register_state_provider", "reset_recorder",
    "unregister_state_provider",
]
