"""Process-wide metrics registry with Prometheus text exposition.

Dependency-free (like the HTTP layer it rides on) and built for the
serving hot path: an observation is one uncontended ``threading.Lock``
acquire plus a few int adds — no allocation after the child exists, no
host syncs, no device interaction of any kind. Metrics must NEVER be
mutated from inside ``jit``/``pjit``/``pallas_call``-traced code (a
host callback there would serialize the device); the ``metric-in-trace``
pio-lint rule enforces this repo-wide.

The module-level :data:`REGISTRY` is the process-wide default every
server and subsystem registers into, so one ``GET /metrics`` scrape
sees the whole process. Fresh :class:`Registry` instances exist for
tests.

Label cardinality discipline: label values must come from BOUNDED sets
(route patterns, status codes, phase names) — never ids, entity names
or other wire-derived strings.
"""

from __future__ import annotations

import bisect
import logging
import math
import os
import random
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

#: exposition content type (Prometheus text format 0.0.4)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: fixed exponential latency buckets: 6.25 µs doubling to ~13.1 s — wide
#: enough to hold a sub-millisecond device fold-in solve at the bottom
#: (the original 100 µs floor dumped every sub-ms solve into one bucket,
#: flattening their quantiles) and a cold XLA compile on the first query
#: at the top, with p50/p95/p99 derivable anywhere in between. The
#: >=100 µs bounds are unchanged, so dashboards keyed on the old ladder
#: keep lining up. Shared by every latency histogram so panels align.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (2.0 ** i) for i in range(-4, 18)
)


def _fmt(v: float) -> str:
    """Prometheus sample value: ints render bare, floats via repr."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        # Prometheus's explicit no-data sample value (the controller's
        # projection gauge goes NaN when no driving signal projects) —
        # int() on it would raise and take down the whole scrape
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# ---------------------------------------------------------------------------
# trace exemplars (OpenMetrics-style) — the "WHICH query was the p99"
# link between a histogram bucket and the distributed-tracing plane.
# Each bucket keeps at most ONE reservoir-sampled exemplar per
# PIO_EXEMPLAR_WINDOW_S window: (ambient trace ID, observed value, wall
# ts), emitted as a `# {trace_id="..."} value ts` suffix on the bucket's
# exposition line. Hot-path cost when no ambient trace exists is one
# contextvar read; PIO_EXEMPLARS=0 turns even that off.
# ---------------------------------------------------------------------------

#: reservoir RNG — module-level and reseedable so tests can pin which
#: observation survives a window (tests/test_recorder.py determinism)
_exemplar_rng = random.Random()


def seed_exemplar_rng(seed: int) -> None:
    """Reseed the exemplar reservoir (tests only — determinism pins)."""
    _exemplar_rng.seed(seed)


#: parsed PIO_EXEMPLARS cache keyed on the raw env string (same idiom as
#: obs/trace.sample_rate: live-retunable, no per-observe dict churn)
_exemplar_cache: Tuple[Optional[str], bool] = ("\0unset", True)


def exemplars_enabled() -> bool:
    global _exemplar_cache
    raw = os.environ.get("PIO_EXEMPLARS")
    cached_raw, cached = _exemplar_cache
    if raw == cached_raw:
        return cached
    enabled = (raw or "1").strip().lower() not in ("0", "off", "false")
    _exemplar_cache = (raw, enabled)
    return enabled


#: parsed PIO_EXEMPLAR_WINDOW_S cache keyed on the raw env string —
#: observe() reads this under the histogram child lock, so the steady
#: state must pay one string compare, not an env parse
_exemplar_window_cache: Tuple[Optional[str], float] = ("\0unset", 60.0)


def exemplar_window_s() -> float:
    """Reservoir window: at most one exemplar survives per bucket per
    window, so a sustained burst cannot pin one early trace forever."""
    global _exemplar_window_cache
    raw = os.environ.get("PIO_EXEMPLAR_WINDOW_S")
    cached_raw, cached = _exemplar_window_cache
    if raw == cached_raw:
        return cached
    try:
        window = float(raw) if raw else 60.0
    except ValueError:
        window = 60.0
    _exemplar_window_cache = (raw, window)
    return window


def _ambient_trace_id() -> Optional[str]:
    """The ambient request's trace ID, imported lazily — obs.trace has
    no import back into this module, but the late bind keeps metrics
    importable absolutely first."""
    from incubator_predictionio_tpu.obs import trace as obs_trace

    return obs_trace.current_trace_id()


def format_exemplar(trace_id: str, value: float, ts: float) -> str:
    """The OpenMetrics exemplar annotation this registry emits (and
    obs/expofmt.py parses back): ``# {trace_id="..."} value ts``."""
    return (f'# {{trace_id="{_escape_label(trace_id)}"}} '
            f"{_fmt(value)} {ts:.3f}")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class _CounterChild:
    """One labeled time series of a Counter. ``inc`` is the hot path."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild:
    # _touched distinguishes "never written" from "set to 0.0" — the
    # SLO engine must not count a registered-but-unpopulated gauge as a
    # healthy observation (obs/slo.py gauge objectives)
    __slots__ = ("_lock", "_value", "_touched")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._touched = False

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self._touched = True

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self._touched = True

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n
            self._touched = True

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild:
    """Fixed-bucket histogram: per-bucket counts + sum + count.

    ``observe(v, n)`` records ``n`` observations of the same value in
    one lock acquire — the micro-batched serving path uses it to keep
    per-query semantics (every query in a fused batch took the batch
    wall) at per-BATCH bookkeeping cost.
    """

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count",
                 "_ex", "_ex_seen", "_ex_win")

    def __init__(self, bounds: Sequence[float]) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(bounds)  # upper bounds, ascending
        self._counts = [0] * (len(self._bounds) + 1)  # + overflow
        self._sum = 0.0
        self._count = 0
        #: per-bucket exemplar (trace_id, value, wall_ts) or None
        self._ex: List[Optional[Tuple[str, float, float]]] = \
            [None] * (len(self._bounds) + 1)
        #: traced observations seen in the bucket's CURRENT window (the
        #: reservoir denominator) + that window's start wall
        self._ex_seen = [0] * (len(self._bounds) + 1)
        self._ex_win = [0.0] * (len(self._bounds) + 1)

    def observe(self, v: float, n: int = 1) -> None:
        i = bisect.bisect_left(self._bounds, v)
        trace_id = (_ambient_trace_id() if exemplars_enabled() else None)
        with self._lock:
            self._counts[i] += n
            self._sum += v * n
            self._count += n
            if trace_id is not None:
                # ≤1 exemplar per bucket per window, reservoir-sampled:
                # every traced observation in the window has an equal
                # chance of being THE exemplar, so the survivor is a
                # fair draw rather than first- or last-wins
                now = time.time()
                if now - self._ex_win[i] >= exemplar_window_s():
                    self._ex_win[i] = now
                    self._ex_seen[i] = 0
                self._ex_seen[i] += 1
                if (self._ex[i] is None
                        or self._ex[i][2] < self._ex_win[i]
                        or _exemplar_rng.random()
                        < 1.0 / self._ex_seen[i]):
                    self._ex[i] = (trace_id, v, now)

    def exemplars(self) -> List[Tuple[float, str, float, float]]:
        """``(le bound, trace_id, value, wall_ts)`` for every bucket
        holding an exemplar (+Inf rendered as math.inf) — the incident
        bundle's "which queries were the p99" payload."""
        with self._lock:
            snap = list(self._ex)
        out: List[Tuple[float, str, float, float]] = []
        for i, ex in enumerate(snap):
            if ex is None:
                continue
            le = (self._bounds[i] if i < len(self._bounds)
                  else float("inf"))
            out.append((le, ex[0], ex[1], ex[2]))
        return out

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. overflow, sum, count) — consistent."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def quantile(self, q: float) -> Optional[float]:
        """Derive a quantile from the buckets (linear interpolation
        within the bucket, Prometheus ``histogram_quantile`` style).
        None when empty; values past the last finite bound report that
        bound (the honest answer a fixed-bucket histogram can give)."""
        counts, _sum, total = self.snapshot()
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self._bounds):  # overflow bucket
                    return self._bounds[-1]
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                return lo + (hi - lo) * max(rank - cum, 0.0) / c
            cum += c
        return self._bounds[-1]


_KINDS = {
    "counter": _CounterChild,
    "gauge": _GaugeChild,
    "histogram": _HistogramChild,
}


class _Metric:
    """One named metric family: fixed label names, children per label
    value tuple. Unlabeled metrics have a single implicit child."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(
            buckets if buckets is not None else DEFAULT_LATENCY_BUCKETS)
        if kind == "histogram" and list(self._buckets) != sorted(
                set(self._buckets)):
            raise ValueError("histogram buckets must be sorted and unique")
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self._buckets)
        return _KINDS[self.kind]()

    def labels(self, **labels: str):
        """Child for one label-value combination (created on first use,
        cached — the hot path pays one dict lookup)."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    # unlabeled convenience: metric.inc()/set()/observe() hit the child
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self._children[()]

    def inc(self, n: float = 1.0) -> None:
        self._solo().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._solo().dec(n)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def observe(self, v: float, n: int = 1) -> None:
        self._solo().observe(v, n)

    @property
    def value(self):
        return self._solo().value

    # Family-level histogram READS aggregate over children: when a
    # family gains a label (pio_query_latency_seconds grew ``tenant``
    # for the multi-tenant platform), every read-side consumer of the
    # whole family — /status quantiles, the scheduler's live-p99 shed
    # feed, cross-process count asserts — keeps meaning "the family",
    # not one child. WRITES on a labeled family still raise via
    # ``_solo``: an observation must always name its child.
    @property
    def sum(self):
        if self.labelnames and self.kind == "histogram":
            with self._lock:
                children = list(self._children.values())
            return sum(c.sum for c in children)
        return self._solo().sum

    @property
    def count(self):
        if self.labelnames and self.kind == "histogram":
            with self._lock:
                children = list(self._children.values())
            return sum(c.count for c in children)
        return self._solo().count

    def quantile(self, q: float):
        if self.labelnames and self.kind == "histogram":
            return self.quantile_over_children(q)
        return self._solo().quantile(q)

    def total(self) -> float:
        """Sum over every labeled child (counter/gauge families) — the
        bench's registry snapshot collapses label sets with this."""
        if self.kind == "histogram":
            raise ValueError("total() is for counter/gauge; use sum/count")
        with self._lock:
            children = list(self._children.values())
        return sum(c.value for c in children)

    def max_value(self) -> float:
        """Max over every labeled child (counter/gauge families) — the
        worst-of reading gauge SLOs evaluate (obs/slo.py): on a fleet-
        federated registry the stalest worker governs, and on the
        single-child process gauge this equals the value. Children
        never written don't vote (a registered-but-unset gauge must
        not read as a healthy 0)."""
        if self.kind == "histogram":
            raise ValueError("max_value() is for counter/gauge")
        with self._lock:
            children = list(self._children.values())
        written = [c.value for c in children
                   if getattr(c, "_touched", True)]
        return max(written) if written else 0.0

    def has_samples(self) -> bool:
        """Gauge families: True when any child was ever written.
        Registration alone creates a 0.0-valued child, and a consumer
        deciding health from the value (the staleness SLO) must be able
        to tell "never populated" from "genuinely zero"."""
        if self.kind != "gauge":
            raise ValueError("has_samples() is for gauges")
        with self._lock:
            children = list(self._children.values())
        return any(c._touched for c in children)

    def cumulative_below(
            self, bound: float,
            labels: Optional[Dict[str, str]] = None) -> Tuple[int, int]:
        """Histogram families only: ``(observations <= the largest bucket
        bound <= ``bound``, total observations)`` summed over every
        labeled child. The SLO engine's good/bad split reads this — a
        threshold between bucket bounds rounds DOWN to the next bound, so
        the good count is never overstated (an SLO can flag early, never
        late). ``labels`` restricts the sum to children matching every
        given label value — per-tenant SLO specs (obs/slo.py) evaluate
        ``{"tenant": <id>}`` slices of the shared latency family."""
        if self.kind != "histogram":
            raise ValueError("cumulative_below() is for histograms")
        # number of bucket counts at bounds <= bound (bisect_right: an
        # exact bound match includes its own le bucket)
        k = bisect.bisect_right(self._buckets, bound)
        with self._lock:
            if labels:
                if any(ln not in self.labelnames for ln in labels):
                    # an unlabeled (or differently-labeled) declaration
                    # of the family has no matching slice — report NO
                    # DATA (0, 0), never a crash: a per-tenant SLO spec
                    # must degrade cleanly on a pre-tenancy process
                    return 0, 0
                idx = [self.labelnames.index(ln) for ln in labels]
                want = [str(labels[ln]) for ln in labels]
                children = [
                    c for key, c in self._children.items()
                    if all(key[i] == w for i, w in zip(idx, want))
                ]
            else:
                children = list(self._children.values())
        below = total = 0
        for child in children:
            counts, _sum, count = child.snapshot()
            below += sum(counts[:k])
            total += count
        return below, total

    def quantile_over_children(self, q: float) -> Optional[float]:
        """Histogram families only: one quantile over the SUM of every
        labeled child's buckets (the dashboard's cross-engine panels
        collapse the ``engine`` label with this). None when empty."""
        if self.kind != "histogram":
            raise ValueError("quantile_over_children() is for histograms")
        with self._lock:
            children = list(self._children.values())
        if not children:
            return None
        merged = _HistogramChild(self._buckets)
        for child in children:
            counts, csum, count = child.snapshot()
            for i, c in enumerate(counts):
                merged._counts[i] += c
            merged._sum += csum
            merged._count += count
        return merged.quantile(q)

    def exemplars(self) -> List[Dict]:
        """Histogram families only: every child's current exemplars as
        JSON-ready dicts (the flight recorder's full-dump block and the
        incident bundle's trace links read this)."""
        if self.kind != "histogram":
            raise ValueError("exemplars() is for histograms")
        with self._lock:
            items = sorted(self._children.items())
        out: List[Dict] = []
        for key, child in items:
            for le, tid, v, ts in child.exemplars():
                out.append({
                    "labels": dict(zip(self.labelnames, key)),
                    "le": ("+Inf" if math.isinf(le) else le),
                    "traceId": tid,
                    "value": v,
                    "ts": round(ts, 3),
                })
        return out

    # -- exposition ---------------------------------------------------------
    def _label_str(self, key: Tuple[str, ...],
                   extra: str = "") -> str:
        parts = [f'{ln}="{_escape_label(lv)}"'
                 for ln, lv in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def expose_into(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            if self.kind in ("counter", "gauge"):
                out.append(
                    f"{self.name}{self._label_str(key)} "
                    f"{_fmt(child.value)}")
            else:
                counts, total_sum, total = child.snapshot()
                # exemplar annotations ride the bucket lines they
                # belong to (OpenMetrics syntax; docs/observability.md)
                ex_by_le = {le: (tid, v, ts)
                            for le, tid, v, ts in child.exemplars()}
                cum = 0
                for bound, c in zip(self._buckets, counts):
                    cum += c
                    le = 'le="' + _fmt(bound) + '"'
                    line = (f"{self.name}_bucket"
                            f"{self._label_str(key, le)} {cum}")
                    ex = ex_by_le.get(bound)
                    if ex is not None:
                        line += " " + format_exemplar(*ex)
                    out.append(line)
                inf = 'le="+Inf"'
                line = (f"{self.name}_bucket"
                        f"{self._label_str(key, inf)} {total}")
                ex = ex_by_le.get(float("inf"))
                if ex is not None:
                    line += " " + format_exemplar(*ex)
                out.append(line)
                out.append(
                    f"{self.name}_sum{self._label_str(key)} "
                    f"{_fmt(total_sum)}")
                out.append(
                    f"{self.name}_count{self._label_str(key)} {total}")


Counter = Gauge = Histogram = _Metric  # type aliases for annotations


class Registry:
    """Named metrics + scrape-time collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create: the second
    registration of a name returns the SAME metric (servers restart
    inside one test process), but a kind or label-set mismatch raises —
    two subsystems silently sharing a misdeclared series is how scrapes
    lie. Collectors are named callbacks run at scrape time, for state
    that lives elsewhere (native counters, queue depths): registering
    the same name again replaces the old callback, so re-created
    backends never accumulate dead hooks.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: Dict[str, Callable[[], None]] = {}

    def _get_or_create(self, name: str, help: str, kind: str,
                       labels: Sequence[str],
                       buckets: Optional[Sequence[float]] = None) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != tuple(
                        labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}")
                if (kind == "histogram" and buckets is not None
                        and tuple(buckets) != existing._buckets):
                    # two subsystems binning one series by different
                    # bounds would silently produce lying quantiles
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {existing._buckets}")
                return existing
            m = _Metric(name, help, kind, labels, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str, labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def register_collector(self, name: str,
                           fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def run_collectors(self) -> None:
        """Run the scrape-time collectors without rendering (the
        freshness controller reads collector-fed gauges — model
        staleness, queue depth — between scrapes; a failing collector
        logs and is skipped, same contract as ``expose``)."""
        with self._lock:
            collectors = list(self._collectors.items())
        for cname, fn in collectors:
            try:
                fn()
            except Exception:
                logger.exception("metrics collector %r failed", cname)

    def expose(self) -> str:
        """Prometheus text exposition of every metric, after running
        the collectors (a failing collector logs and is skipped — a
        broken bridge must never take down the scrape)."""
        self.run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: List[str] = []
        for m in metrics:
            m.expose_into(out)
        return "\n".join(out) + "\n"


#: the process-wide default registry — one scrape sees the whole system
REGISTRY = Registry()
