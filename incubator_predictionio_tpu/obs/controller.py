"""Self-driving freshness — the SLO-burn-driven retrain/reload controller.

The reference PredictionIO makes model freshness a human operation: an
operator watches predictions go stale, re-runs ``pio train``, re-runs
``pio deploy``. This stack already measures everything that operator
looks at — the SLO burn-rate engine (obs/slo.py), fleet federation
(obs/federate.py), the staleness gauge, the speed layer's cursor lag —
and PR 13's front door already choreographs a zero-downtime rolling hot
swap. This module closes the loop (ROADMAP item 2):

- a background loop (hosted by the admin server) consumes the fleet (or
  process) ``/slo`` evaluation plus the raw ``pio_model_staleness_
  seconds`` / ``pio_speed_cursor_lag_events`` gauges through the same
  Registry-shaped protocol the burn engine uses;
- it **projects error-budget exhaustion**: burn-based time-to-empty
  from the fast/slow windows, plus the staleness gauge's direct
  headroom (staleness grows one second per second, so
  ``threshold − max_staleness`` IS the time left before the bound);
- on a projected (or actual) breach it triggers a continuation retrain
  (``CoreWorkflow.run_train`` — the ``prev_models`` continuation seam)
  followed by a rolling fleet hot swap through the front door's
  ``POST /reload`` choreography — with **hysteresis** (consecutive
  breached evaluations required), a **cooldown** after every action so
  it never flaps, a capacity **budget guard** (obs/capacity.py's
  measured rows/chip/s fit says whether a retrain can even finish
  inside the projected budget — when it can't, capacity, not
  freshness, is the binding constraint), a **dry-run mode** and a
  **kill switch** (``PIO_CONTROLLER=off|observe|act``, flippable live
  via ``POST /controller`` on the admin server).

The observability core: every evaluation emits a structured **decision
record** — inputs snapshot, projection math, action, outcome,
rejection reason — under its **own trace ID**. Actuation runs inside
that trace context, so the in-repo HTTP hops it causes (the front
door's ``/reload``, each worker's reload behind it, any storage RPCs
the retrain makes) forward ``X-PIO-Trace-Id``/``X-PIO-Parent-Span``
and ``scripts/trace_stitch.py --decisions`` reconstructs "burn spike →
decision → retrain → rolling swap → staleness recovered" as one tree.
``GET /controller`` serves the bounded decision ring + current state.

Exported series (docs/observability.md):

- ``pio_controller_evaluations_total``
- ``pio_controller_actions_total{reason}`` (reason = the trigger)
- ``pio_controller_skips_total{reason}`` (reason = why it held fire)
- ``pio_controller_state`` (0 off, 1 observe, 2 act)
- ``pio_controller_budget_projection_seconds`` (projected seconds to
  error-budget exhaustion; the staleness headroom when nothing burns)

Lint contract (``unaudited-actuation``): every call into the retrain /
reload actuators from this module must happen inside the decision-
record emitter (:meth:`FreshnessController._actuate`) — an actuation
without a decision record is an unauditable mutation of the fleet.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import secrets
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.utils import times

logger = logging.getLogger(__name__)

#: kill-switch modes, in escalation order
MODES = ("off", "observe", "act")

#: bounded reason enums — metric label values come from these sets only
#: (metric-label-cardinality contract)
SKIP_REASONS = ("off", "observe", "healthy", "no_data", "hysteresis",
                "cooldown", "budget", "no_actuator", "slo_error")
ACTION_REASONS = ("freshness_p95_burn", "staleness_burn",
                  "staleness_projection", "budget_projection")

_EVALUATIONS = obs_metrics.REGISTRY.counter(
    "pio_controller_evaluations_total",
    "freshness-controller evaluation passes (off-mode ticks excluded)")
_ACTIONS = obs_metrics.REGISTRY.counter(
    "pio_controller_actions_total",
    "autonomous retrain+reload actions by trigger reason",
    labels=("reason",))
_SKIPS = obs_metrics.REGISTRY.counter(
    "pio_controller_skips_total",
    "evaluations that did NOT actuate, by rejection reason",
    labels=("reason",))
_STATE = obs_metrics.REGISTRY.gauge(
    "pio_controller_state",
    "controller kill-switch state (0 off, 1 observe, 2 act)")
_PROJECTION = obs_metrics.REGISTRY.gauge(
    "pio_controller_budget_projection_seconds",
    "projected seconds until SLO error-budget exhaustion (min across "
    "the freshness/staleness drivers; staleness headroom when nothing "
    "is burning)")

#: the SLOs whose burn can justify a retrain. serve_p99 is consumed
#: into the inputs snapshot but never triggers: a retrain does not fix
#: serving latency, and acting on it would thrash the fleet for nothing
DRIVING_SLOS = ("freshness_p95", "staleness")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def controller_mode() -> str:
    """The env-declared kill-switch position (``PIO_CONTROLLER``),
    re-read per call so an operator can flip a live admin process via
    the environment too; POST /controller overrides it in-process."""
    raw = os.environ.get("PIO_CONTROLLER", "off").strip().lower()
    return raw if raw in MODES else "off"


@dataclasses.dataclass
class ControllerConfig:
    """Loop cadence + trigger policy. Every number has a
    ``PIO_CONTROLLER_*`` env default so the CLI admin server is
    configurable without code."""

    #: evaluation period — also the kill switch's reaction bound: a
    #: mode flip takes effect within one period
    interval_s: float = 30.0
    #: consecutive triggering evaluations required before acting (the
    #: hysteresis band — one noisy window must never retrain the fleet)
    breach_evals: int = 2
    #: wall after a completed action during which new triggers are
    #: skipped (reason="cooldown") — the anti-flap floor; it must
    #: comfortably exceed a retrain+swap wall
    cooldown_s: float = 600.0
    #: act when the projected budget-exhaustion / staleness headroom
    #: falls under this horizon (acting at zero headroom means the
    #: bound was already broken while the retrain runs)
    horizon_s: float = 900.0
    #: decision-record ring bound
    ring: int = 256

    @staticmethod
    def from_env() -> "ControllerConfig":
        return ControllerConfig(
            interval_s=_env_float("PIO_CONTROLLER_INTERVAL_S", 30.0),
            breach_evals=int(_env_float("PIO_CONTROLLER_HYSTERESIS", 2)),
            cooldown_s=_env_float("PIO_CONTROLLER_COOLDOWN_S", 600.0),
            horizon_s=_env_float("PIO_CONTROLLER_HORIZON_S", 900.0),
            ring=int(_env_float("PIO_CONTROLLER_RING", 256)),
        )


# ---------------------------------------------------------------------------
# actuator factories
# ---------------------------------------------------------------------------
#
# The controller never hard-codes HOW to retrain or reload — it takes
# two callables. These factories build the production pair. Their
# closures run only from inside the decision-record emitter
# (_actuate); the unaudited-actuation lint rule documents the *_fn
# naming convention as the sanctioned construction site.

def workflow_retrain_fn(engine: Any, engine_params: Any,
                        **run_train_kwargs: Any) -> Callable[[], str]:
    """Actuator that runs a CONTINUATION retrain through the core
    workflow: ``CoreWorkflow.run_train`` loads the previous COMPLETED
    instance's models as the ``prev_models`` seed (O(delta) splice +
    early-stop — ops/retrain.py), so the autonomous retrain pays the
    steady-state wall, not the cold one. Returns the new engine
    instance id."""

    def retrain() -> str:
        from incubator_predictionio_tpu.workflow.workflow import (
            CoreWorkflow,
        )

        return CoreWorkflow.run_train(engine, engine_params,
                                      **run_train_kwargs)

    return retrain


def http_reload_fn(url: str, server_key: Optional[str] = None,
                   timeout_s: float = 600.0) -> Callable[[], Dict]:
    """Actuator that POSTs the front door's ``/reload`` (the rolling
    drain → warm-before-swap → re-admit choreography,
    serving/frontdoor.py). The request carries the ambient trace
    headers, so the rolling swap's spans — front door and every worker
    behind it — land under the controller's decision trace."""
    if "://" not in url:
        url = f"http://{url}"
    if server_key:
        from urllib.parse import quote

        url = f"{url}?accessKey={quote(server_key, safe='')}"

    def reload() -> Dict:
        req = urllib.request.Request(
            url, data=b"", method="POST",
            headers=dict(obs_trace.client_headers()))
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    return reload


def capacity_budget_fn(rows: Optional[float] = None,
                       repo_dir: str = ".") -> Callable[
                           [], Optional[float]]:
    """Budget guard from the measured capacity fit (obs/capacity.py,
    ALX-style sizing): estimated continuation-retrain wall =
    rows / rows_per_chip_per_s over the newest non-degraded bench
    record. The fit is computed once at factory time (the trajectory is
    static per process); returns None — no guard — when the row count
    or the fit is unknown, because a fabricated wall would veto real
    retrains."""
    if rows is None:
        rows = _env_float("PIO_CONTROLLER_ROWS", 0.0) or None
    rate: Optional[float] = None
    if rows:
        try:
            from incubator_predictionio_tpu.obs import capacity

            fit = capacity.fit_capacity(
                capacity.load_trajectory(repo_dir))
            rate = fit.get("rows_per_chip_per_s")
        except Exception:
            logger.exception("capacity fit unavailable; controller "
                             "budget guard disabled")

    def estimate() -> Optional[float]:
        if rows and rate:
            return float(rows) / float(rate)
        return None

    return estimate


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class FreshnessController:
    """The burn-driven freshness loop. One instance per admin process;
    every evaluation appends a decision record, every actuation runs
    inside the decision's trace context."""

    def __init__(self,
                 engine: Optional[Any] = None,
                 retrain_fn: Optional[Callable[[], Any]] = None,
                 reload_fn: Optional[Callable[[], Any]] = None,
                 capacity_fn: Optional[Callable[[], Optional[float]]]
                 = None,
                 config: Optional[ControllerConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 mode: Optional[str] = None) -> None:
        self.config = config or ControllerConfig.from_env()
        self._clock = clock if clock is not None else times.monotonic
        self._engine = engine          # lazy-resolved when None
        self._retrain_fn = retrain_fn
        self._reload_fn = reload_fn
        self._capacity_fn = capacity_fn
        self._mode_override: Optional[str] = mode
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(self.config.ring), 1))
        self._streak = 0               # consecutive triggering evals
        self._cooldown_until = 0.0
        self._seq = 0
        self._actions = 0
        self._last_action: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- mode (the kill switch) ---------------------------------------------
    @property
    def mode(self) -> str:
        with self._lock:
            return self._mode_override or controller_mode()

    def set_mode(self, mode: str) -> str:
        """Live flip (POST /controller). Takes effect at the next
        evaluation — within one ``interval_s`` for the running loop.
        The flip itself lands in the decision ring: a kill switch whose
        use leaves no audit trail is half a kill switch."""
        mode = (mode or "").strip().lower()
        if mode not in MODES:
            raise ValueError(
                f"mode must be one of {MODES}, got {mode!r}")
        with self._lock:
            # inline (not the property): self._lock is not reentrant
            prev = self._mode_override or controller_mode()
            self._mode_override = mode
            self._seq += 1
            self._ring.append({
                "id": self._seq,
                "ts": round(time.time(), 3),
                "kind": "mode_change",
                "from": prev,
                "to": mode,
            })
        _STATE.set(float(MODES.index(mode)))
        logger.info("freshness controller mode: %s -> %s", prev, mode)
        return mode

    # -- signal resolution --------------------------------------------------
    def _resolve_engine(self) -> Any:
        """Default signal source: the fleet SLO engine when
        ``PIO_FLEET_TARGETS`` names a fleet, else this process's own
        burn engine — same objectives, same math either way."""
        if self._engine is None:
            from incubator_predictionio_tpu.obs import federate
            from incubator_predictionio_tpu.obs import slo as obs_slo

            if os.environ.get("PIO_FLEET_TARGETS", "").strip():
                self._engine = federate.fleet_slo_engine()
            else:
                self._engine = obs_slo.get_engine()
        return self._engine

    def _gauge_reading(self, registry: Any, name: str) -> Optional[float]:
        """Worst-of (max) reading of a gauge family — over children in
        process mode, over instances AND children through the federated
        registry. None = no data."""
        try:
            m = registry.get(name)
        except Exception:
            return None
        if m is None or m.kind != "gauge" or not m.has_samples():
            return None
        return float(m.max_value())

    # -- projection math ----------------------------------------------------
    def _project(self, engine: Any, slos: List[Dict],
                 staleness_max: Optional[float]) -> Dict[str, Any]:
        """Error-budget exhaustion projection.

        Burn-based: a budget with fraction R remaining over the slow
        window W, burning at the FAST window's rate B, empties in
        ``W · R / B`` seconds (B ≤ 0 means it is refilling — no
        exhaustion). Staleness additionally projects directly: the
        gauge grows one second per second, so ``threshold − value`` is
        the exact headroom before the bound — this is what lets the
        controller act BEFORE the gauge SLO ever records a bad tick."""
        slow_w = float(getattr(engine, "slow_window_s", 3600.0))
        burn_exhaust: Optional[float] = None
        for s in slos:
            if s["name"] not in DRIVING_SLOS or s["noData"]:
                continue
            fast = float(s["windows"]["fast"]["burnRate"])
            remaining = float(s["errorBudgetRemaining"])
            if fast > 0.0:
                t = slow_w * remaining / fast
                if burn_exhaust is None or t < burn_exhaust:
                    burn_exhaust = t
        headroom: Optional[float] = None
        threshold = None
        for s in slos:
            if s["name"] == "staleness":
                threshold = float(s["objective"]["thresholdSeconds"])
        if threshold is not None and staleness_max is not None:
            headroom = max(threshold - staleness_max, 0.0)
        candidates = [t for t in (burn_exhaust, headroom)
                      if t is not None]
        projection = min(candidates) if candidates else None
        return {
            "slowWindowS": slow_w,
            "burnExhaustS": (round(burn_exhaust, 3)
                             if burn_exhaust is not None else None),
            "stalenessHeadroomS": (round(headroom, 3)
                                   if headroom is not None else None),
            "stalenessThresholdS": threshold,
            "projectionS": (round(projection, 3)
                            if projection is not None else None),
            "horizonS": self.config.horizon_s,
        }

    # -- one evaluation -----------------------------------------------------
    def evaluate_once(self) -> Optional[Dict[str, Any]]:
        """One controller pass: consume signals, project, decide,
        possibly actuate. Returns the appended decision record (None
        only in off mode — the kill switch halts evaluation entirely,
        so a disabled controller costs the fleet zero scrapes)."""
        mode = self.mode
        _STATE.set(float(MODES.index(mode)))
        if mode == "off":
            return None
        _EVALUATIONS.inc()
        now = self._clock()
        with self._lock:
            self._seq += 1
            decision: Dict[str, Any] = {
                "id": self._seq,
                "traceId": f"ctl-{secrets.token_hex(6)}",
                "ts": round(time.time(), 3),
                "kind": "evaluation",
                "mode": mode,
                "inputs": None,
                "projection": None,
                "action": "none",
                "reason": None,
                "outcome": None,
                # pre-seeded so _actuate's fill-in replaces values
                # without resizing a dict a concurrent GET /controller
                # may be rendering
                "spanId": None,
            }

        try:
            engine = self._resolve_engine()
            registry = engine.registry
            if hasattr(registry, "run_collectors"):
                # collector-fed gauges (model staleness, queue depth)
                # are normally refreshed at scrape time; the controller
                # reads BETWEEN scrapes, so refresh them itself (the
                # federated registry refreshes by re-scraping instead)
                registry.run_collectors()
            slos = engine.evaluate()
        except Exception as e:  # fleet down ≠ controller crash
            logger.warning("controller signal read failed: %s", e)
            decision["reason"] = "slo_error"
            decision["error"] = str(e)
            _SKIPS.labels(reason="slo_error").inc()
            # a blind evaluation breaks the CONSECUTIVE-breach chain
            # (hysteresis must never count across a gap it could not
            # see), and the projection gauge goes honestly no-data
            # instead of freezing at its last pre-outage value
            with self._lock:
                self._streak = 0
            _PROJECTION.set(float("nan"))
            self._append(decision)
            return decision

        staleness_max = self._gauge_reading(
            registry, "pio_model_staleness_seconds")
        cursor_lag = self._gauge_reading(
            registry, "pio_speed_cursor_lag_events")
        decision["inputs"] = {
            "slos": {
                s["name"]: {
                    "noData": s["noData"],
                    "fastBurn": s["windows"]["fast"]["burnRate"],
                    "slowBurn": s["windows"]["slow"]["burnRate"],
                    "budgetRemaining": s["errorBudgetRemaining"],
                } for s in slos
            },
            "stalenessMaxS": staleness_max,
            "cursorLagEvents": cursor_lag,
        }
        projection = self._project(engine, slos, staleness_max)
        decision["projection"] = projection
        # NaN when nothing projects (no driving signal): a frozen
        # last-known headroom on a dashboard would read as live health
        _PROJECTION.set(projection["projectionS"]
                        if projection["projectionS"] is not None
                        else float("nan"))

        # -- trigger rule ---------------------------------------------------
        trigger: Optional[str] = None
        driving = [s for s in slos if s["name"] in DRIVING_SLOS]
        for s in driving:
            if not s["noData"] and \
                    float(s["windows"]["fast"]["burnRate"]) > 1.0:
                trigger = f"{s['name']}_burn"
                break
        if trigger is None and projection["projectionS"] is not None \
                and projection["projectionS"] <= self.config.horizon_s:
            trigger = ("staleness_projection"
                       if projection["stalenessHeadroomS"] is not None
                       and projection["stalenessHeadroomS"]
                       == projection["projectionS"]
                       else "budget_projection")
        if all(s["noData"] for s in driving) and trigger is None:
            decision["reason"] = "no_data"
            _SKIPS.labels(reason="no_data").inc()
            with self._lock:
                self._streak = 0
            self._append(decision)
            return decision
        if trigger is None:
            decision["reason"] = "healthy"
            _SKIPS.labels(reason="healthy").inc()
            with self._lock:
                self._streak = 0
            self._append(decision)
            return decision

        # -- hysteresis / cooldown / budget / mode gates --------------------
        decision["trigger"] = trigger
        with self._lock:
            self._streak += 1
            streak = self._streak
        decision["streak"] = streak
        if streak < self.config.breach_evals:
            decision["reason"] = "hysteresis"
            _SKIPS.labels(reason="hysteresis").inc()
            self._append(decision)
            return decision
        with self._lock:
            cooldown_until = self._cooldown_until
        if now < cooldown_until:
            decision["reason"] = "cooldown"
            decision["cooldownRemainingS"] = round(
                cooldown_until - now, 3)
            _SKIPS.labels(reason="cooldown").inc()
            self._append(decision)
            return decision
        retrain_wall = None
        if self._capacity_fn is not None:
            try:
                retrain_wall = self._capacity_fn()
            except Exception:
                logger.exception("controller capacity guard failed "
                                 "(treated as no guard)")
        projection["retrainWallEstS"] = (
            round(retrain_wall, 3) if retrain_wall is not None else None)
        if retrain_wall is not None \
                and projection["projectionS"] is not None \
                and retrain_wall > projection["projectionS"]:
            # the measured capacity fit says a retrain cannot complete
            # before the budget empties: capacity, not freshness, is
            # the binding constraint (runbook: add chips, the
            # controller cannot retrain its way out)
            decision["reason"] = "budget"
            _SKIPS.labels(reason="budget").inc()
            self._append(decision)
            return decision
        if mode == "observe":
            decision["action"] = "retrain+reload"
            decision["reason"] = "observe"
            decision["outcome"] = {"actuated": False,
                                   "dryRun": True}
            _SKIPS.labels(reason="observe").inc()
            self._append(decision)
            return decision
        if self._retrain_fn is None and self._reload_fn is None:
            decision["reason"] = "no_actuator"
            _SKIPS.labels(reason="no_actuator").inc()
            self._append(decision)
            return decision

        # -- act ------------------------------------------------------------
        decision["action"] = "retrain+reload"
        decision["reason"] = trigger
        # the record lands in the ring BEFORE actuation (marked
        # in-flight) and is updated in place on completion: a retrain
        # takes minutes, and the runbook's "the ring IS the answer"
        # promise must hold for the operator watching GET /controller
        # exactly while the action runs
        decision["outcome"] = {"actuated": True, "inFlight": True}
        _ACTIONS.labels(reason=trigger).inc()
        with self._lock:
            self._actions += 1
            self._last_action = decision
        self._append(decision)
        self._actuate(decision)
        # cooldown counts from actuation COMPLETION: a long retrain
        # must not eat its own cooldown
        with self._lock:
            self._streak = 0
            self._cooldown_until = self._clock() + self.config.cooldown_s
        return decision

    # -- the decision-record emitter (the ONE sanctioned actuation site) ----
    def _actuate(self, decision: Dict[str, Any]) -> None:
        """Run retrain → rolling reload inside the decision's trace
        context and write the outcome into the record. Every in-repo
        HTTP hop below (front-door /reload, worker reloads, storage
        RPCs) forwards the decision's trace ID, so the stitcher joins
        the whole actuation under this decision span. The
        unaudited-actuation lint rule pins that actuator calls happen
        here and nowhere else in this module."""
        span_id = obs_trace.new_span_id()
        decision["spanId"] = span_id
        token = obs_trace.set_current(decision["traceId"])
        span_token = obs_trace.set_current_span(span_id)
        t0 = time.perf_counter()
        outcome: Dict[str, Any] = {"actuated": True}
        try:
            if self._retrain_fn is not None:
                t_r = time.perf_counter()
                try:
                    instance = self._retrain_fn()
                    outcome["retrain"] = {
                        "ok": True,
                        "instance": (str(instance)
                                     if instance is not None else None),
                        "wallS": round(time.perf_counter() - t_r, 3),
                    }
                    obs_trace.log_stage_span(
                        "controller.retrain", decision["traceId"],
                        time.perf_counter() - t_r,
                        spanId=obs_trace.new_span_id(),
                        parentSpanId=span_id,
                        decisionId=decision["id"],
                        instance=outcome["retrain"]["instance"])
                except Exception as e:
                    logger.exception("controller retrain failed")
                    outcome["retrain"] = {
                        "ok": False,
                        "error": str(e),
                        "wallS": round(time.perf_counter() - t_r, 3),
                    }
                    # a failed retrain leaves the OLD model serving —
                    # swapping nothing is the safe degradation, so the
                    # reload is skipped rather than hot-swapping a
                    # model that never materialized
                    outcome["reload"] = {"ok": False,
                                         "skipped": "retrain_failed"}
                    return
            if self._reload_fn is not None:
                t_w = time.perf_counter()
                try:
                    result = self._reload_fn()
                    outcome["reload"] = {
                        "ok": True,
                        "result": result,
                        "wallS": round(time.perf_counter() - t_w, 3),
                    }
                    obs_trace.log_stage_span(
                        "controller.reload", decision["traceId"],
                        time.perf_counter() - t_w,
                        spanId=obs_trace.new_span_id(),
                        parentSpanId=span_id,
                        decisionId=decision["id"])
                except Exception as e:
                    logger.exception("controller rolling reload failed")
                    outcome["reload"] = {
                        "ok": False,
                        "error": str(e),
                        "wallS": round(time.perf_counter() - t_w, 3),
                    }
        finally:
            outcome["wallS"] = round(time.perf_counter() - t0, 3)
            decision["outcome"] = outcome
            # the decision ROOT span, emitted after actuation so its
            # duration covers the whole retrain+swap
            obs_trace.log_stage_span(
                "controller.decision", decision["traceId"],
                time.perf_counter() - t0,
                spanId=span_id,
                decisionId=decision["id"],
                action=decision["action"],
                reason=decision["reason"])
            obs_trace.reset_current_span(span_token)
            obs_trace.reset_current(token)

    # -- ring / introspection -----------------------------------------------
    def _append(self, decision: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(decision)

    def decisions(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first slice of the decision ring."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:max(int(limit), 0)]

    def stats(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            return {
                # inline (not the property): self._lock is not reentrant
                "mode": self._mode_override or controller_mode(),
                "running": self._thread is not None
                and self._thread.is_alive(),
                "intervalS": self.config.interval_s,
                "breachEvals": self.config.breach_evals,
                "cooldownS": self.config.cooldown_s,
                "horizonS": self.config.horizon_s,
                "streak": self._streak,
                "cooldownRemainingS": round(
                    max(self._cooldown_until - now, 0.0), 3),
                "actions": self._actions,
                "decisionsRecorded": self._seq,
                "lastAction": self._last_action,
                "actuators": {
                    "retrain": self._retrain_fn is not None,
                    "reload": self._reload_fn is not None,
                    "capacityGuard": self._capacity_fn is not None,
                },
            }

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Start the background loop (idempotent). The loop runs in
        EVERY mode — an off controller just idles its tick — so a live
        ``POST /controller`` flip to act resumes actuation within one
        interval, no restart required.

        Each loop generation owns its OWN stop event (captured at
        spawn): a stop() whose join timed out on a long in-flight
        actuation leaves the old thread holding a permanently-set
        event, so a later start() can never resurrect it into a second
        concurrent loop — the old thread exits the moment its
        actuation returns."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive() \
                    and not self._stop.is_set():
                return
            stop = threading.Event()
            self._stop = stop
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="pio-freshness-controller", daemon=True)
            self._thread.start()

    def _loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.evaluate_once()
            except Exception:
                logger.exception("controller evaluation failed")
            stop.wait(self.config.interval_s)

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            stop = self._stop
            t = self._thread
        stop.set()
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                # mid-actuation past the join budget: its set event
                # ends it after the in-flight action; leave the handle
                # so start() spawns a FRESH generation rather than
                # clearing this one's event back to life
                return
        with self._lock:
            if self._thread is t:
                self._thread = None


# ---------------------------------------------------------------------------
# process-wide controller (the admin server's instance; tests reset)
# ---------------------------------------------------------------------------

_controller: Optional[FreshnessController] = None
_controller_lock = threading.Lock()


def get_controller() -> FreshnessController:
    """The process controller, wired from the environment: signals
    resolve fleet-first (``PIO_FLEET_TARGETS``), the reload actuator
    comes from ``PIO_CONTROLLER_RELOAD_URL`` (the front door's
    ``/reload``; ``PIO_CONTROLLER_RELOAD_KEY`` authes it), and the
    budget guard engages when ``PIO_CONTROLLER_ROWS`` names the
    training-set scale the capacity fit should project. A retrain
    actuator needs an engine object, so the CLI admin process runs
    reload-only unless an embedder wires :func:`workflow_retrain_fn`
    in programmatically."""
    global _controller
    with _controller_lock:
        if _controller is None:
            reload_url = os.environ.get(
                "PIO_CONTROLLER_RELOAD_URL", "").strip()
            # an inert guard (no rows declared / no usable fit — the
            # closure is deterministic, so one probe decides) is passed
            # as None: GET /controller's actuators.capacityGuard must
            # report whether the guard can actually veto, not whether
            # a callable exists
            cap_fn = capacity_budget_fn()
            if cap_fn() is None:
                cap_fn = None
            _controller = FreshnessController(
                reload_fn=(http_reload_fn(
                    reload_url,
                    os.environ.get("PIO_CONTROLLER_RELOAD_KEY")
                    or None) if reload_url else None),
                capacity_fn=cap_fn,
            )
        return _controller


def reset_controller() -> None:
    """Drop (and stop) the process controller — tests re-read the
    PIO_CONTROLLER_* env on next use."""
    global _controller
    with _controller_lock:
        if _controller is not None:
            _controller.stop(timeout=2.0)
        _controller = None


def peek_decisions(limit: int = 256) -> List[Dict[str, Any]]:
    """The decision ring WITHOUT creating a controller — the incident
    capture's export seam (obs/recorder.py): a bundle frozen on a
    process that never ran a controller records an empty audit trail
    rather than instantiating one as a side effect. An embedder that
    wired a custom controller (the admin server's injected instance)
    points the capture at it via :func:`export_ring_fn`."""
    with _controller_lock:
        c = _controller
    return c.decisions(limit=limit) if c is not None else []


def export_ring_fn(controller: "FreshnessController",
                   limit: int = 256) -> Callable[[], List[Dict[str, Any]]]:
    """Bind one controller's decision ring as an incident-capture
    ``decisions_fn`` (the admin server wires its hosted — possibly
    injected — controller through this). Duck-typed on
    ``decisions(limit=)``, so the same binder also exports the knob
    controller's ring (obs/knobs.KnobController) as the capture's
    ``knobs_fn`` — the two control loops share one audit machinery."""

    def export() -> List[Dict[str, Any]]:
        return controller.decisions(limit=limit)

    return export


__all__ = [
    "ACTION_REASONS", "ControllerConfig", "DRIVING_SLOS",
    "FreshnessController", "MODES", "SKIP_REASONS",
    "capacity_budget_fn", "controller_mode", "export_ring_fn",
    "get_controller", "http_reload_fn", "peek_decisions",
    "reset_controller", "workflow_retrain_fn",
]
