"""HTTP-side observability: the shared /metrics route.

Every server's ``_build_router`` calls :func:`add_metrics_route` so
``GET /metrics`` answers Prometheus text exposition from the
process-wide registry on all of them (event :7070, prediction :8000,
admin :7071, dashboard :9000 — plus the storage server). The route is
unauthenticated by design, like the reference's status pages: it
exposes operational counters, never event data; bind-address policy is
the operator's access control, same as ``GET /``.

The request-level instrumentation itself (per-route counters, latency
histogram, trace-ID stamping, span logs) lives in the HTTP layer
(``utils/http.py``) so every server gets it without per-server wiring.
"""

from __future__ import annotations

from incubator_predictionio_tpu.obs import metrics


def add_metrics_route(router) -> None:
    """Register ``GET /metrics`` (Prometheus text exposition) on a
    Router. Imports the http module lazily — obs must stay importable
    below utils/http.py, which itself imports obs for instrumentation."""
    from incubator_predictionio_tpu.utils.http import Request, Response

    def metrics_route(request: Request) -> Response:
        return Response(
            200,
            body=metrics.REGISTRY.expose().encode("utf-8"),
            content_type=metrics.CONTENT_TYPE,
        )

    router.add("GET", "/metrics", metrics_route)
