"""HTTP-side observability: the shared /metrics, /slo and /profile routes.

Every server's ``_build_router`` calls :func:`add_metrics_route` so
``GET /metrics`` answers Prometheus text exposition from the
process-wide registry on all of them (event :7070, prediction :8000,
admin :7071, dashboard :9000 — plus the storage server). The route is
unauthenticated by design, like the reference's status pages: it
exposes operational counters, never event data; bind-address policy is
the operator's access control, same as ``GET /``.

``GET /slo`` (admin + dashboard, :func:`add_slo_route`) answers the SLO
burn-rate engine's JSON evaluation — error budget remaining and
fast/slow burn rates per declared objective (obs/slo.py).

``POST /profile?seconds=N`` (admin, :func:`add_profile_route`) captures
an on-demand ``jax.profiler`` xplane trace (obs/profile.py) for offline
kernel analysis.

The request-level instrumentation itself (per-route counters, latency
histogram, trace-ID stamping, span logs) lives in the HTTP layer
(``utils/http.py``) so every server gets it without per-server wiring.
"""

from __future__ import annotations

from incubator_predictionio_tpu.obs import metrics


def _set_build_info() -> None:
    """Register the constant ``pio_build_info{version,jax_version,
    backend}`` gauge (value always 1 — the standard Prometheus build-
    info idiom: the *labels* are the data, joinable onto any series).
    The backend label reports the CONFIGURED platform (``JAX_PLATFORMS``
    or "default") rather than poking ``jax.devices()`` — a scrape must
    never be the thing that initializes a TPU backend — and the jax
    version comes from package metadata (or sys.modules when jax is
    already up), never a fresh ``import jax``: the event server is
    deliberately jax-free and must not pay the import for a label."""
    import os
    import sys

    mod = sys.modules.get("jax")
    if mod is not None:
        jax_version = getattr(mod, "__version__", "unknown")
    else:
        try:
            from importlib.metadata import version

            jax_version = version("jax")
        except Exception:
            jax_version = "unavailable"
    try:
        from incubator_predictionio_tpu import __version__ as version
    except Exception:
        version = "unknown"
    metrics.REGISTRY.gauge(
        "pio_build_info",
        "constant build/runtime identity gauge (always 1; the labels "
        "are the data)",
        labels=("version", "jax_version", "backend"),
    ).labels(
        version=version, jax_version=jax_version,
        backend=os.environ.get("JAX_PLATFORMS") or "default",
    ).set(1)


def add_metrics_route(router) -> None:
    """Register ``GET /metrics`` (Prometheus text exposition) on a
    Router. Imports the http module lazily — obs must stay importable
    below utils/http.py, which itself imports obs for instrumentation."""
    from incubator_predictionio_tpu.utils.http import Request, Response

    _set_build_info()

    def metrics_route(request: Request) -> Response:
        return Response(
            200,
            body=metrics.REGISTRY.expose().encode("utf-8"),
            content_type=metrics.CONTENT_TYPE,
        )

    router.add("GET", "/metrics", metrics_route)


def add_slo_route(router) -> None:
    """Register ``GET /slo`` — the burn-rate engine's JSON evaluation.
    Unauthenticated like /metrics (operational state only).
    ``?fleet=1`` evaluates the SAME objectives over the federated
    registry (obs/federate.py) instead of this process's — the fleet
    p99 promise, not one worker's."""
    from incubator_predictionio_tpu.obs import slo as obs_slo
    from incubator_predictionio_tpu.utils.http import Request, Response

    def slo_route(request: Request) -> Response:
        fleet = request.query.get("fleet", "") not in ("", "0", "false")
        if fleet:
            from incubator_predictionio_tpu.obs import federate

            engine = federate.fleet_slo_engine()
            try:
                slos = engine.evaluate()
            except ValueError as e:  # no PIO_FLEET_TARGETS configured
                return Response(400, {"message": str(e)})
        else:
            engine = obs_slo.get_engine()
            slos = engine.evaluate()
        return Response(200, {
            "scope": "fleet" if fleet else "process",
            "slos": slos,
            "windows": {"fastSeconds": engine.fast_window_s,
                        "slowSeconds": engine.slow_window_s},
        })

    router.add("GET", "/slo", slo_route)


def add_federate_route(router) -> None:
    """Register ``GET /federate`` — scrape every ``PIO_FLEET_TARGETS``
    worker's ``/metrics``, merge the families under an ``instance``
    label and re-expose the fleet as ONE text exposition
    (obs/federate.py). The handler is synchronous, so the HTTP layer
    runs it on the executor: N worker scrapes never block the admin's
    event loop. 503 when no targets are configured — an empty
    federation is a misconfiguration, not an empty healthy fleet."""
    from incubator_predictionio_tpu.obs import federate
    from incubator_predictionio_tpu.utils.http import Request, Response

    def federate_route(request: Request) -> Response:
        try:
            snapshot = federate.federate()
        except ValueError as e:
            return Response(503, {"message": str(e)})
        return Response(
            200,
            body=snapshot.expose().encode("utf-8"),
            content_type=metrics.CONTENT_TYPE,
        )

    router.add("GET", "/federate", federate_route)


def add_recorder_route(router) -> None:
    """Register ``GET /recorder`` — the flight recorder's reconstructed
    metric-history window (obs/recorder.py). Starts the background
    sampler as a side effect (the route IS the "this server records"
    declaration); with ``PIO_RECORDER=0`` no thread exists and the
    route answers 503. Query params:

    - ``series=<name>[,<name>...]`` — those families' windows;
    - ``window=<seconds>`` — trailing window (≤ the ring bound);
    - ``all=1`` — the full self-describing dump (every series +
      current exemplars + state-provider blocks) an incident bundle
      freezes;
    - none of the above — the cheap index (series list, cadence, ring
      size).

    Handlers are synchronous, so the HTTP layer runs them on the
    executor — a window reconstruction never blocks the event loop
    (and never touches the serving path: the ``recorder-in-serve-path``
    lint rule pins that direction too)."""
    from incubator_predictionio_tpu.obs import recorder as obs_recorder
    from incubator_predictionio_tpu.utils.http import Request, Response

    # starting the sampler at route-registration time (not first
    # request) makes the window already warm when an operator first
    # looks — an incident's pre-breach history must predate the breach.
    # The capture engine arms alongside it when PIO_INCIDENT_DIR names
    # a destination (no-op otherwise), so a lone worker captures its
    # own breaches without an admin in the loop.
    obs_recorder.get_recorder()
    obs_recorder.get_capture()

    def recorder_route(request: Request) -> Response:
        rec = obs_recorder.get_recorder()
        if rec is None:
            return Response(503, {
                "message": "flight recorder disabled (PIO_RECORDER=0)"})
        window = None
        raw_window = request.query.get("window", "")
        if raw_window:
            try:
                window = float(raw_window)
            except ValueError:
                return Response(400,
                                {"message": "window must be seconds"})
        if request.query.get("all", "") not in ("", "0", "false"):
            return Response(200, rec.dump(window_s=window))
        series = [s for s in request.query.get("series", "").split(",")
                  if s.strip()]
        if series:
            return Response(200, rec.window(series=series,
                                            window_s=window))
        return Response(200, rec.index())

    router.add("GET", "/recorder", recorder_route)


def add_incident_routes(router) -> None:
    """Register the incident-capture endpoints (admin server):

    - ``GET /incidents`` — newest-first bundle summaries from
      ``PIO_INCIDENT_DIR``;
    - ``GET /incidents/{id}`` — one full bundle;
    - ``POST /incident`` — manual capture (trigger="manual"), answers
      the new bundle's id + path.

    503 when ``PIO_INCIDENT_DIR`` is unset — like ``/federate``, a
    capture plane with no destination is a misconfiguration, not an
    empty healthy state."""
    from incubator_predictionio_tpu.obs import recorder as obs_recorder
    from incubator_predictionio_tpu.utils.http import Request, Response

    def _capture_or_503():
        cap = obs_recorder.get_capture()
        if cap is None:
            return None, Response(503, {
                "message": "incident capture disabled: set "
                           "PIO_INCIDENT_DIR"})
        return cap, None

    def list_incidents(request: Request) -> Response:
        cap, err = _capture_or_503()
        if err is not None:
            return err
        return Response(200, {"incidents": cap.list_incidents(),
                              "directory": cap.directory,
                              "cooldownS": cap.cooldown_s})

    def get_incident(request: Request) -> Response:
        cap, err = _capture_or_503()
        if err is not None:
            return err
        bundle = cap.read_incident(request.path_params["inc_id"])
        if bundle is None:
            return Response(404, {"message": "no such incident"})
        return Response(200, bundle)

    def post_incident(request: Request) -> Response:
        cap, err = _capture_or_503()
        if err is not None:
            return err
        # manual captures bypass the breach cooldown (an operator
        # asking for a bundle NOW is the authority) but still run on
        # this handler synchronously — it's the admin's executor, not
        # a serving path
        out = cap.capture_now(cap.MANUAL_TRIGGER)
        return Response(200, out)

    router.add("GET", "/incidents", list_incidents)
    router.add("GET", "/incidents/{inc_id}", get_incident)
    router.add("POST", "/incident", post_incident)


def add_profile_route(router) -> None:
    """Register ``POST /profile?seconds=N`` — on-demand jax.profiler
    xplane capture (obs/profile.py). The handler is synchronous, so the
    HTTP layer runs it on the executor: the capture window never blocks
    the event loop. 409 while another capture runs, 400 on a bad
    window."""
    from incubator_predictionio_tpu.obs import profile as obs_profile
    from incubator_predictionio_tpu.utils.http import Request, Response

    def profile_route(request: Request) -> Response:
        try:
            seconds = float(request.query.get("seconds", "1"))
        except ValueError:
            return Response(400, {"message": "seconds must be a number"})
        try:
            out = obs_profile.capture_trace(seconds)
        except ValueError as e:
            return Response(400, {"message": str(e)})
        except RuntimeError as e:
            return Response(409, {"message": str(e)})
        except Exception as e:  # profiler unavailable on this backend
            return Response(503, {"message": f"profiler capture "
                                             f"failed: {e}"})
        return Response(200, out)

    router.add("POST", "/profile", profile_route)


def render_latency_panels() -> str:
    """HTML panel rows for the dashboard: p50/p95/p99 serving latency
    and the freshness histogram's quantiles, derived from the process
    registry (this replaces the old average-only serving figure — a
    running average hides tail regressions entirely)."""
    reg = metrics.REGISTRY

    def quantiles(name, qs, scale, unit):
        m = reg.get(name)
        cells = []
        for q in qs:
            v = (m.quantile_over_children(q)
                 if m is not None and m.kind == "histogram" else None)
            cells.append("&mdash;" if v is None
                         else f"{v * scale:.2f}{unit}")
        return cells

    p50, p95, p99 = quantiles(
        "pio_query_latency_seconds", (0.50, 0.95, 0.99), 1e3, "ms")
    f50, f95, f99 = quantiles(
        "pio_freshness_seconds", (0.50, 0.95, 0.99), 1.0, "s")
    return (
        "<h2>Serving latency</h2>"
        "<table border=1><tr><th>p50</th><th>p95</th><th>p99</th></tr>"
        f"<tr><td>{p50}</td><td>{p95}</td><td>{p99}</td></tr></table>"
        "<h2>Freshness (event append &rarr; served)</h2>"
        "<table border=1><tr><th>p50</th><th>p95</th><th>p99</th></tr>"
        f"<tr><td>{f50}</td><td>{f95}</td><td>{f99}</td></tr></table>"
        "<p><a href='/slo'>SLO budget / burn rates (JSON)</a> &middot; "
        "<a href='/metrics'>raw metrics</a></p>"
    )


def render_slo_panel() -> str:
    """HTML summary table of the SLO engine's current evaluation."""
    from incubator_predictionio_tpu.obs import slo as obs_slo

    rows = []
    for s in obs_slo.get_engine().evaluate():
        fast = s["windows"]["fast"]["burnRate"]
        slow = s["windows"]["slow"]["burnRate"]
        state = ("no data" if s["noData"]
                 else "BREACH" if s["breached"] else "ok")
        rows.append(
            "<tr>"
            f"<td>{s['name']}</td>"
            f"<td>&le; {s['objective']['thresholdSeconds']}s @ "
            f"{s['objective']['target']:.2%}</td>"
            f"<td>{fast}</td><td>{slow}</td>"
            f"<td>{s['errorBudgetRemaining']:.2%}</td>"
            f"<td>{state}</td></tr>")
    return (
        "<h2>SLOs</h2>"
        "<table border=1><tr><th>SLO</th><th>Objective</th>"
        "<th>Burn (fast)</th><th>Burn (slow)</th>"
        "<th>Budget left</th><th>State</th></tr>"
        + "".join(rows) + "</table>"
    )


def render_tenant_panel() -> str:
    """HTML per-tenant table for the dashboard: policy (weight/quota),
    queue depth + shed totals from the scheduler, and the tenant's
    own p99 read from the tenant-labeled latency family. Returns ""
    in single-tenant mode (empty registry) so the index stays clean."""
    from incubator_predictionio_tpu.serving import tenancy

    reg = tenancy.get_registry()
    if not reg:
        return ""
    m = metrics.REGISTRY.get("pio_query_latency_seconds")
    shed = metrics.REGISTRY.get("pio_serve_shed_total")
    depth = metrics.REGISTRY.get("pio_serve_queue_depth")
    rows = []
    for t in reg.tenants():
        label = reg.label(t.tenant_id)
        p99 = None
        if m is not None and m.kind == "histogram":
            try:
                p99 = m.labels(tenant=label).quantile(0.99)
            except Exception:
                p99 = None
        shed_n = 0.0
        if shed is not None and "tenant" in shed.labelnames:
            ti = shed.labelnames.index("tenant")
            for key, child in getattr(shed, "_children", {}).items():
                if len(key) > ti and key[ti] == label:
                    shed_n += child.value
        d = None
        if depth is not None:
            try:
                d = depth.labels(tenant=label).value
            except Exception:
                d = None
        rows.append(
            "<tr>"
            f"<td>{t.tenant_id}</td>"
            f"<td>{t.weight}</td>"
            f"<td>{'&mdash;' if t.quota is None else t.quota}</td>"
            f"<td>{'&mdash;' if d is None else int(d)}</td>"
            f"<td>{int(shed_n)}</td>"
            f"<td>{'&mdash;' if p99 is None else f'{p99 * 1e3:.2f}ms'}"
            f"</td>"
            f"<td>{'enabled' if t.enabled else 'disabled'}</td></tr>")
    return (
        "<h2>Tenants</h2>"
        "<table border=1><tr><th>Tenant</th><th>Weight</th>"
        "<th>Quota</th><th>Queue depth</th><th>Shed</th>"
        "<th>p99</th><th>State</th></tr>"
        + "".join(rows) + "</table>"
    )


__all__ = [
    "add_federate_route", "add_incident_routes", "add_metrics_route",
    "add_recorder_route", "add_slo_route", "add_profile_route",
    "render_latency_panels", "render_slo_panel", "render_tenant_panel",
]
