"""Self-tuning serving — the recorder-driven knob controller.

The reference PredictionIO leaves every serving parameter to a human
editing ``engine.json``; this stack's serving knobs are env vars an
operator tunes by hand and forgets. This module closes the second
control loop (ROADMAP item 5): a declarative registry over the four
serving knob families —

- MIPS search effort: ``PIO_SERVE_MIPS_NPROBE`` /
  ``PIO_SERVE_MIPS_CANDIDATES`` (ops/mips.py, call-time env reads);
- scheduler ladder: ``PIO_SERVE_MAX_BATCH`` cap +
  ``PIO_SERVE_MAX_WAIT_MS`` (serving/scheduler.py);
- shed projection: ``PIO_SERVE_SHED`` (serving/scheduler.py);
- overlay fold-in budget base: ``PIO_SPEED_MAX_BATCH``
  (speed/foldin.py → speed/overlay.py's adaptive rungs)

— driven by a bounded per-knob hill-climb. Every evaluation reads
**flight-recorder history windows** (obs/recorder.py — the trailing
p99 / queue-wait / shed-rate / recall series, not an instantaneous
scrape a single hiccup can spoof), decides at most ONE signed step per
pass, and gates it behind **hysteresis** (consecutive same-direction
desires), a per-knob **post-change cooldown**, the registry **bounds**,
and an ALX-style **capacity guard** (obs/capacity.py's fit says how far
a knob may move before capacity, not tuning, becomes binding —
arxiv 2112.02194's sizing argument).

Actuation happens through ONE sanctioned seam, exactly like
``FreshnessController._actuate``: :meth:`KnobController._apply` emits a
structured decision record (inputs snapshot, per-knob gate map, step,
outcome, rejection reason) into a bounded ring under its own trace ID
(``knb-``) and pushes the full knob vector through the fleet front
door's ``POST /knobs`` (serving/frontdoor.py fans each worker's
``POST /knobs`` under the rolling-reload serialization; the knobs are
call-time env reads, so they take effect without restart or drain).
``scripts/trace_stitch.py --decisions`` stitches ``knob.decision`` →
``knob.apply`` → the fleet's ``/knobs`` HTTP hops into one tree, and
the ``unaudited-knob-write`` lint rule pins that no other code path
mutates a registered knob.

Incident capture is the safety net: an SLO breach (obs/slo.py burn
engine — the same listener seam IncidentCapture rides) arriving while
the last adjustment is still inside its cooldown window schedules an
automatic **rollback to the last-known-good vector**, itself a normal
audited decision (``action="rollback"``, ``reason="incident"``), and
the knob decision ring lands in incident bundles via the capture's
``knobs_fn`` seam.

Exported series (docs/observability.md):

- ``pio_knob_evaluations_total``
- ``pio_knob_adjustments_total{knob}``
- ``pio_knob_rollbacks_total``
- ``pio_knob_value{knob}`` (the vector the controller believes is live)
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import secrets
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.utils import times

logger = logging.getLogger(__name__)

#: kill-switch modes, in escalation order (shared with the freshness
#: controller so one operator mental model covers both loops)
MODES = ("off", "observe", "act")

#: every env var the registry owns. The ``unaudited-knob-write`` lint
#: rule (analysis/rules.py) carries a literal copy of this set — a
#: rule must not import the runtime it audits — and tests pin the two
#: sets equal so a knob added here cannot silently escape the audit.
KNOB_ENV_VARS = frozenset((
    "PIO_SERVE_MIPS_NPROBE",
    "PIO_SERVE_MIPS_CANDIDATES",
    "PIO_SERVE_MAX_BATCH",
    "PIO_SERVE_MAX_WAIT_MS",
    "PIO_SERVE_SHED",
    "PIO_SPEED_MAX_BATCH",
    "PIO_SERVE_MIPS_PQ_M",
    "PIO_SERVE_MIPS_PQ_CANDIDATES",
    "PIO_MIPS_REBUILD_TAIL",
    "PIO_MIPS_REBUILD_AGE_S",
))

#: bounded reason enums — decision records and docs draw from these
#: sets only (metric-label-cardinality contract)
SKIP_REASONS = ("off", "observe", "healthy", "no_data", "hysteresis",
                "cooldown", "capacity", "bound", "no_actuator",
                "inputs_error")
ACTION_REASONS = ("recall_low", "latency_high", "queue_high",
                  "latency_headroom", "shed_active", "fold_lag",
                  "incident", "tail_high", "index_stale")

_EVALUATIONS = obs_metrics.REGISTRY.counter(
    "pio_knob_evaluations_total",
    "knob-controller evaluation passes (off-mode ticks excluded)")
_ADJUSTMENTS = obs_metrics.REGISTRY.counter(
    "pio_knob_adjustments_total",
    "autonomous knob steps actually applied, by knob name",
    labels=("knob",))
_ROLLBACKS = obs_metrics.REGISTRY.counter(
    "pio_knob_rollbacks_total",
    "incident-triggered rollbacks to the last-known-good knob vector")
_VALUE = obs_metrics.REGISTRY.gauge(
    "pio_knob_value",
    "current registry value per knob (the vector the controller "
    "believes the fleet is serving with)",
    labels=("knob",))

#: recorder series one evaluation consumes (window reads, not scrapes)
INPUT_SERIES = (
    "pio_query_latency_seconds",
    "pio_serve_queue_wait_seconds",
    "pio_serve_shed_total",
    "pio_serve_mips_recall",
    "pio_freshness_fold_seconds",
    "pio_mips_tail_size",
    "pio_mips_index_age_seconds",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def knobs_mode() -> str:
    """The env-declared kill-switch position (``PIO_KNOBS``), re-read
    per call; ``POST /knobs`` on the admin server overrides it
    in-process."""
    raw = os.environ.get("PIO_KNOBS", "off").strip().lower()
    return raw if raw in MODES else "off"


@dataclasses.dataclass
class KnobConfig:
    """Loop cadence + step policy. Every number has a ``PIO_KNOBS_*``
    env default so the CLI admin server is configurable without code."""

    #: evaluation period — also the kill switch's reaction bound
    interval_s: float = 10.0
    #: consecutive SAME-DIRECTION desires required before stepping (the
    #: hysteresis band — one noisy window must never move the fleet)
    hysteresis_evals: int = 2
    #: per-knob wall after a step during which it holds still; also the
    #: incident-rollback arming window — a breach landing inside it
    #: indicts the step
    cooldown_s: float = 120.0
    #: recorder window each evaluation reads
    window_s: float = 30.0
    #: decision-record ring bound
    ring: int = 256
    #: recall@k floor the MIPS knobs defend
    recall_target: float = 0.95
    #: recall slack required before latency may trade recall away
    recall_margin: float = 0.02
    #: fold-in wall the overlay budget knob defends
    fold_objective_s: float = 2.0

    @staticmethod
    def from_env() -> "KnobConfig":
        return KnobConfig(
            interval_s=_env_float("PIO_KNOBS_INTERVAL_S", 10.0),
            hysteresis_evals=int(_env_float("PIO_KNOBS_HYSTERESIS", 2)),
            cooldown_s=_env_float("PIO_KNOBS_COOLDOWN_S", 120.0),
            window_s=_env_float("PIO_KNOBS_WINDOW_S", 30.0),
            ring=int(_env_float("PIO_KNOBS_RING", 256)),
            recall_target=_env_float("PIO_KNOBS_RECALL_TARGET", 0.95),
            recall_margin=_env_float("PIO_KNOBS_RECALL_MARGIN", 0.02),
            fold_objective_s=_env_float(
                "PIO_KNOBS_FOLD_OBJECTIVE_S", 2.0),
        )


# ---------------------------------------------------------------------------
# the knob registry
# ---------------------------------------------------------------------------

#: decide(value, inputs, ctx) → (direction −1/0/+1, ACTION_REASONS
#: member or None). Pure functions of the inputs snapshot: the
#: machinery (hysteresis, cooldown, bounds, capacity, actuation, audit)
#: lives in the controller, the POLICY lives here.
DecideFn = Callable[[int, Dict[str, Any], Dict[str, float]],
                    Tuple[int, Optional[str]]]


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One registry entry: where the knob lives (env var), where it may
    go (bounds + step scale), and what it optimizes (decide rule)."""

    name: str
    env: str
    default: int
    lo: int
    hi: int
    decide: DecideFn
    #: "pow2" doubles/halves (clamped); "binary" toggles 0/1
    scale: str = "pow2"

    def read_env(self) -> int:
        """Current live value: the env var when parseable, the registry
        default otherwise (a knob left on its auto heuristic starts the
        climb from the default, never from a sentinel)."""
        try:
            v = int(os.environ.get(self.env, "") or self.default)
        except ValueError:
            v = self.default
        if v <= 0 and self.scale == "pow2":
            v = self.default
        return min(max(v, self.lo), self.hi)

    def step(self, value: int, direction: int) -> int:
        """One bounded step. Returns ``value`` unchanged at a bound —
        the controller records that as gate="bound", it never saturates
        silently."""
        if direction == 0:
            return value
        if self.scale == "binary":
            return self.hi if direction > 0 else self.lo
        nxt = value * 2 if direction > 0 else value // 2
        return min(max(nxt, self.lo), self.hi)


def _decide_mips(value: int, inputs: Dict[str, Any],
                 ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """Shared MIPS effort rule (nprobe and the candidate pool): defend
    the recall floor first; spend recall SLACK on latency only when the
    serve objective is actually breached."""
    recall = inputs.get("recall")
    p99 = inputs.get("p99S")
    if recall is not None and recall < ctx["recallTarget"]:
        return 1, "recall_low"
    if p99 is not None and p99 > ctx["p99ObjectiveS"] \
            and recall is not None \
            and recall >= ctx["recallTarget"] + ctx["recallMargin"]:
        return -1, "latency_high"
    return 0, None


def _decide_cap(value: int, inputs: Dict[str, Any],
                ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """Ladder cap: grow when the queue (not the compute) dominates the
    latency budget; shrink when per-dispatch latency breaches with an
    empty queue (the batch itself is the wall)."""
    p99 = inputs.get("p99S")
    queue = inputs.get("queueP99S")
    obj = ctx["p99ObjectiveS"]
    if queue is not None and p99 is not None \
            and queue > 0.5 * obj and p99 <= obj:
        return 1, "queue_high"
    if p99 is not None and p99 > obj \
            and (queue is None or queue < 0.25 * obj):
        return -1, "latency_high"
    return 0, None


def _decide_wait(value: int, inputs: Dict[str, Any],
                 ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """Batch-formation wait: cut it under breach (waiting is latency it
    volunteered for); raise it only inside a wide healthy deadband so a
    doubled wait cannot jump the objective and oscillate."""
    p99 = inputs.get("p99S")
    queue = inputs.get("queueP99S")
    obj = ctx["p99ObjectiveS"]
    if p99 is not None and p99 > obj:
        return -1, "latency_high"
    if p99 is not None and p99 < 0.25 * obj \
            and (queue is None or queue < 0.1 * obj):
        return 1, "latency_headroom"
    return 0, None


def _decide_shed(value: int, inputs: Dict[str, Any],
                 ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """Shed projection toggle: arm it under sustained breach; disarm
    only when it is actively shedding WHILE the fleet is comfortably
    healthy (a misfiring projection turning away good traffic)."""
    p99 = inputs.get("p99S")
    shed_rate = inputs.get("shedPerS")
    obj = ctx["p99ObjectiveS"]
    if value < 1 and p99 is not None and p99 > obj:
        return 1, "latency_high"
    if value >= 1 and shed_rate is not None and shed_rate > 0.0 \
            and p99 is not None and p99 < 0.5 * obj:
        return -1, "shed_active"
    return 0, None


def _decide_foldin(value: int, inputs: Dict[str, Any],
                   ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """Overlay fold-in budget base: grow when the fold wall lags its
    objective and serving has headroom to pay for it; shrink when
    serving breaches while folds are cheap (the overlay is stealing
    compute the queries need)."""
    p99 = inputs.get("p99S")
    fold = inputs.get("foldP99S")
    obj = ctx["p99ObjectiveS"]
    if fold is not None and fold > ctx["foldObjectiveS"] \
            and (p99 is None or p99 <= obj):
        return 1, "fold_lag"
    if p99 is not None and p99 > obj \
            and fold is not None and fold <= 0.5 * ctx["foldObjectiveS"]:
        return -1, "latency_high"
    return 0, None


def _decide_pq_m(value: int, inputs: Dict[str, Any],
                 ctx: Dict[str, float]) -> Tuple[int, Optional[str]]:
    """PQ subquantizer count: more subspaces = finer residual codes =
    better coarse ranking, at M bytes/item. Defend the recall floor
    only — M is a BUILD-time knob (takes effect at the next daemon
    rebuild), so it never trades recall away autonomously; shrinking M
    for memory is the capacity guard's veto territory, not a climb."""
    recall = inputs.get("recall")
    if recall is not None and recall < ctx["recallTarget"]:
        return 1, "recall_low"
    return 0, None


def _decide_rebuild_tail(value: int, inputs: Dict[str, Any],
                         ctx: Dict[str, float]
                         ) -> Tuple[int, Optional[str]]:
    """Rebuild tail trigger: the exact tail is an O(tail·K) host scan
    on EVERY query, so a tail sustained above the trigger means fold-in
    outruns the rebuild cadence — tighten the trigger. Relax it only
    when serving breaches while the tail is nearly empty (rebuild
    clustering competes with serving for the same cores)."""
    tail = inputs.get("tailRows")
    p99 = inputs.get("p99S")
    if tail is not None and tail > value:
        return -1, "tail_high"
    if p99 is not None and p99 > ctx["p99ObjectiveS"] \
            and tail is not None and tail < value // 8:
        return 1, "latency_high"
    return 0, None


def _decide_rebuild_age(value: int, inputs: Dict[str, Any],
                        ctx: Dict[str, float]
                        ) -> Tuple[int, Optional[str]]:
    """Rebuild age trigger: an index aging past its own trigger while
    churn keeps arriving means the cadence is too loose (or the daemon
    is drowning) — tighten. Relax when serving breaches and the index
    is comfortably fresh."""
    age = inputs.get("indexAgeS")
    tail = inputs.get("tailRows")
    p99 = inputs.get("p99S")
    if age is not None and age > value and tail is not None and tail > 0:
        return -1, "index_stale"
    if p99 is not None and p99 > ctx["p99ObjectiveS"] \
            and age is not None and age < value // 4:
        return 1, "latency_high"
    return 0, None


def default_knobs() -> Tuple[KnobSpec, ...]:
    """The knob families, in adjustment priority order (one step
    per evaluation: quality defense first, then scheduler relief, then
    background-work budget, then the MIPS lifecycle knobs added with
    the PQ/rebuild-daemon work — appended last so the established
    priority order is unchanged)."""
    return (
        KnobSpec("mips_nprobe", "PIO_SERVE_MIPS_NPROBE",
                 default=64, lo=4, hi=4096, decide=_decide_mips),
        KnobSpec("mips_candidates", "PIO_SERVE_MIPS_CANDIDATES",
                 default=1024, lo=128, hi=16384, decide=_decide_mips),
        KnobSpec("max_batch", "PIO_SERVE_MAX_BATCH",
                 default=512, lo=32, hi=4096, decide=_decide_cap),
        KnobSpec("max_wait_ms", "PIO_SERVE_MAX_WAIT_MS",
                 default=250, lo=10, hi=1000, decide=_decide_wait),
        KnobSpec("shed", "PIO_SERVE_SHED",
                 default=1, lo=0, hi=1, decide=_decide_shed,
                 scale="binary"),
        KnobSpec("foldin_budget", "PIO_SPEED_MAX_BATCH",
                 default=64, lo=8, hi=1024, decide=_decide_foldin),
        KnobSpec("mips_pq_candidates", "PIO_SERVE_MIPS_PQ_CANDIDATES",
                 default=2048, lo=256, hi=32768, decide=_decide_mips),
        KnobSpec("mips_pq_m", "PIO_SERVE_MIPS_PQ_M",
                 default=16, lo=4, hi=64, decide=_decide_pq_m),
        KnobSpec("mips_rebuild_tail", "PIO_MIPS_REBUILD_TAIL",
                 default=4096, lo=256, hi=65536,
                 decide=_decide_rebuild_tail),
        KnobSpec("mips_rebuild_age_s", "PIO_MIPS_REBUILD_AGE_S",
                 default=900, lo=60, hi=14400,
                 decide=_decide_rebuild_age),
    )


# ---------------------------------------------------------------------------
# recorder-window input extraction
# ---------------------------------------------------------------------------

def _hist_window_p99(fam: Optional[Dict[str, Any]]) -> Optional[float]:
    """Count-weighted mean of the per-interval p99s across every child
    in the window — the recorder already computed each interval's tail
    from bucket deltas; weighting by interval count keeps one idle
    second from diluting a busy one."""
    if not fam:
        return None
    num = den = 0.0
    for child in fam.get("children", ()):
        for pt in child.get("points", ()):
            if len(pt) >= 6 and pt[3] and pt[5] is not None:
                num += float(pt[5]) * float(pt[3])
                den += float(pt[3])
    return num / den if den > 0 else None


def _counter_window_rate(fam: Optional[Dict[str, Any]]) -> Optional[float]:
    """Summed per-second rate over the window across children (shed
    reasons, worker instances). None when the window is too short to
    hold a rate."""
    if not fam:
        return None
    total = 0.0
    span = 0.0
    saw = False
    for child in fam.get("children", ()):
        pts = child.get("points", ())
        if len(pts) < 2:
            continue
        saw = True
        total += float(pts[-1][1]) - float(pts[0][1])
        span = max(span, float(pts[-1][0]) - float(pts[0][0]))
    if not saw or span <= 0:
        return None
    return max(total, 0.0) / span


def _gauge_window_last(fam: Optional[Dict[str, Any]],
                       worst: Callable[..., float] = min
                       ) -> Optional[float]:
    """Newest reading per child, reduced by ``worst`` across children
    (min for recall: the weakest index is the fleet's recall)."""
    if not fam:
        return None
    vals = [float(child["points"][-1][1])
            for child in fam.get("children", ())
            if child.get("points")]
    return worst(vals) if vals else None


# ---------------------------------------------------------------------------
# actuator factories
# ---------------------------------------------------------------------------
#
# Like the freshness controller's retrain/reload pair, the knob
# controller never hard-codes HOW a vector reaches the fleet — it takes
# one callable. The closures below run only from inside the decision-
# record emitter (_apply); the unaudited-knob-write lint rule documents
# the *_fn naming convention as the sanctioned construction site.

def http_knobs_fn(url: str, server_key: Optional[str] = None,
                  timeout_s: float = 60.0
                  ) -> Callable[[Dict[str, int]], Dict]:
    """Actuator that POSTs the front door's fleet ``/knobs`` (which
    fans each worker's ``POST /knobs`` under the rolling-reload
    serialization, serving/frontdoor.py). The request carries the
    ambient trace headers, so every worker hop lands under the
    decision's trace."""
    if "://" not in url:
        url = f"http://{url}"
    if server_key:
        from urllib.parse import quote

        url = f"{url}?accessKey={quote(server_key, safe='')}"

    def apply(vector: Dict[str, int]) -> Dict:
        body = json.dumps(
            {"values": {k: int(v) for k, v in sorted(vector.items())}},
        ).encode("utf-8")
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={**obs_trace.client_headers(),
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    return apply


def local_knobs_fn() -> Callable[[Dict[str, int]], Dict]:
    """Actuator for a single-process deployment (or tests): writes the
    knob env vars directly — every registered knob is a call-time env
    read, so the very next dispatch sees the new vector."""

    def apply(vector: Dict[str, int]) -> Dict:
        applied = {}
        for env, v in sorted(vector.items()):
            os.environ[env] = str(int(v))
            applied[env] = int(v)
        return {"local": True, "applied": applied}

    return apply


def _fanout_failures(result: Any) -> List[str]:
    """Per-worker failures hidden inside a "successful" actuator call.
    The front door's fleet ``/knobs`` answers HTTP 200 even when some
    (or all) workers fail or reject the vector — the real outcome
    lives in the body's ``failed`` list and ``applied`` count
    (serving/frontdoor.py knobs_fanout_async). Any failed entry means
    part of the fleet still serves the OLD vector, so the apply did
    NOT succeed and the controller's belief must not advance."""
    if not isinstance(result, dict):
        return []
    failed = result.get("failed")
    if isinstance(failed, (list, tuple)) and failed:
        return [str(w) for w in failed]
    workers, applied = result.get("workers"), result.get("applied")
    # local_knobs_fn reports ``applied`` as a dict — only the fleet
    # door's int/int pair is a coverage count worth comparing
    if isinstance(workers, int) and isinstance(applied, int) \
            and applied < workers:
        return [f"{workers - applied} worker(s) unapplied"]
    return []


def capacity_caps_fn(repo_dir: str = ".") -> Callable[
        [], Optional[Dict[str, int]]]:
    """Capacity guard from the measured fit (obs/capacity.py): the
    newest non-degraded bench records bound how far the effort knobs
    may climb before capacity — not tuning — becomes binding. The fit
    is computed once at factory time; returns None (no guard) when no
    usable fit exists, because a fabricated ceiling would veto real
    steps."""
    caps: Optional[Dict[str, int]] = None
    try:
        from incubator_predictionio_tpu.obs import capacity

        fit = capacity.fit_capacity(capacity.load_trajectory(repo_dir))
        block = fit.get("knobs")
        if block:
            caps = {k: int(v) for k, v in block.items()
                    if isinstance(v, (int, float)) and v > 0}
    except Exception:
        logger.exception("capacity fit unavailable; knob capacity "
                         "guard disabled")

    def estimate() -> Optional[Dict[str, int]]:
        return dict(caps) if caps else None

    return estimate


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class KnobController:
    """The recorder-driven serving-knob loop. One instance per admin
    process, hosted next to the freshness controller; every evaluation
    appends a decision record, every actuation runs inside the
    decision's trace context, and an SLO breach inside the newest
    step's cooldown rolls the whole vector back."""

    def __init__(self,
                 specs: Optional[Tuple[KnobSpec, ...]] = None,
                 apply_fn: Optional[Callable[[Dict[str, int]], Any]]
                 = None,
                 capacity_fn: Optional[Callable[
                     [], Optional[Dict[str, int]]]] = None,
                 recorder_fn: Optional[Callable[[], Any]] = None,
                 config: Optional[KnobConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 mode: Optional[str] = None,
                 apply_scope: Optional[str] = None) -> None:
        self.specs = tuple(specs) if specs is not None \
            else default_knobs()
        self.config = config or KnobConfig.from_env()
        self._clock = clock if clock is not None else times.monotonic
        self._apply_fn = apply_fn
        #: "fleet" | "local" | None — how far the actuator reaches
        #: (stats() surfaces it so one status call shows whether
        #: adjustments leave this process)
        self._apply_scope = apply_scope
        self._capacity_fn = capacity_fn
        self._recorder_fn = recorder_fn
        self._mode_override: Optional[str] = mode
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(self.config.ring), 1))
        #: signed per-knob desire streaks (hysteresis state)
        self._streaks: Dict[str, int] = {}
        #: per-knob cooldown deadlines (monotonic)
        self._cooldowns: Dict[str, float] = {}
        #: the vector the controller believes is live
        self._vector: Dict[str, int] = {
            s.env: s.read_env() for s in self.specs}
        #: vector before the newest applied step — the rollback target
        self._last_good: Optional[Dict[str, int]] = None
        #: the newest applied step while its cooldown arms the rollback
        self._last_change: Optional[Dict[str, Any]] = None
        self._rollback_pending: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._adjustments = 0
        self._rollbacks = 0
        self._last_action: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for s in self.specs:
            _VALUE.labels(knob=s.name).set(float(self._vector[s.env]))

    # -- mode (the kill switch) ---------------------------------------------
    @property
    def mode(self) -> str:
        with self._lock:
            return self._mode_override or knobs_mode()

    def set_mode(self, mode: str) -> str:
        """Live flip (POST /knobs on the admin server). The flip lands
        in the decision ring — same contract as the freshness
        controller's kill switch."""
        mode = (mode or "").strip().lower()
        if mode not in MODES:
            raise ValueError(
                f"mode must be one of {MODES}, got {mode!r}")
        with self._lock:
            # inline (not the property): self._lock is not reentrant
            prev = self._mode_override or knobs_mode()
            self._mode_override = mode
            self._seq += 1
            self._ring.append({
                "id": self._seq,
                "ts": round(time.time(), 3),
                "kind": "mode_change",
                "from": prev,
                "to": mode,
            })
        logger.info("knob controller mode: %s -> %s", prev, mode)
        return mode

    # -- breach listener (the rollback trigger) -----------------------------
    def install(self, *engines: Any) -> None:
        """Ride the same breach-listener seam IncidentCapture uses
        (obs/slo.py ``add_breach_listener``): a breach inside the
        newest step's cooldown window indicts that step."""
        for engine in engines:
            engine.add_breach_listener(self.on_breach)

    def on_breach(self, entry: Dict[str, Any]) -> None:
        """Arm a rollback when a breach lands inside the cooldown of
        the newest applied step. Non-blocking — the actual (audited)
        rollback runs on the controller's own loop, never on the SLO
        engine's evaluation thread."""
        now = self._clock()
        with self._lock:
            lc = self._last_change
            if lc is None or self._rollback_pending is not None:
                return
            if now >= lc["cooldownUntil"] or self._last_good is None:
                return
            self._rollback_pending = {
                "slo": entry.get("name"),
                "knob": lc["knob"],
                "decisionId": lc["decisionId"],
                "ts": round(time.time(), 3),
            }
        logger.warning(
            "knob controller: SLO %r breached inside cooldown of "
            "knob %r step (decision #%s) — rollback armed",
            entry.get("name"), lc["knob"], lc["decisionId"])

    # -- signal resolution --------------------------------------------------
    def _resolve_recorder(self) -> Any:
        if self._recorder_fn is not None:
            return self._recorder_fn()
        from incubator_predictionio_tpu.obs import recorder as obs_rec

        return obs_rec.get_recorder()

    def _ctx(self) -> Dict[str, float]:
        """Objectives the decide rules climb against. The serve p99
        objective is the serve_p99 SLO threshold (obs/slo.py), so the
        knob loop and the burn engine defend the SAME number."""
        from incubator_predictionio_tpu.obs import slo as obs_slo

        p99_objective = 0.25
        for spec in obs_slo.default_specs():
            if spec.name == "serve_p99":
                p99_objective = float(spec.threshold)
        return {
            "p99ObjectiveS": p99_objective,
            "recallTarget": self.config.recall_target,
            "recallMargin": self.config.recall_margin,
            "foldObjectiveS": self.config.fold_objective_s,
        }

    def _read_inputs(self) -> Optional[Dict[str, Any]]:
        """One inputs snapshot from the flight recorder's trailing
        window. None = nothing recorded yet (reason="no_data")."""
        rec = self._resolve_recorder()
        if rec is None:
            return None
        win = rec.window(series=INPUT_SERIES,
                         window_s=self.config.window_s)
        if win.get("samples", 0) < 2:
            return None
        ser = win.get("series", {})
        inputs = {
            "p99S": _hist_window_p99(
                ser.get("pio_query_latency_seconds")),
            "queueP99S": _hist_window_p99(
                ser.get("pio_serve_queue_wait_seconds")),
            "shedPerS": _counter_window_rate(
                ser.get("pio_serve_shed_total")),
            "recall": _gauge_window_last(
                ser.get("pio_serve_mips_recall"), worst=min),
            "foldP99S": _hist_window_p99(
                ser.get("pio_freshness_fold_seconds")),
            # MIPS lifecycle gauges (worst = max: the most-lagged
            # engine/index is what the rebuild knobs defend)
            "tailRows": _gauge_window_last(
                ser.get("pio_mips_tail_size"), worst=max),
            "indexAgeS": _gauge_window_last(
                ser.get("pio_mips_index_age_seconds"), worst=max),
            "samples": win.get("samples", 0),
            "windowS": win.get("windowS"),
        }
        if all(inputs[k] is None for k in
               ("p99S", "queueP99S", "shedPerS", "recall")):
            return None
        return inputs

    # -- one evaluation -----------------------------------------------------
    def evaluate_once(self) -> Optional[Dict[str, Any]]:
        """One controller pass: read the window, run every knob's
        decide rule, gate, and step AT MOST ONE knob (coordinate
        descent keeps every decision attributable to one cause).
        Returns the appended decision record (None only in off mode)."""
        mode = self.mode
        if mode == "off":
            return None
        _EVALUATIONS.inc()
        now = self._clock()
        with self._lock:
            self._seq += 1
            decision: Dict[str, Any] = {
                "id": self._seq,
                "traceId": f"knb-{secrets.token_hex(6)}",
                "ts": round(time.time(), 3),
                "kind": "evaluation",
                "mode": mode,
                "inputs": None,
                "knobs": {},
                "knob": None,
                "action": "none",
                "reason": None,
                "outcome": None,
                # pre-seeded so _apply's fill-in replaces values
                # without resizing a dict a concurrent GET /knobs may
                # be rendering
                "spanId": None,
            }
            pending = self._rollback_pending

        if pending is not None:
            return self._rollback(decision, pending, mode)

        try:
            inputs = self._read_inputs()
        except Exception as e:  # recorder race ≠ controller crash
            logger.warning("knob controller inputs read failed: %s", e)
            decision["reason"] = "inputs_error"
            decision["error"] = str(e)
            # a blind evaluation breaks every consecutive-desire chain:
            # hysteresis must never count across a gap it could not see
            with self._lock:
                self._streaks.clear()
            self._append(decision)
            return decision
        if inputs is None:
            decision["reason"] = "no_data"
            with self._lock:
                self._streaks.clear()
            self._append(decision)
            return decision
        decision["inputs"] = inputs
        ctx = self._ctx()

        caps: Optional[Dict[str, int]] = None
        if self._capacity_fn is not None:
            try:
                caps = self._capacity_fn()
            except Exception:
                logger.exception("knob capacity guard failed "
                                 "(treated as no guard)")

        picked: Optional[Tuple[KnobSpec, int, int, int, str]] = None
        gates: List[str] = []
        with self._lock:
            believed = dict(self._vector)
        for spec in self.specs:
            value = believed[spec.env]
            try:
                desire, why = spec.decide(value, inputs, ctx)
            except Exception:
                logger.exception("knob %s decide rule failed",
                                 spec.name)
                desire, why = 0, None
            with self._lock:
                s = self._streaks.get(spec.name, 0)
                if desire == 0:
                    s = 0
                elif s == 0 or (s > 0) == (desire > 0):
                    s += desire
                else:
                    s = desire
                self._streaks[spec.name] = s
                cooldown_until = self._cooldowns.get(spec.name, 0.0)
            entry: Dict[str, Any] = {
                "value": value, "desire": desire, "why": why,
                "streak": s,
            }
            decision["knobs"][spec.name] = entry
            if desire == 0:
                continue
            if abs(s) < self.config.hysteresis_evals:
                entry["gate"] = "hysteresis"
                gates.append("hysteresis")
                continue
            if now < cooldown_until:
                entry["gate"] = "cooldown"
                entry["cooldownRemainingS"] = round(
                    cooldown_until - now, 3)
                gates.append("cooldown")
                continue
            proposed = spec.step(value, desire)
            if proposed == value:
                entry["gate"] = "bound"
                gates.append("bound")
                continue
            cap = caps.get(spec.name) if caps else None
            if desire > 0 and cap is not None and proposed > cap:
                # the measured fit says this step overruns capacity:
                # capacity, not tuning, is the binding constraint
                # (runbook: add chips, the knob cannot climb its way
                # out)
                entry["gate"] = "capacity"
                entry["capacityMax"] = cap
                gates.append("capacity")
                continue
            if picked is None:
                entry["gate"] = "selected"
                picked = (spec, value, proposed, desire, why or "")
            else:
                # one step per evaluation; this knob keeps its streak
                # and goes first next pass if still desiring
                entry["gate"] = "queued"

        if picked is None:
            for reason in ("capacity", "cooldown", "hysteresis",
                           "bound"):
                if reason in gates:
                    decision["reason"] = reason
                    break
            else:
                decision["reason"] = "healthy"
            self._append(decision)
            return decision

        spec, value, proposed, desire, why = picked
        decision["knob"] = spec.name
        decision["action"] = "step_up" if desire > 0 else "step_down"
        decision["from"] = value
        decision["to"] = proposed
        decision["reason"] = why
        if mode == "observe":
            decision["outcome"] = {"actuated": False, "dryRun": True}
            self._append(decision)
            return decision
        if self._apply_fn is None:
            decision["reason"] = "no_actuator"
            self._append(decision)
            return decision

        # -- act ------------------------------------------------------------
        # the record lands in the ring BEFORE actuation (marked
        # in-flight) and is updated in place on completion, same
        # contract as the freshness controller's ring
        decision["outcome"] = {"actuated": True, "inFlight": True}
        with self._lock:
            self._last_action = decision
            previous = dict(self._vector)
        vector = dict(previous)
        vector[spec.env] = proposed
        self._append(decision)
        self._apply(decision, vector)
        if decision["outcome"].get("actuated"):
            # counted AFTER the fan-out: the metric means steps that
            # actually landed, never attempts
            _ADJUSTMENTS.labels(knob=spec.name).inc()
        with self._lock:
            if decision["outcome"].get("actuated"):
                self._adjustments += 1
                # cooldown counts from actuation COMPLETION; the
                # rollback arming window is the same wall, so a breach
                # during the fan-out itself already indicts this step
                self._streaks[spec.name] = 0
                self._cooldowns[spec.name] = \
                    self._clock() + self.config.cooldown_s
                self._last_good = previous
                self._last_change = {
                    "knob": spec.name,
                    "decisionId": decision["id"],
                    "cooldownUntil": self._cooldowns[spec.name],
                }
            # a FAILED apply leaves streak and cooldown untouched:
            # the knob stays eligible and the next evaluation
            # re-proposes the same step instead of freezing for a
            # cooldown the fleet never earned
        return decision

    def _rollback(self, decision: Dict[str, Any],
                  pending: Dict[str, Any],
                  mode: str) -> Dict[str, Any]:
        """The incident path: restore the last-known-good vector as a
        normal audited decision, then re-arm (streaks cleared, every
        knob cooled down) so the climb restarts from scratch."""
        decision["action"] = "rollback"
        decision["reason"] = "incident"
        decision["knob"] = pending.get("knob")
        decision["incident"] = {
            "slo": pending.get("slo"),
            "steppedBy": pending.get("decisionId"),
        }
        with self._lock:
            target = (dict(self._last_good)
                      if self._last_good is not None else None)
            decision["fromVector"] = dict(self._vector)
            decision["toVector"] = target
        if mode != "act" or target is None or self._apply_fn is None:
            decision["outcome"] = {"actuated": False, "dryRun": True}
            with self._lock:
                self._rollback_pending = None
                self._last_change = None
            self._append(decision)
            return decision
        decision["outcome"] = {"actuated": True, "inFlight": True}
        with self._lock:
            self._last_action = decision
        self._append(decision)
        self._apply(decision, target)
        if decision["outcome"].get("actuated"):
            # counted on completion only — a pending rollback retried
            # across ticks is ONE rollback, not one per attempt
            _ROLLBACKS.inc()
        with self._lock:
            if decision["outcome"].get("actuated"):
                self._rollbacks += 1
                self._rollback_pending = None
                self._last_change = None
                self._last_good = None
                self._streaks.clear()
                cooled = self._clock() + self.config.cooldown_s
                for spec in self.specs:
                    self._cooldowns[spec.name] = cooled
            # a failed fan-out leaves the rollback PENDING: the next
            # tick retries rather than abandoning a known-bad vector
        return decision

    # -- the decision-record emitter (the ONE sanctioned actuation site) ----
    def _apply(self, decision: Dict[str, Any],
               vector: Dict[str, int]) -> None:
        """Push ``vector`` through the actuator inside the decision's
        trace context and write the outcome into the record. The
        fleet's ``/knobs`` HTTP hops forward the decision's trace ID,
        so the stitcher joins the whole fan-out under this decision
        span. The unaudited-knob-write lint rule pins that knob
        mutations happen here (or in the ``/knobs`` routes the fan-out
        lands on) and nowhere else."""
        span_id = obs_trace.new_span_id()
        decision["spanId"] = span_id
        token = obs_trace.set_current(decision["traceId"])
        span_token = obs_trace.set_current_span(span_id)
        t0 = time.perf_counter()
        outcome: Dict[str, Any] = {"actuated": True}
        try:
            t_a = time.perf_counter()
            try:
                result = self._apply_fn(dict(vector))
                failed = _fanout_failures(result)
                if failed:
                    # a 200 from the door with workers in its
                    # ``failed`` list is a split fleet, not a success:
                    # keep the old belief exactly as if the call had
                    # raised, so the next evaluation re-proposes
                    logger.warning(
                        "knob apply rejected by part of the fleet "
                        "(%s) — belief held", ", ".join(failed))
                    outcome["actuated"] = False
                    outcome["apply"] = {
                        "ok": False,
                        "failed": failed,
                        "result": result,
                        "wallS": round(time.perf_counter() - t_a, 3),
                    }
                else:
                    outcome["apply"] = {
                        "ok": True,
                        "result": result,
                        "wallS": round(time.perf_counter() - t_a, 3),
                    }
                    obs_trace.log_stage_span(
                        "knob.apply", decision["traceId"],
                        time.perf_counter() - t_a,
                        spanId=obs_trace.new_span_id(),
                        parentSpanId=span_id,
                        decisionId=decision["id"],
                        knob=decision.get("knob"))
                    with self._lock:
                        self._vector = dict(vector)
                    for spec in self.specs:
                        if spec.env in vector:
                            _VALUE.labels(knob=spec.name).set(
                                float(vector[spec.env]))
            except Exception as e:
                logger.exception("knob apply failed")
                # a failed fan-out leaves the OLD vector authoritative:
                # the controller's belief only moves on success, so the
                # next evaluation re-proposes rather than drifting
                outcome["actuated"] = False
                outcome["apply"] = {
                    "ok": False,
                    "error": str(e),
                    "wallS": round(time.perf_counter() - t_a, 3),
                }
        finally:
            outcome["wallS"] = round(time.perf_counter() - t0, 3)
            decision["outcome"] = outcome
            # the decision ROOT span, emitted after actuation so its
            # duration covers the whole fan-out
            obs_trace.log_stage_span(
                "knob.decision", decision["traceId"],
                time.perf_counter() - t0,
                spanId=span_id,
                decisionId=decision["id"],
                action=decision["action"],
                reason=decision["reason"],
                knob=decision.get("knob"))
            obs_trace.reset_current_span(span_token)
            obs_trace.reset_current(token)

    # -- ring / introspection -----------------------------------------------
    def _append(self, decision: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(decision)

    def decisions(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first slice of the decision ring."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:max(int(limit), 0)]

    def values(self) -> Dict[str, int]:
        """The live vector, keyed by env var."""
        with self._lock:
            return dict(self._vector)

    def stats(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            lc = self._last_change
            return {
                # inline (not the property): self._lock is not reentrant
                "mode": self._mode_override or knobs_mode(),
                "running": self._thread is not None
                and self._thread.is_alive(),
                "intervalS": self.config.interval_s,
                "hysteresisEvals": self.config.hysteresis_evals,
                "cooldownS": self.config.cooldown_s,
                "windowS": self.config.window_s,
                "knobs": {
                    s.name: {
                        "env": s.env,
                        "value": self._vector[s.env],
                        "lo": s.lo,
                        "hi": s.hi,
                        "streak": self._streaks.get(s.name, 0),
                        "cooldownRemainingS": round(max(
                            self._cooldowns.get(s.name, 0.0) - now,
                            0.0), 3),
                    } for s in self.specs
                },
                "adjustments": self._adjustments,
                "rollbacks": self._rollbacks,
                "rollbackArmed": lc is not None
                and now < lc["cooldownUntil"],
                "rollbackPending": self._rollback_pending is not None,
                "decisionsRecorded": self._seq,
                "lastAction": self._last_action,
                "actuators": {
                    "apply": self._apply_fn is not None,
                    "scope": self._apply_scope,
                    "capacityGuard": self._capacity_fn is not None,
                },
            }

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Start the background loop (idempotent; same per-generation
        stop-event discipline as the freshness controller, so a timed-
        out stop can never leave two live loops)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive() \
                    and not self._stop.is_set():
                return
            stop = threading.Event()
            self._stop = stop
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="pio-knob-controller", daemon=True)
            self._thread.start()

    def _loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.evaluate_once()
            except Exception:
                logger.exception("knob evaluation failed")
            stop.wait(self.config.interval_s)

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            stop = self._stop
            t = self._thread
        stop.set()
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                return
        with self._lock:
            if self._thread is t:
                self._thread = None


# ---------------------------------------------------------------------------
# process-wide controller (the admin server's instance; tests reset)
# ---------------------------------------------------------------------------

_knob_controller: Optional[KnobController] = None
_knob_lock = threading.Lock()


def get_knob_controller() -> KnobController:
    """The process knob controller, wired from the environment: the
    actuator POSTs the front door's fleet ``/knobs`` when
    ``PIO_KNOBS_URL`` names it (``PIO_KNOBS_KEY`` authes it), else
    writes this process's own env (single-process deployments); the
    capacity guard engages when the measured fit exposes knob
    ceilings."""
    global _knob_controller
    with _knob_lock:
        if _knob_controller is None:
            url = os.environ.get("PIO_KNOBS_URL", "").strip()
            if not url and knobs_mode() == "act":
                # a forgotten URL in act mode silently tunes ONE
                # process while the fleet serves the old vector —
                # loud here, and visible in stats() actuators.scope
                logger.warning(
                    "PIO_KNOBS=act with PIO_KNOBS_URL unset: the "
                    "knob actuator writes only THIS process's env; "
                    "no fleet worker will see adjustments. Set "
                    "PIO_KNOBS_URL to the front door's /knobs for "
                    "multi-worker deployments.")
            cap_fn = capacity_caps_fn()
            if cap_fn() is None:
                # inert guard reported honestly as absent (stats()'
                # actuators.capacityGuard must mean "can veto")
                cap_fn = None
            _knob_controller = KnobController(
                apply_fn=(http_knobs_fn(
                    url, os.environ.get("PIO_KNOBS_KEY") or None)
                    if url else local_knobs_fn()),
                capacity_fn=cap_fn,
                apply_scope="fleet" if url else "local",
            )
        return _knob_controller


def reset_knob_controller() -> None:
    """Drop (and stop) the process knob controller — tests re-read the
    PIO_KNOBS_* env on next use."""
    global _knob_controller
    with _knob_lock:
        if _knob_controller is not None:
            _knob_controller.stop(timeout=2.0)
        _knob_controller = None


def peek_knob_decisions(limit: int = 256) -> List[Dict[str, Any]]:
    """The knob decision ring WITHOUT creating a controller — the
    incident capture's ``knobs_fn`` default (obs/recorder.py): a bundle
    frozen on a process that never ran the knob loop records an empty
    audit trail rather than instantiating one as a side effect."""
    with _knob_lock:
        c = _knob_controller
    return c.decisions(limit=limit) if c is not None else []


__all__ = [
    "ACTION_REASONS", "INPUT_SERIES", "KNOB_ENV_VARS", "KnobConfig",
    "KnobController", "KnobSpec", "MODES", "SKIP_REASONS",
    "capacity_caps_fn", "default_knobs", "get_knob_controller",
    "http_knobs_fn", "knobs_mode", "local_knobs_fn",
    "peek_knob_decisions", "reset_knob_controller",
]
