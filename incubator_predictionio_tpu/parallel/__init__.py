"""Device mesh, sharding and runtime context.

This package replaces the reference's Spark cluster runtime: where the
reference hands every DASE component a ``SparkContext``
(core/.../core/BaseDataSource.scala:43, BaseAlgorithm.scala:69), this
framework hands them a :class:`RuntimeContext` carrying a
``jax.sharding.Mesh`` over the TPU slice plus run configuration. Collectives
ride XLA (psum/all_gather/reduce_scatter over ICI/DCN) instead of Spark
shuffles.
"""

from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    make_mesh,
    mesh_shape_for,
    device_count,
)

__all__ = [
    "RuntimeContext",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "make_mesh",
    "mesh_shape_for",
    "device_count",
]
