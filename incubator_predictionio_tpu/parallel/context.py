"""RuntimeContext — what every DASE component receives instead of a
SparkContext (reference: WorkflowContext.scala builds the SparkContext; the
``sc`` parameter threads through BaseDataSource/BasePreparator/BaseAlgorithm).

Carries the device mesh, a deterministic PRNG stream, and run configuration.
Construction is lazy: pure-host engines (event property work, tests of the
controller wiring) never touch JAX at all.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class RuntimeContext:
    def __init__(
        self,
        mesh: Optional[Any] = None,
        seed: int = 0,
        conf: Optional[Dict[str, Any]] = None,
        model_parallelism: int = 1,
    ):
        self._mesh = mesh
        self.seed = seed
        self.conf: Dict[str, Any] = dict(conf or {})
        self.model_parallelism = model_parallelism
        self._rng_lock = threading.Lock()
        self._rng_count = 0
        self._rng_key = None

    @property
    def mesh(self):
        """The device mesh, created on first use."""
        if self._mesh is None:
            from incubator_predictionio_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(model_parallelism=self.model_parallelism)
        return self._mesh

    def next_rng(self):
        """A fresh jax PRNG key, deterministic in ``seed`` and call order."""
        import jax

        with self._rng_lock:
            if self._rng_key is None:
                self._rng_key = jax.random.key(self.seed)
            self._rng_key, sub = jax.random.split(self._rng_key)
            self._rng_count += 1
            return sub

    def stop(self) -> None:
        """SparkContext.stop parity — drop the mesh so serving processes can
        release any compile caches tied to it (Engine.scala:258 stops sc once
        models are local)."""
        self._mesh = None

    def __repr__(self) -> str:
        mesh = self._mesh.shape if self._mesh is not None else "lazy"
        return f"RuntimeContext(mesh={mesh}, seed={self.seed})"
