"""Pod launcher — spawn and supervise one worker process per host.

The reference's ``pio train``/``deploy`` don't just *join* a cluster run,
they *launch* it: Runner.runOnSpark (tools/.../Runner.scala:101-213)
builds the spark-submit command line and forwards every ``PIO_*`` env var
to the executors (Runner.scala:129-131). This is the TPU-pod equivalent:
given N hosts, spawn the SAME pio command on each with the coordinator
env trio set (``PIO_COORDINATOR_ADDRESS`` / ``PIO_NUM_PROCESSES`` /
``PIO_PROCESS_ID`` — consumed by parallel/distributed.py
``ensure_initialized``), stream per-host logs with a host prefix, and
supervise: the first failing worker tears the rest down, spark-driver
style.

Host specs: ``local`` / ``localhost`` / ``127.0.0.1`` spawn directly;
anything else goes through ``ssh <host> env K=V... <cmd>`` (ssh does not
forward environment, so the trio + PIO_* vars ride the command line).
Process 0 runs on the first host, which also hosts the coordinator.
"""

from __future__ import annotations

import logging
import os
import shlex
import socket
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

_LOCAL = {"local", "localhost", "127.0.0.1"}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _is_local(host: str) -> bool:
    return host.split("@")[-1] in _LOCAL


class PodLauncher:
    """Launch ``argv`` once per host with the coordinator trio set."""

    def __init__(
        self,
        hosts: Sequence[str],
        argv: Sequence[str],
        coordinator_port: Optional[int] = None,
        env_extra: Optional[Dict[str, str]] = None,
        ssh: Sequence[str] = ("ssh", "-o", "BatchMode=yes"),
    ):
        if not hosts:
            raise ValueError("need at least one host")
        self.hosts = list(hosts)
        self.argv = list(argv)
        self.ssh = list(ssh)
        self.env_extra = dict(env_extra or {})
        # the coordinator lives on host 0. A local host 0 can pick a free
        # port here; for a remote host 0 an auto-picked port would be
        # validated on the WRONG machine, so it must be given explicitly
        # (PIO_COORDINATOR_PORT / --coordinator-port).
        first = self.hosts[0]
        if coordinator_port is None and not _is_local(first):
            coordinator_port = int(
                os.environ.get("PIO_COORDINATOR_PORT", "0")) or None
            if coordinator_port is None:
                raise ValueError(
                    f"host 0 ({first}) is remote: pass coordinator_port "
                    "(or set PIO_COORDINATOR_PORT) — a port picked on "
                    "this machine is not known to be free there")
        self.port = coordinator_port or _free_port()
        if not _is_local(first):
            coord_host = first.split("@")[-1]
        elif all(_is_local(h) for h in self.hosts):
            coord_host = "127.0.0.1"
        else:
            # host 0 is this machine but other workers are remote: loopback
            # would point each remote worker at itself — advertise a
            # reachable name (override with PIO_COORDINATOR_HOST when the
            # default hostname doesn't resolve from the workers)
            coord_host = os.environ.get(
                "PIO_COORDINATOR_HOST") or socket.getfqdn()
        self.coordinator = f"{coord_host}:{self.port}"
        self.procs: List[subprocess.Popen] = []

    def _worker_env(self, process_id: int) -> Dict[str, str]:
        env = {
            "PIO_COORDINATOR_ADDRESS": self.coordinator,
            "PIO_NUM_PROCESSES": str(len(self.hosts)),
            "PIO_PROCESS_ID": str(process_id),
        }
        # PIO_* forwarding parity (Runner.scala:129-131)
        env.update({
            k: v for k, v in os.environ.items()
            if k.startswith("PIO_") and k not in env
        })
        env.update(self.env_extra)
        return env

    def _spawn(self, host: str, process_id: int) -> subprocess.Popen:
        wenv = self._worker_env(process_id)
        if _is_local(host):
            env = dict(os.environ)
            env.update(wenv)
            cmd = self.argv
        else:
            env = dict(os.environ)
            cmd = self.ssh + [host, "env"] + [
                f"{k}={shlex.quote(v)}" for k, v in wenv.items()
            ] + [shlex.quote(a) for a in self.argv]
        logger.info("pod launcher: process %d on %s", process_id, host)
        return subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, errors="replace",
        )

    @staticmethod
    def _stream(proc: subprocess.Popen, tag: str, sink) -> None:
        for line in proc.stdout:  # type: ignore[union-attr]
            sink(f"[{tag}] {line.rstrip()}")

    def launch(self, sink=None, timeout: Optional[float] = None) -> int:
        """Run all workers to completion → worst exit code.

        The first non-zero exit terminates the remaining workers (a pod
        program cannot make progress minus one participant — collectives
        would deadlock)."""
        sink = sink or (lambda line: print(line, file=sys.stderr))
        self.procs = [
            self._spawn(host, i) for i, host in enumerate(self.hosts)
        ]
        streams = [
            threading.Thread(
                target=self._stream, args=(p, f"{i}:{h}", sink), daemon=True)
            for i, (p, h) in enumerate(zip(self.procs, self.hosts))
        ]
        for t in streams:
            t.start()
        rc = 0
        try:
            pending = set(range(len(self.procs)))
            import time as _time
            deadline = None if timeout is None else _time.time() + timeout
            while pending:
                for i in list(pending):
                    r = self.procs[i].poll()
                    if r is None:
                        continue
                    pending.discard(i)
                    if r != 0:
                        rc = rc or (128 - r if r < 0 else r)
                        logger.error(
                            "pod launcher: process %d (%s) exited %d — "
                            "terminating the pod", i, self.hosts[i], r)
                        self.terminate()
                        pending.clear()
                        break
                if pending:
                    if deadline is not None and _time.time() > deadline:
                        logger.error("pod launcher: timeout — terminating")
                        self.terminate()
                        rc = rc or 124
                        break
                    _time.sleep(0.05)
        finally:
            self.terminate()
            for p in self.procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    # a worker holding a SIGTERM handler (grpc coordinator
                    # threads) must not wedge the launcher: escalate
                    p.kill()
                    p.wait()
            for t in streams:
                t.join(timeout=5)
        for p in self.procs:
            code = p.returncode or 0
            if code and not rc:
                # normalize signal deaths (negative returncode) to the
                # shell convention so a crashed worker can never be
                # masked to success by a clean sibling
                rc = 128 - code if code < 0 else code
        return rc

    def terminate(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()


def relaunch_over_hosts(hosts: Sequence[str],
                        extra_env: Optional[Dict[str, str]] = None,
                        argv: Optional[Sequence[str]] = None) -> int:
    """Re-run THIS pio invocation once per host (minus its ``--hosts``
    flag), coordinator trio set — the CLI hook for
    ``pio train --hosts h1,h2``. ``argv`` is the pio argument list
    (without the program name); defaults to sys.argv[1:] for the
    command-line entry point. Returns the pod's exit code."""
    source = list(argv) if argv is not None else sys.argv[1:]
    argv = [sys.executable, "-m", "incubator_predictionio_tpu.cli.main"]
    skip_next = False
    for a in source:
        if skip_next:
            skip_next = False
            continue
        if a == "--hosts":
            skip_next = True
            continue
        if a.startswith("--hosts="):
            continue
        argv.append(a)
    return PodLauncher(hosts, argv, env_extra=extra_env).launch()
