"""Multi-host (multi-process) runtime for TPU pods and pod slices.

The reference scales out by submitting to a Spark cluster
(tools/.../Runner.scala:101-213 builds the spark-submit line; executors talk
through Spark's shuffle service). The TPU-native equivalent is JAX's
multi-controller runtime: one Python process per host, every process runs
the same program, and arrays are globally sharded over all hosts' devices —
collectives ride ICI inside a slice and DCN across slices.

``ensure_initialized`` is the single entry point; it is safe to call on a
laptop (no-op), under pytest's forced-CPU mesh, and on a real pod where the
coordinator env vars are set.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

_initialized = False
#: True only when this process actually joined a multi-controller runtime
_multiprocess = False


def ensure_initialized() -> bool:
    """Initialize ``jax.distributed`` when a coordinator is configured.

    Configuration comes from the standard JAX env vars (auto-detected on
    Cloud TPU) or the explicit ``PIO_COORDINATOR_ADDRESS`` /
    ``PIO_NUM_PROCESSES`` / ``PIO_PROCESS_ID`` trio, mirroring how the
    reference forwards ``PIO_*`` env across process boundaries
    (Runner.scala:129-131). Returns True when running multi-process.
    """
    global _initialized, _multiprocess
    if _initialized:
        return jax.process_count() > 1
    coord = os.environ.get("PIO_COORDINATOR_ADDRESS")
    if coord and "PIO_NUM_PROCESSES" not in os.environ:
        # fail loudly: silently defaulting to 1 would make every host of
        # a misconfigured pod train its own duplicate model
        raise RuntimeError(
            "PIO_COORDINATOR_ADDRESS is set but PIO_NUM_PROCESSES is not "
            "— set the full coordinator env trio (launcher.py does)")
    n_proc = int(os.environ.get("PIO_NUM_PROCESSES", "1") or 1)
    if coord and n_proc <= 1:
        # a 1-host pod has nothing to coordinate: plain single-controller
        # JAX is the correct runtime (and distributed.initialize with a
        # 1-process service hangs under proxied/tunneled device platforms)
        logger.info("distributed: single process — coordinator skipped")
        coord = None
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=n_proc,
            process_id=int(os.environ["PIO_PROCESS_ID"]),
        )
        _multiprocess = True
        logger.info(
            "distributed: process %d/%d via coordinator %s",
            jax.process_index(), jax.process_count(), coord,
        )
    _initialized = True
    return jax.process_count() > 1


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_multihost() -> bool:
    return jax.process_count() > 1


def barrier(name: str) -> None:
    """Pod-wide sync point: returns only when EVERY process has reached it.

    Used as the completion gate before process 0 persists an
    EngineInstance as COMPLETED — a worker that crashed mid-train leaves
    its peers parked here until the launcher tears the pod down, so a
    failed pod run can never publish a COMPLETED instance (the
    supervision contract of Runner.scala:101-213, proven by
    tests/test_launcher.py's killed-worker drill). No-op off-pod.

    Gates on an ACTUAL multi-controller runtime — either one this module
    joined (``_multiprocess``) or an externally-provisioned
    ``jax.distributed`` client (Cloud TPU auto-init) — NOT on
    process_count(): tests fake process counts to simulate pod roles in
    one process, and the sync primitive only functions on a real
    runtime."""
    if not _runtime_active() or jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def _runtime_active() -> bool:
    """True when a jax.distributed client genuinely exists in this
    process, however it was initialized."""
    if _multiprocess:
        return True
    try:  # externally-provisioned runtime (auto-init on Cloud TPU)
        from jax._src import distributed as _jax_distributed

        return getattr(_jax_distributed.global_state, "client",
                       None) is not None
    except Exception:  # pragma: no cover - private-API drift
        return False


def is_pod_worker() -> bool:
    """True on a multi-process pod's non-zero processes — the ones that
    run the SPMD program but never own storage writes (the Spark
    executor role; CoreWorkflow gates persistence on this)."""
    return jax.process_count() > 1 and jax.process_index() != 0


def make_pod_mesh(
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A named mesh over all (global) devices, DCN-aware on multi-host.

    The FIRST axis is the cross-host axis: on a real multi-slice topology it
    is laid out over DCN (via ``create_hybrid_device_mesh``) so that only
    that axis's collectives cross the data-center network, while every later
    axis stays inside a slice on ICI — put ``dp`` first and ``mp``/``sp``
    after it (the scaling-book layout).

    ``axis_sizes`` may use -1 once to absorb the remaining device count.
    """
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    sizes = list(axis_sizes)
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        if len(devs) % known != 0:
            raise ValueError(
                f"{len(devs)} devices not divisible by fixed axes {known}"
            )
        sizes[sizes.index(-1)] = len(devs) // known
    total = 1
    for s in sizes:
        total *= s
    if total != len(devs):
        raise ValueError(
            f"mesh {dict(zip(axis_names, sizes))} needs {total} devices, "
            f"have {len(devs)}"
        )

    if is_multihost() and devices is None:
        from jax.experimental import mesh_utils

        per_host = sizes[0] // jax.process_count() or 1
        try:
            grid = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(per_host, *sizes[1:]),
                dcn_mesh_shape=(sizes[0] // per_host,) + (1,) * (len(sizes) - 1),
            )
            return Mesh(grid, tuple(axis_names))
        except Exception:
            logger.warning(
                "hybrid DCN mesh layout failed; falling back to flat device "
                "order (collectives on the first axis may cross DCN "
                "suboptimally)", exc_info=True,
            )
    grid = np.array(devs).reshape(*sizes)
    return Mesh(grid, tuple(axis_names))


def host_local_batch_slice(global_batch: int) -> slice:
    """Which rows of a global batch this host is responsible for feeding.

    Multi-host input pipelines load only their slice and form global arrays
    with ``jax.make_array_from_process_local_data``; this gives the row
    range, replacing the reference's per-executor RDD partition assignment.
    """
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(
            f"global_batch={global_batch} is not divisible by "
            f"process_count={n}; remainder rows would silently be fed by "
            "no host — pad or trim the batch first"
        )
    per = global_batch // n
    start = per * jax.process_index()
    return slice(start, start + per)


def global_array_from_local(local, sharding):
    """Assemble a globally-sharded array from this host's local rows."""
    return jax.make_array_from_process_local_data(sharding, local)
