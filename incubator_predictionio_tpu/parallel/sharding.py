"""Sharding placement helpers for the training data structures.

The single "sharding recipe" of the framework (scaling-book style): batch
dimensions shard over the whole mesh (both axes flattened), factor/parameter
tables are replicated (small) or sharded over ``mp`` (large). XLA/GSPMD
propagates these placements through the jitted sweeps and inserts the
collectives (all-gathers after scatter, psums in grads) on ICI.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from incubator_predictionio_tpu.ops.sparse import PaddedRows
from incubator_predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across every device (dp×mp flattened)."""
    return NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def model_sharding(mesh: Mesh) -> NamedSharding:
    """Parameter tables sharded on rows over the model axis (ALX layout)."""
    return NamedSharding(mesh, P(MODEL_AXIS))


# ---------------------------------------------------------------------------
# shard-blocked bucket layout (the FactorPlacement training-data layout)
# ---------------------------------------------------------------------------

def shard_block_bucket(bucket: PaddedRows, n_shards: int,
                       shard_rows: int) -> PaddedRows:
    """Regroup one padded bucket into ``n_shards`` equal contiguous row
    blocks ordered by owning shard (owner = row_id // shard_rows).

    The flat result shards on axis 0 over the mesh: device ``s`` sees
    exactly the rows it owns. ``row_ids`` stay GLOBAL here (the host
    mirror / prep-plan convention); :func:`localize_tree` converts to
    shard-local ids for the device trees. Padding rows (-1) fill each
    block to the common size.
    """
    ids = np.asarray(bucket.row_ids)
    live = np.flatnonzero(ids >= 0)
    owner = ids[live] // shard_rows
    counts = np.bincount(owner, minlength=n_shards)
    b = max(int(counts.max()) if len(live) else 0, 1)
    width = bucket.width
    row_ids = np.full(n_shards * b, -1, np.int32)
    cols = np.zeros((n_shards * b, width), np.int32)
    vals = np.zeros((n_shards * b, width), np.float32)
    mask = np.zeros((n_shards * b, width), np.float32)
    order = np.argsort(owner, kind="stable")
    src = live[order]
    # positions: contiguous within each owner's block
    within = np.arange(len(src)) - np.repeat(
        np.cumsum(counts) - counts, counts)
    pos = owner[order] * b + within
    row_ids[pos] = ids[src]
    cols[pos] = bucket.cols[src]
    vals[pos] = bucket.vals[src]
    mask[pos] = bucket.mask[src]
    return PaddedRows(row_ids=row_ids, cols=cols, vals=vals, mask=mask)


def shard_block_buckets(buckets: Sequence[PaddedRows], n_shards: int,
                        shard_rows: int) -> list[PaddedRows]:
    return [shard_block_bucket(b, n_shards, shard_rows) for b in buckets]


def shard_block_heavy(heavy, n_shards: int, shard_rows: int):
    """Shard-block :class:`~...ops.sparse.HeavySegments`: split-row
    segments regroup by the owning shard of their row, padded to common
    per-shard segment/row counts, ids localized.

    Per-device view inside shard_map: ``(seg_ids [S], row_ids [H],
    cols/vals/mask [S, W])`` — exactly the single-chip heavy tuple, so
    ``ops.als._solve_heavy`` runs verbatim per shard (the partial-Gram
    reduction stays shard-local: a row's segments all live with its
    owner). Padding segments point at segment 0 with zero mask; padding
    row slots carry id −1 (solved to 0, dropped at scatter).
    """
    if heavy is None:
        return None
    seg_ids = np.asarray(heavy.seg_ids)
    row_ids = np.asarray(heavy.row_ids)
    owner_row = row_ids // shard_rows
    # host-side numpy over sparse.split_heavy output: every seg_id maps
    # a real split segment to its row slot, never a -1 padding sentinel
    owner_seg = owner_row[seg_ids]  # pio-lint: disable=neg-gather
    h_counts = np.bincount(owner_row, minlength=n_shards)
    s_counts = np.bincount(owner_seg, minlength=n_shards)
    h = max(int(h_counts.max()), 1)
    s = max(int(s_counts.max()), 1)
    w = heavy.cols.shape[1]
    out_rows = np.full(n_shards * h, -1, np.int32)
    out_seg = np.zeros((n_shards, s), np.int32)
    out_cols = np.zeros((n_shards * s, w), np.int32)
    out_vals = np.zeros((n_shards * s, w), np.float32)
    out_mask = np.zeros((n_shards * s, w), np.float32)
    # heavy rows: contiguous per owner block, LOCAL ids
    new_slot = np.empty(len(row_ids), np.int64)
    for sh in range(n_shards):
        rows_here = np.flatnonzero(owner_row == sh)
        new_slot[rows_here] = np.arange(len(rows_here))
        out_rows[sh * h + np.arange(len(rows_here))] = (
            row_ids[rows_here] - sh * shard_rows)
        segs_here = np.flatnonzero(owner_seg == sh)
        out_seg[sh, : len(segs_here)] = new_slot[seg_ids[segs_here]]
        dst = sh * s + np.arange(len(segs_here))
        out_cols[dst] = heavy.cols[segs_here]
        out_vals[dst] = heavy.vals[segs_here]
        out_mask[dst] = heavy.mask[segs_here]
    return (out_seg.reshape(n_shards * s), out_rows,
            out_cols, out_vals, out_mask)


def localize_tree(buckets: Sequence[PaddedRows], n_shards: int,
                  shard_rows: int):
    """Shard-blocked buckets → device trees with SHARD-LOCAL row ids
    (``ops.als._buckets_tree`` format). The owner of flat position ``p``
    is ``p // block`` by construction, so localization is arithmetic."""
    import jax.numpy as jnp

    out = []
    for b in buckets:
        ids = np.asarray(b.row_ids)
        block = len(ids) // n_shards
        owner = np.arange(len(ids)) // block
        local = np.where(ids >= 0, ids - owner * shard_rows, -1)
        out.append((jnp.asarray(local.astype(np.int32)),
                    jnp.asarray(b.cols), jnp.asarray(b.vals),
                    jnp.asarray(b.mask)))
    return tuple(out)


# ---------------------------------------------------------------------------
# ring layout: wide-table half-sweeps against rotating table slices
# ---------------------------------------------------------------------------

def _next_pow2_arr(m: np.ndarray) -> np.ndarray:
    """Elementwise smallest power of two ≥ m (m ≥ 1), integer bit-smear
    (no float log2 — exact at any width)."""
    v = np.asarray(m, np.int64) - 1
    for shift in (1, 2, 4, 8, 16, 32):
        v |= v >> shift
    return v + 1


def _width_class(d, min_width: int) -> np.ndarray:
    """The loop builder's width ladder, vectorized: ``w = min_width;
    while w < d: w *= 2`` ≡ ``min_width · next_pow2(ceil(d / min_width))``
    (the smallest min_width·2^k ≥ d)."""
    d = np.asarray(d, np.int64)
    m = np.maximum((d + min_width - 1) // min_width, 1)
    return min_width * _next_pow2_arr(m)


def _cumcount(key: np.ndarray) -> np.ndarray:
    """Occurrence index of each element within its key group, in input
    order — the vectorized twin of the loop builder's per-cell fill
    counters."""
    if len(key) == 0:
        return np.zeros(0, np.int64)
    o = np.argsort(key, kind="stable")
    sk = key[o]
    new_run = np.r_[True, sk[1:] != sk[:-1]]
    starts = np.flatnonzero(new_run)
    run_id = np.cumsum(new_run) - 1
    out = np.empty(len(key), np.int64)
    # run_id = cumsum(bool) - 1 ≥ 0 always (first element is True) —
    # no -1 padding sentinel can reach this host-side gather
    out[o] = np.arange(len(key)) - starts[run_id]  # pio-lint: disable=neg-gather
    return out


def build_ring_side_reference(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_shards: int,
    shard_rows_self: int,
    shard_rows_other: int,
    min_width: int = 8,
    max_width: int = 1 << 16,
):
    """The original per-(row, step) Python-loop ring builder, kept as
    the bitwise-parity oracle for :func:`build_ring_side` (the
    vectorized production path). O(pairs) Python-interpreter work —
    minutes at the 100M-row scale ring mode targets, which is exactly
    why the vectorized twin replaced it on the hot path
    (tests/test_sharded_als.py pins their outputs identical).

    At ring step ``s`` device ``r`` holds the other table's slice
    ``c = (r − s) mod n`` (``ppermute_next`` rotation), so every
    interaction is assigned to step ``s = (owner(row) − owner(col)) mod
    n`` and its col id is localized to that slice. Rows whose cols all
    live in ONE slice ("pure") solve completely at their step — the
    fused Gram+solve kernel applies with only the slice resident; rows
    spanning slices ("mixed") accumulate partial Grams across steps and
    solve once after the ring (the ALX cross-shard reduction,
    shard-local per owner).

    Returns ``(pure, mixed)``:

    - ``pure``: tuple per width class of ``(row_ids [n, steps, B],
      cols/vals/mask [n, steps, B, w])`` — dim 0 shards over the mesh,
      row ids local to the owner, col ids local to the step's slice.
    - ``mixed``: ``None`` or ``(row_ids [n, H], seg_ids [n, steps, S],
      cols/vals/mask [n, steps, S, W])`` with ``seg_ids`` indexing the
      local row list (padding → H, dropped after the segment sum).
    """
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals, np.float32)
    n = n_shards
    owner_r = rows // shard_rows_self
    owner_c = cols // shard_rows_other
    step = (owner_r - owner_c) % n
    # stable sort by (row, step): contiguous (row, step) segments
    order = np.lexsort((step, rows))
    rows_s, vals_s, step_s = rows[order], vals[order], step[order]
    loc_cols = (cols - owner_c * shard_rows_other)[order]

    uniq_rows, row_start, row_deg = np.unique(
        rows_s, return_index=True, return_counts=True)
    # distinct steps per row: count boundaries of (row, step) pairs
    pair_key = rows_s * n + step_s
    pair_uniq, pair_start, pair_cnt = np.unique(
        pair_key, return_index=True, return_counts=True)
    pair_row = pair_uniq // n
    pair_step = (pair_uniq % n).astype(np.int64)
    steps_per_row = np.bincount(
        np.searchsorted(uniq_rows, pair_row), minlength=len(uniq_rows))
    pure_mask_row = (steps_per_row == 1) & (row_deg <= max_width)
    row_is_pure = dict(
        zip(uniq_rows.tolist(), pure_mask_row.tolist()))

    # -- pure rows: bucket by (owner, step, width class) --------------------
    classes: dict[int, list] = {}
    mixed_pairs: list = []
    for pi in range(len(pair_uniq)):
        rid = int(pair_row[pi])
        if row_is_pure[rid]:
            d = int(pair_cnt[pi])
            w = min_width
            while w < d:
                w *= 2
            classes.setdefault(w, []).append(pi)
        else:
            mixed_pairs.append(pi)

    def _pair_block(pi):
        a, c = int(pair_start[pi]), int(pair_cnt[pi])
        return loc_cols[a:a + c], vals_s[a:a + c]

    pure = []
    for w in sorted(classes):
        members = classes[w]
        counts = np.zeros((n, n), np.int64)
        for pi in members:
            counts[int(pair_row[pi]) // shard_rows_self,
                   int(pair_step[pi])] += 1
        b = max(int(counts.max()), 1)
        rid_a = np.full((n, n, b), -1, np.int32)
        col_a = np.zeros((n, n, b, w), np.int32)
        val_a = np.zeros((n, n, b, w), np.float32)
        msk_a = np.zeros((n, n, b, w), np.float32)
        fill = np.zeros((n, n), np.int64)
        for pi in members:
            rid = int(pair_row[pi])
            sh, st = rid // shard_rows_self, int(pair_step[pi])
            k = int(fill[sh, st]); fill[sh, st] += 1
            c, v = _pair_block(pi)
            rid_a[sh, st, k] = rid - sh * shard_rows_self
            col_a[sh, st, k, : len(c)] = c
            val_a[sh, st, k, : len(v)] = v
            msk_a[sh, st, k, : len(c)] = 1.0
        pure.append((rid_a, col_a, val_a, msk_a))

    # -- mixed rows: per-step segments + shard-local row lists --------------
    mixed = None
    if mixed_pairs:
        mixed_rows = np.unique(pair_row[mixed_pairs])
        owner_m = mixed_rows // shard_rows_self
        h_counts = np.bincount(owner_m, minlength=n)
        h = max(int(h_counts.max()), 1)
        slot_of: dict[int, int] = {}
        rid_m = np.full((n, h), -1, np.int32)
        fill_h = np.zeros(n, np.int64)
        for rid in mixed_rows.tolist():
            sh = rid // shard_rows_self
            k = int(fill_h[sh]); fill_h[sh] += 1
            slot_of[rid] = k
            rid_m[sh, k] = rid - sh * shard_rows_self
        # split over-wide (row, step) groups into ≤ seg_w chunks
        segs: list = []  # (shard, step, slot, cols, vals)
        seg_w = 0
        for pi in mixed_pairs:
            rid = int(pair_row[pi])
            sh, st = rid // shard_rows_self, int(pair_step[pi])
            c, v = _pair_block(pi)
            cap = max_width
            for off in range(0, len(c), cap):
                cc, vv = c[off:off + cap], v[off:off + cap]
                segs.append((sh, st, slot_of[rid], cc, vv))
                seg_w = max(seg_w, len(cc))
        w = min_width
        while w < seg_w:
            w *= 2
        s_counts = np.zeros((n, n), np.int64)
        for sh, st, *_ in segs:
            s_counts[sh, st] += 1
        s_max = max(int(s_counts.max()), 1)
        sid_a = np.full((n, n, s_max), h, np.int32)  # sentinel → dropped
        col_a = np.zeros((n, n, s_max, w), np.int32)
        val_a = np.zeros((n, n, s_max, w), np.float32)
        msk_a = np.zeros((n, n, s_max, w), np.float32)
        fill = np.zeros((n, n), np.int64)
        for sh, st, slot, cc, vv in segs:
            k = int(fill[sh, st]); fill[sh, st] += 1
            sid_a[sh, st, k] = slot
            col_a[sh, st, k, : len(cc)] = cc
            val_a[sh, st, k, : len(vv)] = vv
            msk_a[sh, st, k, : len(cc)] = 1.0
        mixed = (rid_m, sid_a, col_a, val_a, msk_a)
    return tuple(pure), mixed


def build_ring_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_shards: int,
    shard_rows_self: int,
    shard_rows_other: int,
    min_width: int = 8,
    max_width: int = 1 << 16,
):
    """One orientation's interactions in the ring ragged-gather layout —
    the VECTORIZED host prep (ROADMAP item 1's flagged hot spot): every
    stage is numpy bucketing (sort → unique pairs → grouped cumcount →
    flat fancy-index scatters), no per-(row, step) Python iteration, and
    the output is BITWISE-IDENTICAL to
    :func:`build_ring_side_reference` (pinned in
    tests/test_sharded_als.py — same cell fill order, same padding).

    Layout semantics (see the reference's docstring for the full
    story): step ``s = (owner(row) − owner(col)) mod n``; rows whose
    cols all land in one slice are "pure" (solve at their step, fused
    kernel eligible), the rest are "mixed" (partial Grams across steps,
    solved post-ring). Returns ``(pure, mixed)`` in the shapes
    ``_ring_sweep_side`` consumes.
    """
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals, np.float32)
    n = n_shards
    owner_r = rows // shard_rows_self
    owner_c = cols // shard_rows_other
    step = (owner_r - owner_c) % n
    order = np.lexsort((step, rows))
    rows_s, vals_s, step_s = rows[order], vals[order], step[order]
    loc_cols = (cols - owner_c * shard_rows_other)[order]

    uniq_rows, _row_start, row_deg = np.unique(
        rows_s, return_index=True, return_counts=True)
    pair_key = rows_s * n + step_s
    pair_uniq, pair_start, pair_cnt = np.unique(
        pair_key, return_index=True, return_counts=True)
    pair_row = pair_uniq // n
    pair_step = (pair_uniq % n).astype(np.int64)
    row_of_pair = np.searchsorted(uniq_rows, pair_row)
    steps_per_row = np.bincount(row_of_pair, minlength=len(uniq_rows))
    pure_mask_row = (steps_per_row == 1) & (row_deg <= max_width)
    pair_pure = pure_mask_row[row_of_pair]

    def _scatter_elems(pis, dst_cell, width, total_cells):
        """Element-level scatter of each pair's contiguous (col, val)
        block into its cell row → (cols, vals, mask) flat [cells*width]
        arrays. ``dst_cell`` is each pair's flat cell index."""
        cnt = pair_cnt[pis]
        total = int(cnt.sum())
        rep = np.repeat(np.arange(len(pis)), cnt)
        within = np.arange(total) - np.repeat(
            np.cumsum(cnt) - cnt, cnt)
        src = pair_start[pis][rep] + within
        dst = dst_cell[rep] * width + within
        col_f = np.zeros(total_cells * width, np.int32)
        val_f = np.zeros(total_cells * width, np.float32)
        msk_f = np.zeros(total_cells * width, np.float32)
        col_f[dst] = loc_cols[src]
        val_f[dst] = vals_s[src]
        msk_f[dst] = 1.0
        return col_f, val_f, msk_f

    # -- pure rows: bucket by (owner, step, width class) --------------------
    pure_pis = np.flatnonzero(pair_pure)
    pure = []
    if len(pure_pis):
        wclass = _width_class(pair_cnt[pure_pis], min_width)
        for w in np.unique(wclass):
            pis = pure_pis[wclass == w]  # ascending pair order
            w = int(w)
            sh = pair_row[pis] // shard_rows_self
            st = pair_step[pis]
            cell = sh * n + st
            counts = np.bincount(cell, minlength=n * n)
            b = max(int(counts.max()), 1)
            k = _cumcount(cell)
            flat = cell * b + k
            rid_a = np.full(n * n * b, -1, np.int32)
            rid_a[flat] = (pair_row[pis]
                           - sh * shard_rows_self).astype(np.int32)
            col_f, val_f, msk_f = _scatter_elems(pis, flat, w, n * n * b)
            pure.append((rid_a.reshape(n, n, b),
                         col_f.reshape(n, n, b, w),
                         val_f.reshape(n, n, b, w),
                         msk_f.reshape(n, n, b, w)))

    # -- mixed rows: per-step segments + shard-local row lists --------------
    mixed = None
    mixed_pis = np.flatnonzero(~pair_pure)
    if len(mixed_pis):
        mixed_rows = np.unique(pair_row[mixed_pis])  # ascending
        owner_m = mixed_rows // shard_rows_self      # nondecreasing
        h_counts = np.bincount(owner_m, minlength=n)
        h = max(int(h_counts.max()), 1)
        slot = _cumcount(owner_m)  # ascending-rid fill per shard
        rid_m = np.full((n, h), -1, np.int32)
        rid_m[owner_m, slot] = (mixed_rows
                                - owner_m * shard_rows_self).astype(
            np.int32)
        slot_of_row = np.zeros(len(uniq_rows), np.int64)
        slot_of_row[np.searchsorted(uniq_rows, mixed_rows)] = slot
        # split over-wide (row, step) groups into ≤ max_width chunks,
        # in pair-then-chunk order (the loop builder's segment order)
        cap = max_width
        d_m = pair_cnt[mixed_pis]
        nchunks = (d_m + cap - 1) // cap
        total_segs = int(nchunks.sum())
        seg_pair = np.repeat(np.arange(len(mixed_pis)), nchunks)
        chunk_idx = np.arange(total_segs) - np.repeat(
            np.cumsum(nchunks) - nchunks, nchunks)
        seg_len = np.minimum(d_m[seg_pair] - chunk_idx * cap, cap)
        w = int(_width_class(np.array([int(seg_len.max())]),
                             min_width)[0])
        seg_pi = mixed_pis[seg_pair]
        sh = pair_row[seg_pi] // shard_rows_self
        st = pair_step[seg_pi]
        cell = sh * n + st
        s_counts = np.bincount(cell, minlength=n * n)
        s_max = max(int(s_counts.max()), 1)
        k = _cumcount(cell)
        flat = cell * s_max + k
        sid_a = np.full(n * n * s_max, h, np.int32)  # sentinel → dropped
        sid_a[flat] = slot_of_row[
            np.searchsorted(uniq_rows, pair_row[seg_pi])].astype(np.int32)
        # element scatter, chunk-offset into each pair's block
        rep = np.repeat(np.arange(total_segs), seg_len)
        within = np.arange(int(seg_len.sum())) - np.repeat(
            np.cumsum(seg_len) - seg_len, seg_len)
        src = (pair_start[seg_pi][rep] + chunk_idx[rep] * cap + within)
        dst = flat[rep] * w + within
        col_f = np.zeros(n * n * s_max * w, np.int32)
        val_f = np.zeros(n * n * s_max * w, np.float32)
        msk_f = np.zeros(n * n * s_max * w, np.float32)
        col_f[dst] = loc_cols[src]
        val_f[dst] = vals_s[src]
        msk_f[dst] = 1.0
        mixed = (rid_m, sid_a.reshape(n, n, s_max),
                 col_f.reshape(n, n, s_max, w),
                 val_f.reshape(n, n, s_max, w),
                 msk_f.reshape(n, n, s_max, w))
    return tuple(pure), mixed


def shard_bucket(bucket: PaddedRows, mesh: Mesh) -> PaddedRows:
    """Place one padded bucket with rows sharded over the mesh. The bucket
    must have been built with ``row_multiple`` = device count."""
    rows = batch_sharding(mesh)
    return PaddedRows(
        row_ids=jax.device_put(bucket.row_ids, rows),
        cols=jax.device_put(bucket.cols, rows),
        vals=jax.device_put(bucket.vals, rows),
        mask=jax.device_put(bucket.mask, rows),
    )


def shard_buckets(buckets: Sequence[PaddedRows], mesh: Mesh) -> list[PaddedRows]:
    return [shard_bucket(b, mesh) for b in buckets]
