"""Sharding placement helpers for the training data structures.

The single "sharding recipe" of the framework (scaling-book style): batch
dimensions shard over the whole mesh (both axes flattened), factor/parameter
tables are replicated (small) or sharded over ``mp`` (large). XLA/GSPMD
propagates these placements through the jitted sweeps and inserts the
collectives (all-gathers after scatter, psums in grads) on ICI.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from incubator_predictionio_tpu.ops.sparse import PaddedRows
from incubator_predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across every device (dp×mp flattened)."""
    return NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def model_sharding(mesh: Mesh) -> NamedSharding:
    """Parameter tables sharded on rows over the model axis (ALX layout)."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def shard_bucket(bucket: PaddedRows, mesh: Mesh) -> PaddedRows:
    """Place one padded bucket with rows sharded over the mesh. The bucket
    must have been built with ``row_multiple`` = device count."""
    rows = batch_sharding(mesh)
    return PaddedRows(
        row_ids=jax.device_put(bucket.row_ids, rows),
        cols=jax.device_put(bucket.cols, rows),
        vals=jax.device_put(bucket.vals, rows),
        mask=jax.device_put(bucket.mask, rows),
    )


def shard_buckets(buckets: Sequence[PaddedRows], mesh: Mesh) -> list[PaddedRows]:
    return [shard_bucket(b, mesh) for b in buckets]
