"""FactorPlacement — the placement abstraction over ALS factor state.

ALX (PAPERS.md: arxiv 2112.02194) scales ALS to billion-row catalogs by
sharding BOTH factor tables across the TPU mesh and structuring each
half-sweep as shard-local solves over the rows a device owns, with the
other side's factor shards moved by collectives. This module is the
single source of truth for that layout:

- **Ownership** is contiguous row blocks: the padded table is split into
  ``n_shards`` equal slices and shard ``s`` owns global rows
  ``[s·shard_rows, (s+1)·shard_rows)``. Contiguous blocks mean the
  global↔local index maps are pure arithmetic (``owner = id // rows``,
  ``local = id − owner·rows``) — no lookup tables ride the trace.
- **Tables** shard on rows over the WHOLE mesh (both axes flattened):
  per-device HBM/VMEM footprint divides by the full device count, which
  is what re-enables the fused Gram+solve kernel's VMEM table residency
  at big-table shapes (docs/performance.md "Sharded ALS").
- **Interaction buckets** are shard-blocked: rows grouped into equal
  per-shard blocks along axis 0 (parallel/sharding.py
  ``shard_block_buckets``), so the SAME flat arrays serve the
  single-chip path (n_shards=1) and the shard_map path (each device
  sees exactly its block).

A :class:`FactorPlacement` is a frozen, hashable dataclass — it rides
``ALSState`` as static pytree metadata and jit cache keys, so resharding
(a different mesh shape) naturally recompiles while steady-state
retrains under a fixed placement never do.

Cross-replica update sharding (arxiv 2004.13336) falls out of the
layout: each device solves and scatters ONLY its own row block, so
factor updates are shard-local by construction — no update collective
exists to optimize away.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True, eq=False)
class FactorPlacement:
    """Mesh + per-table sharding + shard-local↔global index arithmetic.

    ``n_users``/``n_items`` are the TRUE table sizes; padded sizes (to a
    multiple of the shard count) are derived. Hashable and cheap to
    compare: jit paths take it as a static argument — and because the
    traced programs depend only on the shard GEOMETRY (mesh + padded
    table shapes), eq/hash are keyed on exactly that, not the true
    sizes. With ``grow=True`` capacities, ids appending within capacity
    produce an EQUAL placement: steady-state retrains hit the jit cache,
    only a geometry change (reshard / capacity doubling) recompiles.
    True sizes stay host-side data (``unplace_state`` slicing, the
    serving ``valid_items`` mask).
    """

    mesh: Mesh
    n_users: int
    n_items: int
    #: fixed padded capacities (multiples of the shard count). None =
    #: tight fit; the continuation-retrain path sets pow2-per-shard
    #: capacities (:func:`make_placement` ``grow=True``) so the shard
    #: geometry — and with it the resident prep plan and every compiled
    #: program — stays stable while new ids append within capacity.
    users_capacity: Optional[int] = None
    items_capacity: Optional[int] = None

    def _geometry(self) -> Tuple[Any, int, int]:
        return (self.mesh, self.n_users_padded, self.n_items_padded)

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, FactorPlacement)
                and self._geometry() == other._geometry())

    def __hash__(self) -> int:
        return hash(self._geometry())

    # -- mesh geometry ------------------------------------------------------
    @property
    def axes(self) -> Tuple[str, ...]:
        """The flattened logical shard axis (every mesh axis)."""
        return tuple(self.mesh.axis_names)

    @property
    def n_shards(self) -> int:
        return int(self.mesh.devices.size)

    # -- padded table shapes ------------------------------------------------
    def _padded(self, n: int, cap: Optional[int]) -> int:
        m = self.n_shards
        tight = -(-max(n, 1) // m) * m
        return max(cap, tight) if cap else tight

    @property
    def n_users_padded(self) -> int:
        return self._padded(self.n_users, self.users_capacity)

    @property
    def n_items_padded(self) -> int:
        return self._padded(self.n_items, self.items_capacity)

    def shard_rows(self, side: str) -> int:
        """Rows per shard of one table ("user" | "item")."""
        n = self.n_users_padded if side == "user" else self.n_items_padded
        return n // self.n_shards

    # -- shardings ----------------------------------------------------------
    @property
    def table_spec(self) -> P:
        return P(self.axes)

    def table_sharding(self) -> NamedSharding:
        """Rows sharded over the flattened mesh — both factor tables."""
        return NamedSharding(self.mesh, self.table_spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- index maps ---------------------------------------------------------
    def owner_of(self, side: str, ids: np.ndarray) -> np.ndarray:
        """Global row ids → owning shard index (host-side numpy)."""
        return np.asarray(ids) // self.shard_rows(side)

    def localize(self, side: str, ids: np.ndarray) -> np.ndarray:
        """Global row ids → shard-local indices; negatives pass through
        (the padding sentinel the scatter drops)."""
        ids = np.asarray(ids)
        local = ids - self.owner_of(side, ids) * self.shard_rows(side)
        return np.where(ids >= 0, local, ids)

    def globalize(self, side: str, shard: int, local: np.ndarray) -> np.ndarray:
        return np.asarray(local) + shard * self.shard_rows(side)

    # -- state movement -----------------------------------------------------
    def place_table(self, arr: Any, side: str) -> jax.Array:
        """Pad a [n, K] factor table to the padded size and shard it."""
        arr = jnp.asarray(arr, jnp.float32)
        n = self.n_users_padded if side == "user" else self.n_items_padded
        if arr.shape[0] < n:
            arr = jnp.pad(arr, ((0, n - arr.shape[0]), (0, 0)))
        elif arr.shape[0] > n:
            arr = arr[:n]
        return jax.device_put(arr, self.table_sharding())

    def place_state(self, state: Any) -> Any:
        """ALSState → placed (padded + sharded) ALSState carrying this
        placement. Re-placing a state trained at a DIFFERENT mesh shape
        is the continuation resharding path: the true-size prefix is the
        model; padding is recomputed for the new shard count."""
        from incubator_predictionio_tpu.ops.als import ALSState

        uf = state.user_factors
        vf = state.item_factors
        prev = getattr(state, "placement", None)
        if prev is not None:
            uf = uf[: prev.n_users]
            vf = vf[: prev.n_items]
        return ALSState(
            user_factors=self.place_table(uf, "user"),
            item_factors=self.place_table(vf, "item"),
            placement=self,
        )

    def unplace_state(self, state: Any) -> Any:
        """Placed state → plain state sliced back to the true sizes."""
        from incubator_predictionio_tpu.ops.als import ALSState

        return ALSState(
            user_factors=state.user_factors[: self.n_users],
            item_factors=state.item_factors[: self.n_items],
        )

    # -- bookkeeping --------------------------------------------------------
    def describe(self) -> str:
        """e.g. "4x2" — the bench record's ``shard_mesh_shape``."""
        return "x".join(str(self.mesh.shape[a]) for a in self.axes)

    def cache_key(self) -> str:
        """Plan-invalidation key: a prep plan built under one placement
        must not be spliced under another (resharding rebuilds). Keyed
        on the shard GEOMETRY (mesh + padded capacities), not the exact
        live sizes — ids appending within capacity keep the plan."""
        return (f"{self.describe()}:{self.n_users_padded}:"
                f"{self.n_items_padded}:"
                f"{hash(self.mesh) & 0xFFFFFFFF:x}")

    def allgather_bytes(self, side_gathered: str, sweeps: int,
                        rank: int, itemsize: int = 4) -> int:
        """Analytic collective volume of ``sweeps`` half-sweeps that
        all-gather the ``side_gathered`` table: each device receives the
        (n−1)/n of the table it does not hold."""
        n = self.n_shards
        if n <= 1:
            return 0
        rows = (self.n_users_padded if side_gathered == "user"
                else self.n_items_padded)
        per_dev = rows * rank * itemsize * (n - 1) // n
        return per_dev * n * sweeps


def is_distributed(x: Any) -> bool:
    """True when ``x`` is a jax array actually SHARDED over >1 device
    (not merely replicated) — the serving/fold-in routing predicate for
    placed factor tables."""
    s = getattr(x, "sharding", None)
    if s is None:
        return False
    try:
        return (len(s.device_set) > 1
                and not s.is_fully_replicated)
    except Exception:
        return False


def placement_for_ctx(ctx: Any, n_users: int, n_items: int,
                      ) -> Optional[FactorPlacement]:
    """THE engine seam: the training placement for this RuntimeContext,
    or None for the single-chip path. Sharding engages when the context
    asks for model parallelism (``pio train --model-parallelism N``) or
    `PIO_SHARD_TABLES=1` forces it, AND more than one device exists.
    ``grow=True`` keeps the shard geometry stable across continuation
    retrains while ids append."""
    import os

    forced = os.environ.get("PIO_SHARD_TABLES", "0") not in (
        "0", "off", "false")
    want = int(getattr(ctx, "model_parallelism", 1) or 1) > 1 or forced
    if not want:
        return None
    placement = make_placement(ctx.mesh, n_users, n_items, grow=True)
    # gate on the mesh the placement will actually use (which honors
    # the PIO_MESH_DEVICES cap), not the raw global device count — a
    # capped 1-device mesh is the single-chip path
    if placement.n_shards <= 1:
        return None
    return placement


def make_placement(mesh: Optional[Mesh], n_users: int, n_items: int,
                   grow: bool = False) -> FactorPlacement:
    """Placement over ``mesh`` (default: the standard full-device mesh).

    ``grow=True`` (the steady-state retrain policy) rounds each table's
    per-shard rows up to a power of two: capacity doubles occasionally
    instead of shifting every retrain, so the shard geometry — the prep
    plan, the compiled sharded programs, the index arithmetic — is
    stable while new ids append. Padding rows hold zero factors and are
    never solved or served (ops/topk.py masks them)."""
    if mesh is None:
        from incubator_predictionio_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    uc = ic = None
    if grow:
        n = int(mesh.devices.size)

        def cap(rows: int) -> int:
            per = -(-max(rows, 1) // n)
            return n * (1 << max(per - 1, 0).bit_length())

        uc, ic = cap(n_users), cap(n_items)
    return FactorPlacement(mesh=mesh, n_users=int(n_users),
                           n_items=int(n_items),
                           users_capacity=uc, items_capacity=ic)
