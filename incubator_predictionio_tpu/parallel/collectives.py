"""XLA collective wrappers — the framework's distributed communication
backend.

The reference's "communication backend" is Spark shuffle/broadcast inside
MLlib plus HTTP between servers (SURVEY.md §2.7); it has no NCCL/MPI layer.
The TPU-native equivalent is XLA collectives over ICI (intra-slice) and DCN
(across slices), expressed as ``jax.lax`` primitives under ``shard_map`` /
``pjit``. This module is the single place the rest of the framework goes for
them, so the mapping from "what the algorithm needs" to "which collective
rides which interconnect" lives in one file.

All functions take ``axis_name`` (a mesh axis as seen inside ``shard_map``)
and are traceable — they compile to the corresponding XLA collective and are
no-ops (or cheap copies) when the axis has size 1.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]

try:  # jax ≥ 0.6 exports it at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # the long-stable experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_impl

#: which replication-check kwarg the installed jax understands
#: (renamed check_rep → check_vma upstream)
_SM_PARAMS = frozenset(inspect.signature(_shard_map_impl).parameters)


def shard_map(f=None, **kwargs):
    """Version-stable ``shard_map`` — THE import the framework uses.

    Papers over the two upstream API moves that would otherwise pin the
    repo to one jax version: the export location (``jax.shard_map`` vs
    ``jax.experimental.shard_map``) and the replication-check kwarg
    rename (``check_rep`` → ``check_vma``). Callers may pass either
    spelling; it is translated to whatever the installed jax accepts.
    """
    if f is None:
        return functools.partial(shard_map, **kwargs)
    rep = kwargs.pop("check_rep", kwargs.pop("check_vma", None))
    if rep is not None:
        kwargs["check_vma" if "check_vma" in _SM_PARAMS
               else "check_rep"] = rep
    return _shard_map_impl(f, **kwargs)


def axis_size(axis_name: AxisName) -> int:
    """Number of shards along ``axis_name`` (inside shard_map).

    ``lax.psum`` of the constant 1 folds to the axis size AT TRACE TIME
    (a python int, usable for static permutation tables) on every jax
    version; ``lax.axis_size`` only exists on newer ones."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def axis_index(axis_name: AxisName):
    """This shard's coordinate along ``axis_name`` (inside shard_map)."""
    return lax.axis_index(axis_name)


def all_reduce_sum(x: Any, axis_name: AxisName) -> Any:
    """Sum over the axis — one XLA all-reduce on ICI/DCN (lax.psum)."""
    return lax.psum(x, axis_name)


def all_reduce_mean(x: Any, axis_name: AxisName) -> Any:
    """Mean over the axis — the DP gradient-sync collective (lax.pmean)."""
    return lax.pmean(x, axis_name)


def all_reduce_max(x: Any, axis_name: AxisName) -> Any:
    return lax.pmax(x, axis_name)


def all_gather(x: Any, axis_name: AxisName, axis: int = 0,
               tiled: bool = True) -> Any:
    """Gather shards along ``axis`` from every member of the mesh axis.

    ``tiled=True`` concatenates (shard dim multiplies by axis size), matching
    the layout produced by sharding an array over that axis.
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: Any, axis_name: AxisName, axis: int = 0,
                   tiled: bool = True) -> Any:
    """Sum then scatter: each shard keeps its slice of the reduced result.
    Half the bandwidth of all-reduce when the consumer is itself sharded —
    the right primitive for sharded optimizer states (ZeRO-style)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


def ppermute_next(x: Any, axis_name: AxisName) -> Any:
    """Rotate shards one step around the axis ring (i → i+1 mod n).

    This is the ring-attention / ring-exchange building block: on TPU the
    permutation maps onto neighbor ICI links, so every step moves all shards
    concurrently at full ring bandwidth.
    """
    n = axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def ppermute_prev(x: Any, axis_name: AxisName) -> Any:
    """Rotate shards one step the other way (i → i-1 mod n)."""
    n = axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(i, (i - 1) % n) for i in range(n)])


def all_to_all(x: Any, axis_name: AxisName, split_axis: int,
               concat_axis: int, tiled: bool = True) -> Any:
    """Transpose shard ownership between two array dims — the Ulysses-style
    sequence↔head resharding collective for long-context attention."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def broadcast_from(x: Any, axis_name: AxisName, src_index: int = 0) -> Any:
    """Every shard receives ``x`` as seen by shard ``src_index`` (the Spark
    ``broadcast`` analogue, but over ICI instead of the driver network)."""
    idx = lax.axis_index(axis_name)
    masked = jax.tree_util.tree_map(
        lambda t: jnp.where(idx == src_index, t, jnp.zeros_like(t)), x
    )
    return lax.psum(masked, axis_name)
