"""Mesh construction helpers.

The standard mesh has two logical axes:

- ``"dp"`` (data/batch axis)  — users/examples are sharded here.
- ``"mp"`` (model axis)       — embedding tables / factor matrices here.

This is the ALX layout for matrix factorization on TPU pods (PAPERS.md:
"ALX: Large Scale Matrix Factorization on TPUs") and the general recipe of
the scaling-book: pick a mesh, annotate shardings, let XLA insert the
collectives. On a single chip both axes are 1 and everything compiles to the
degenerate (no-collective) program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

DATA_AXIS = "dp"
MODEL_AXIS = "mp"
#: sequence/context axis for long-input attention (ring / all-to-all SP)
SEQ_AXIS = "sp"


def forced_device_count() -> Optional[int]:
    """`PIO_MESH_DEVICES` — cap the devices the standard mesh uses.

    The multi-device simulation seam: the test fixture (and any
    operator pinning a sub-mesh) sets this to run sharded paths on a
    subset of the visible devices — e.g. mesh shapes {1, 2, 4, 8} on
    the 8-device forced-host-platform CPU sim — without constructing
    meshes by hand. Read per call (the env-import lint contract)."""
    import os

    raw = os.environ.get("PIO_MESH_DEVICES", "")
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None


def device_count() -> int:
    n = forced_device_count()
    return min(jax.device_count(), n) if n else jax.device_count()


def mesh_shape_for(
    n_devices: int, model_parallelism: int = 1
) -> tuple[int, int]:
    """(dp, mp) factorization of ``n_devices``.

    ``model_parallelism`` is a target; it is clamped to a divisor of
    ``n_devices`` so the mesh always uses every device.
    """
    mp = max(1, min(model_parallelism, n_devices))
    while n_devices % mp != 0:
        mp -= 1
    return n_devices // mp, mp


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallelism: int = 1,
    axis_names: tuple[str, str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build the standard (dp, mp) mesh over the given (default: all) devices.

    TPU note: jax.devices() ordering on a slice follows the physical torus,
    so adjacent mesh coordinates are ICI neighbors; ``mp`` varies fastest,
    keeping model-axis collectives (the all_gathers of factor shards in the
    ALS sweep) on the innermost, fastest rings.
    """
    devs = list(devices if devices is not None else jax.devices())
    if devices is None:
        forced = forced_device_count()
        if forced:
            devs = devs[:forced]
    dp, mp = mesh_shape_for(len(devs), model_parallelism)
    import numpy as np

    grid = np.array(devs[: dp * mp]).reshape(dp, mp)
    return Mesh(grid, axis_names)
