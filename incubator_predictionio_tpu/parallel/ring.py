"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context support is first-class in this framework even though the
reference has none (SURVEY.md §5 "Long-context / sequence parallelism:
Absent"): the sequence engines must scale past one chip's HBM in sequence
length. Two standard strategies, both over the ``sp`` mesh axis:

- :func:`ring_attention` — KV blocks rotate around the ``sp`` ring via
  ``ppermute`` (neighbor ICI links), each device accumulating online-softmax
  attention for its local query block. Communication overlaps compute after
  the first hop; memory is O(S/n) per device. (Liu et al., "Ring Attention
  with Blockwise Transformers", PAPERS.md.)
- :func:`ulysses_attention` — two ``all_to_all`` reshards: seq-sharded →
  head-sharded, run full-sequence attention per head locally, and reshard
  back. Cheaper at moderate S (2 collectives instead of n-1 hops) but caps
  parallelism at the head count.

Both produce numerics matching ops/attention.py's single-device kernels (the
shared ``_online_block`` accumulator) and are plain traceable functions: jit
them under a mesh and XLA lays the ppermutes onto the ICI torus.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from incubator_predictionio_tpu.parallel.collectives import (
    axis_size as _axis_size,
    shard_map,
)
from jax.sharding import Mesh, PartitionSpec as P

from incubator_predictionio_tpu.ops.attention import (
    _finalize,
    _online_block,
    _scale,
)
from incubator_predictionio_tpu.parallel.collectives import ppermute_next
from incubator_predictionio_tpu.parallel.mesh import SEQ_AXIS


def _ring_attention_local(q, k, v, kv_valid, axis_name, causal, scale):
    """Per-shard body: q stays put, (k, v, kv_valid) rotate around the ring."""
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    sc = _scale(q, scale)
    q_pos = my * s_loc + jnp.arange(s_loc)

    def accumulate(i, k_blk, v_blk, valid_blk, m, l, o):
        # after i forward rotations the resident block originated at rank
        # (my - i) mod n, which fixes its global key positions for masking
        src = (my - i) % n
        kv_pos = src * s_loc + jnp.arange(s_loc)
        return _online_block(
            q, k_blk, v_blk, m, l, o, sc, causal, q_pos, kv_pos,
            kv_valid=valid_blk,
        )

    def step(i, carry):
        # rotate first, then accumulate: hop 0 runs outside the loop, so
        # exactly n-1 ppermutes are issued and none is discarded
        k_blk, v_blk, valid_blk, m, l, o = carry
        k_blk = ppermute_next(k_blk, axis_name)
        v_blk = ppermute_next(v_blk, axis_name)
        valid_blk = ppermute_next(valid_blk, axis_name)
        m, l, o = accumulate(i, k_blk, v_blk, valid_blk, m, l, o)
        return k_blk, v_blk, valid_blk, m, l, o

    m, l, o = accumulate(
        0, k, v, kv_valid,
        jnp.full((b, h, s_loc), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, s_loc), jnp.float32),
        jnp.zeros((b, h, s_loc, d), jnp.float32),
    )
    _, _, _, m, l, o = lax.fori_loop(1, n, step, (k, v, kv_valid, m, l, o))
    return _finalize(m, l, o, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = SEQ_AXIS,
    kv_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention over [B, S, H, D] with S sharded on ``axis_name``.

    Inputs may be unsharded (GSPMD moves them); output is sharded the same
    way as q. S must divide evenly by the axis size. ``kv_valid`` ([B, S])
    masks padding keys and is sharded/rotated with the keys.
    """
    if kv_valid is None:
        kv_valid = jnp.ones((q.shape[0], k.shape[1]), bool)
    spec = P(None, axis_name, None, None)
    vspec = P(None, axis_name)
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name, causal=causal, scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, vspec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_valid)


def _ulysses_local(q, k, v, kv_valid, axis_name, causal, scale):
    from incubator_predictionio_tpu.ops.attention import blockwise_attention

    def seq_to_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]: gather seq, scatter heads
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # validity has no head dim to scatter — every shard needs the full mask
    valid_full = lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)
    o = blockwise_attention(q, k, v, causal=causal, scale=scale,
                            kv_valid=valid_full)
    return heads_to_seq(o)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = SEQ_AXIS,
    kv_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Requires the head count to be divisible by the ``axis_name`` size.
    """
    if q.shape[2] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"heads {q.shape[2]} not divisible by sequence-parallel degree "
            f"{mesh.shape[axis_name]}"
        )
    if kv_valid is None:
        kv_valid = jnp.ones((q.shape[0], k.shape[1]), bool)
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal, scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(None, axis_name)),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_valid)
