"""Stock backtesting template (parity: examples/experimental/scala-stock)."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.models.stock import (
    BacktestingEvaluator,
    BacktestingParams,
    DataSourceParams,
    MomentumStrategyParams,
    Query,
    RegressionStrategyParams,
    StockEngine,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.workflow import CoreWorkflow

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
N_DAYS = 80


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def seeded_app():
    """UP compounds +1%/day, DOWN −1%/day, SPY flat with tiny noise —
    deterministic trends a momentum strategy must separate."""
    Storage.get_meta_data_apps().insert(App(0, "stockapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("stockapp").id
    dao = Storage.get_events()
    rng = np.random.default_rng(0)
    # trends carry a little noise: perfectly constant returns would make
    # the shift-return indicators exactly collinear with the intercept
    series = {
        "UP": 100.0 * np.cumprod(
            1.01 + 0.002 * rng.standard_normal(N_DAYS)),
        "DOWN": 100.0 * np.cumprod(
            0.99 + 0.002 * rng.standard_normal(N_DAYS)),
        "SPY": 100.0 * (1 + 0.0005 * rng.standard_normal(N_DAYS)).cumprod(),
    }
    for ticker, prices in series.items():
        for d, price in enumerate(prices):
            dao.insert(Event(
                event="price", entity_type="ticker", entity_id=ticker,
                properties=DataMap({"price": float(price)}),
                event_time=T0 + timedelta(days=d)), app_id)
    return app_id


def _ep(algo, algo_params, eval_days=0):
    return EngineParams(
        data_source_params=("", DataSourceParams(
            app_name="stockapp", eval_from_idx=30, eval_days=eval_days)),
        algorithm_params_list=[(algo, algo_params)],
    )


def test_panel_assembly_and_momentum_scores(seeded_app):
    engine = StockEngine().apply()
    ep = _ep("momentum", MomentumStrategyParams(window=5))
    models = engine.train(RuntimeContext(), ep)
    td = models[0].td
    assert td.tickers == ("DOWN", "SPY", "UP")
    assert len(td.times) == N_DAYS
    assert td.active.all()
    algo = engine.algorithms(ep)[0]
    p = algo.predict(models[0], Query(idx=40))
    assert p.scores["UP"] > p.scores["SPY"] > p.scores["DOWN"]
    # before the window fills there is nothing to score
    assert algo.predict(models[0], Query(idx=2)).scores == {}


def test_regression_strategy_learns_trend(seeded_app):
    engine = StockEngine().apply()
    ep = _ep("regression", RegressionStrategyParams(periods=(1, 5, 10)))
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    p = algo.predict(models[0], Query(idx=60))
    # deterministic compounding: predicted next-day return ≈ ±1%
    assert p.scores["UP"] > 0.005
    assert p.scores["DOWN"] < -0.005


def test_backtest_evaluator_goes_long_the_winner(seeded_app):
    engine = StockEngine().apply()
    evaluation = Evaluation()
    evaluation.engine_evaluator = (
        engine,
        BacktestingEvaluator(BacktestingParams(
            enter_threshold=0.001, exit_threshold=-0.001,
            max_positions=1)),
    )
    ep = _ep("momentum", MomentumStrategyParams(window=5), eval_days=40)
    iid, result = CoreWorkflow.run_evaluation(evaluation, [ep])
    # momentum holds UP through the eval window: ~1%/day compounding
    assert result.overall.ret > 0.2
    assert result.overall.days > 30
    assert result.overall.sharpe > 1.0
    assert all(d.position_count <= 1 for d in result.daily)
    assert result.to_one_liner().startswith("ret=")


def test_gappy_ticker_is_masked_not_poisoned(seeded_app):
    """A ticker listing mid-panel must neither train on ±log(p) NaN
    placeholders nor receive scores before its indicators are real."""
    app_id = seeded_app
    dao = Storage.get_events()
    rng = np.random.default_rng(9)
    for d in range(50, N_DAYS):  # NEW lists on day 50 only
        dao.insert(Event(
            event="price", entity_type="ticker", entity_id="NEW",
            properties=DataMap(
                {"price": float(50.0 * (1.005 + 0.002 *
                                        rng.standard_normal()) ** (d - 50))}),
            event_time=T0 + timedelta(days=d)), app_id)
    engine = StockEngine().apply()
    ep = _ep("regression", RegressionStrategyParams(periods=(1, 5, 10)))
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    assert np.isfinite(models[0].weights).all()
    # day 55: NEW is active but its period-10 indicator reaches into the
    # pre-listing gap → no score; the established tickers still score sanely
    p = algo.predict(models[0], Query(idx=55))
    assert "NEW" not in p.scores
    assert abs(p.scores["UP"]) < 0.1
    # day 75: all indicators real → NEW scores
    p2 = algo.predict(models[0], Query(idx=75))
    assert "NEW" in p2.scores and abs(p2.scores["NEW"]) < 0.1


def test_empty_strategy_flat_nav(seeded_app):
    engine = StockEngine().apply()
    evaluation = Evaluation()
    evaluation.engine_evaluator = (
        engine, BacktestingEvaluator(BacktestingParams()))
    from incubator_predictionio_tpu.models.stock.engine import (
        EmptyStrategyParams,
    )

    ep = _ep("empty", EmptyStrategyParams(), eval_days=20)
    iid, result = CoreWorkflow.run_evaluation(evaluation, [ep])
    assert result.overall.ret == pytest.approx(0.0)
    assert all(d.position_count == 0 for d in result.daily)
