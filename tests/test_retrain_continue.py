"""Continuation retrain: O(delta) steady-state training.

Contracts under test (ops/retrain.py, ops/als.continue_state,
workflow continuation plumbing):

- the prefix id-mapping continuation relies on is byte-identical to the
  traincache ``merge_tables`` remaps across tail folds, and breaks
  (detectably) under deletes / unordered times;
- plan-reused prep produces bitwise-identical training inputs to a
  fresh build after a tail, and invalidates itself on any prefix break;
- the convergence early-stop honors its floor (≥ min_sweeps, ≥ 1) and
  ceiling (the fixed budget) on both the fused (while_loop) and the
  unfused (chunked probe) paths;
- continuation retrain after a small tail reaches fit quality at parity
  with a fresh train;
- the workflow auto-disables continuation on any spec/params change and
  under PIO_RETRAIN_CONTINUE=0, and fresh-train behavior is untouched.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.ops import als, retrain


@pytest.fixture(autouse=True)
def _fresh_plans():
    retrain.drop_plans()
    yield
    retrain.drop_plans()


def _coo(rng, n_u, n_i, nnz, rank=4):
    u_true = rng.normal(0, 1 / np.sqrt(rank), (n_u, rank)).astype(np.float32)
    v_true = rng.normal(0, 1, (n_i, rank)).astype(np.float32)
    users = rng.integers(0, n_u, nnz).astype(np.int64)
    items = rng.integers(0, n_i, nnz).astype(np.int64)
    vals = (3.0 + np.einsum("nk,nk->n", u_true[users], v_true[items])
            ).astype(np.float32)
    return users, items, vals


# ---------------------------------------------------------------------------
# factor continuation
# ---------------------------------------------------------------------------

def test_continue_state_prefix_copy_is_exact():
    prev_u = np.arange(12, dtype=np.float32).reshape(4, 3)
    prev_i = -np.arange(6, dtype=np.float32).reshape(2, 3)
    st = als.continue_state(prev_u, prev_i, 7, 5, seed=0)
    uf = np.asarray(st.user_factors)
    vf = np.asarray(st.item_factors)
    assert uf.shape == (7, 3) and vf.shape == (5, 3)
    # prefix rows are copied bit-for-bit — row i still names entity i
    np.testing.assert_array_equal(uf[:4], prev_u)
    np.testing.assert_array_equal(vf[:2], prev_i)
    # appended rows are als_init-scale random, never zero/copies
    assert np.all(np.any(uf[4:] != 0, axis=1))
    assert np.std(uf[4:]) < 1.0  # scale 0.1 noise, not garbage


def test_continue_state_refuses_shrunk_index_space():
    prev = np.zeros((5, 3), np.float32)
    assert als.continue_state(prev, prev, 4, 5, seed=0) is None  # users shrank
    assert als.continue_state(prev, prev, 5, 4, seed=0) is None  # items shrank
    assert als.continue_state(np.zeros((2, 3), np.float32),
                              np.zeros((2, 4), np.float32), 5, 5) is None


def test_bimap_index_prefix_gate():
    prev = BiMap({"a": 0, "b": 1})
    grown = BiMap({"a": 0, "b": 1, "c": 2})
    assert prev.is_index_prefix_of(grown)
    assert prev.is_index_prefix_of(prev)
    # a delete/rebuild reorders the dense index space → gate closes
    reordered = BiMap({"b": 0, "a": 1, "c": 2})
    assert not prev.is_index_prefix_of(reordered)
    dropped = BiMap({"a": 0})
    assert not prev.is_index_prefix_of(dropped)


def test_prefix_mapping_matches_merge_tables():
    """The continuation's 'row i still names entity i' assumption IS the
    merge_tables contract: merging a tail table appends unseen ids only,
    so the base table is a byte-identical prefix of the merged one."""
    from incubator_predictionio_tpu.data.storage import traincache

    base = traincache._build_table([b"u0", b"u1", b"u2"])
    tail = traincache._build_table([b"u1", b"u9", b"u0", b"u7"])
    merged, remap = traincache.merge_tables(base, tail)
    assert traincache.table_bytes(merged)[:3] == traincache.table_bytes(base)
    # tail ids remap to base indices when seen, first-seen appends after
    np.testing.assert_array_equal(remap, [1, 3, 0, 4])
    # an unordered/deleted rebuild (first_seen_reindex of a reordered
    # stream) does NOT preserve the prefix — exactly what the BiMap gate
    # must catch
    idx = np.asarray([2, 0, 1], np.int32)
    _re_idx, re_tab = traincache.first_seen_reindex(idx, base)
    assert traincache.table_bytes(re_tab)[0] != \
        traincache.table_bytes(base)[0]


# ---------------------------------------------------------------------------
# convergence early-stop: floor and ceiling, fused and probe paths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fused", ["1", "0"])
def test_early_stop_floor_and_ceiling(monkeypatch, fused):
    monkeypatch.setenv("PIO_RETRAIN_FUSED", fused)
    monkeypatch.setenv("PIO_RETRAIN_PROBE_EVERY", "2")
    rng = np.random.default_rng(0)
    users, items, vals = _coo(rng, 40, 30, 800)

    # ceiling: tol=0 never converges → the fixed budget runs in full
    stats = {}
    retrain.als_retrain(users, items, vals, 40, 30, rank=4, iterations=5,
                        l2=0.05, seed=0, tol=0.0, stats=stats)
    assert stats["sweeps_used"] == 5

    # floor: an absurd tolerance still runs min_sweeps (and ≥ 1)
    stats = {}
    retrain.als_retrain(users, items, vals, 40, 30, rank=4, iterations=5,
                        l2=0.05, seed=0, tol=1e9, stats=stats)
    if fused == "1":
        assert stats["sweeps_used"] == 1
    else:
        assert stats["sweeps_used"] == 2  # one probe chunk
    stats = {}
    retrain.als_retrain(users, items, vals, 40, 30, rank=4, iterations=5,
                        l2=0.05, seed=0, tol=1e9, min_sweeps=3,
                        stats=stats)
    assert 3 <= stats["sweeps_used"] <= 5


def test_early_stop_fixed_budget_matches_als_train():
    """tol=0 + fresh init must reproduce als_train exactly (the
    byte-stability guarantee for the disabled/fresh path)."""
    rng = np.random.default_rng(1)
    users, items, vals = _coo(rng, 30, 20, 500)
    ref, _ = als.als_train(users, items, vals, 30, 20, rank=4,
                           iterations=4, l2=0.05, seed=3)
    stats = {}
    got = retrain.als_retrain(users, items, vals, 30, 20, rank=4,
                              iterations=4, l2=0.05, seed=3, tol=0.0,
                              stats=stats)
    np.testing.assert_array_equal(np.asarray(ref.user_factors),
                                  np.asarray(got.user_factors))
    np.testing.assert_array_equal(np.asarray(ref.item_factors),
                                  np.asarray(got.item_factors))
    assert stats["mode"] == "fresh"


# ---------------------------------------------------------------------------
# prep/plan reuse
# ---------------------------------------------------------------------------

def _run_two_sweeps(trees, n_u, n_i):
    ut, it, uh, ih = trees
    init = als.als_init(jax.random.key(0), n_u, n_i, 4)
    out = als._als_run_fused(
        als.ALSState(jnp.array(init.user_factors),
                     jnp.array(init.item_factors)),
        ut, it, 0.05, 0.0, 2, True, jnp.float32,
        jax.lax.Precision.HIGHEST, implicit=False,
        user_heavy=uh, item_heavy=ih)
    return np.asarray(out.user_factors), np.asarray(out.item_factors)


def test_plan_reuse_is_bitwise_identical_to_fresh_build():
    rng = np.random.default_rng(2)
    users, items, vals = _coo(rng, 60, 40, 1500)
    t_users, t_items, t_vals = _coo(rng, 60, 40, 120)
    u2 = np.concatenate([users, t_users])
    i2 = np.concatenate([items, t_items])
    v2 = np.concatenate([vals, t_vals])

    retrain.prepare_with_reuse(users, items, vals, 60, 40, plan_key="p")
    stats = {}
    reused = retrain.prepare_with_reuse(u2, i2, v2, 60, 40, plan_key="p",
                                        stats=stats)
    assert stats["prep_plan"] == "reused"
    assert stats["prep_delta_rows"] == 120
    fresh = retrain.prepare_with_reuse(u2, i2, v2, 60, 40, plan_key=None)
    ur, vr = _run_two_sweeps(reused, 60, 40)
    uf, vf = _run_two_sweeps(fresh, 60, 40)
    np.testing.assert_array_equal(ur, uf)
    np.testing.assert_array_equal(vr, vf)

    # idempotent: re-preparing the same data folds an empty tail
    stats = {}
    retrain.prepare_with_reuse(u2, i2, v2, 60, 40, plan_key="p",
                               stats=stats)
    assert stats["prep_plan"] == "reused"
    assert stats["prep_delta_rows"] == 0


def test_plan_reuse_compaction_bound_forces_fresh_rebuild():
    """Accumulated dead slots must eventually force a compact rebuild —
    the unbounded-creep guard for long retrain sequences."""
    rng = np.random.default_rng(9)
    users, items, vals = _coo(rng, 40, 30, 600)
    retrain.prepare_with_reuse(users, items, vals, 40, 30, plan_key="c")
    plan = retrain._PLAN_CACHE["c"]
    plan.user.dead_rows = 10_000  # far past the 25%-of-live threshold
    t_u, t_i, t_v = _coo(rng, 40, 30, 50)
    u2 = np.concatenate([users, t_u])
    i2 = np.concatenate([items, t_i])
    v2 = np.concatenate([vals, t_v])
    stats = {}
    rebuilt = retrain.prepare_with_reuse(u2, i2, v2, 40, 30, plan_key="c",
                                         stats=stats)
    assert stats["prep_plan"] == "rebuilt"
    # the rebuild re-registered a compact plan and stays correct
    assert retrain._PLAN_CACHE["c"].user.dead_rows == 0
    fresh = retrain.prepare_with_reuse(u2, i2, v2, 40, 30, plan_key=None)
    ur, vr = _run_two_sweeps(rebuilt, 40, 30)
    uf, vf = _run_two_sweeps(fresh, 40, 30)
    np.testing.assert_array_equal(ur, uf)
    np.testing.assert_array_equal(vr, vf)


def test_plan_reuse_invalidates_on_prefix_break():
    """A mutated interior triple (the latest-wins dedup class) must fail
    the digest and fall back to a fresh build — never a silent splice."""
    rng = np.random.default_rng(4)
    users, items, vals = _coo(rng, 30, 20, 400)
    retrain.prepare_with_reuse(users, items, vals, 30, 20, plan_key="q")
    mutated = vals.copy()
    mutated[5] += 1.0
    stats = {}
    retrain.prepare_with_reuse(users, items, mutated, 30, 20,
                               plan_key="q", stats=stats)
    assert stats["prep_plan"] == "invalidated"


def test_plan_reuse_handles_growing_index_space():
    rng = np.random.default_rng(5)
    users, items, vals = _coo(rng, 20, 15, 300)
    # tail introduces brand-new users/items (grown tables)
    t_users = np.asarray([20, 21, 3, 22], np.int64)
    t_items = np.asarray([15, 2, 16, 15], np.int64)
    t_vals = np.asarray([1, 2, 3, 4], np.float32)
    u2 = np.concatenate([users, t_users])
    i2 = np.concatenate([items, t_items])
    v2 = np.concatenate([vals, t_vals])
    retrain.prepare_with_reuse(users, items, vals, 20, 15, plan_key="g")
    stats = {}
    reused = retrain.prepare_with_reuse(u2, i2, v2, 23, 17, plan_key="g",
                                        stats=stats)
    assert stats["prep_plan"] == "reused"
    fresh = retrain.prepare_with_reuse(u2, i2, v2, 23, 17, plan_key=None)
    ur, vr = _run_two_sweeps(reused, 23, 17)
    uf, vf = _run_two_sweeps(fresh, 23, 17)
    np.testing.assert_array_equal(ur, uf)
    np.testing.assert_array_equal(vr, vf)


# ---------------------------------------------------------------------------
# continuation quality parity (planted workload)
# ---------------------------------------------------------------------------

def test_continuation_after_tail_reaches_fresh_quality():
    rng = np.random.default_rng(6)
    n_u, n_i, rank = 50, 35, 4
    users, items, vals = _coo(rng, n_u, n_i, 2000, rank=rank)
    cut = int(len(vals) * 0.95)  # last 5% is "the tail"
    base = retrain.als_retrain(
        users[:cut], items[:cut], vals[:cut], n_u, n_i, rank=8,
        iterations=8, l2=0.05, seed=0, tol=0.0)
    stats = {}
    cont = retrain.als_retrain(
        users, items, vals, n_u, n_i, rank=8, iterations=8, l2=0.05,
        seed=0, prev_state=base, tol=1e-3, plan_key="parity",
        stats=stats)
    fresh, _ = als.als_train(users, items, vals, n_u, n_i, rank=8,
                             iterations=8, l2=0.05, seed=0)
    r_cont = als.rmse(cont, users, items, vals)
    r_fresh = als.rmse(fresh, users, items, vals)
    assert stats["mode"] == "continue"
    # parity within a small noise margin, never catastrophically worse
    assert r_cont <= r_fresh * 1.15 + 0.02, (r_cont, r_fresh)


def test_continuation_implicit_path():
    rng = np.random.default_rng(7)
    users, items, vals = _coo(rng, 30, 25, 900)
    weights = np.abs(vals)
    base = retrain.als_retrain(users[:800], items[:800], weights[:800],
                               30, 25, rank=4, iterations=4, l2=0.05,
                               seed=0, implicit=True, tol=0.0)
    stats = {}
    cont = retrain.als_retrain(users, items, weights, 30, 25, rank=4,
                               iterations=6, l2=0.05, seed=0,
                               implicit=True, prev_state=base,
                               tol=1e-3, stats=stats)
    assert stats["mode"] == "continue"
    assert 1 <= stats["sweeps_used"] <= 6
    assert np.all(np.isfinite(np.asarray(cont.user_factors)))


# ---------------------------------------------------------------------------
# engine + workflow plumbing
# ---------------------------------------------------------------------------

def _sweep_counter(mode):
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    m = obs_metrics.REGISTRY.get("pio_train_sweeps_total")
    if m is None:
        return 0.0
    return m.labels(mode=mode).value


def test_engine_continuation_compat_gate():
    """Rank mismatch / foreign model / broken prefix → fresh train."""
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        PreparedData,
    )

    rng = np.random.default_rng(8)
    users, items, vals = _coo(rng, 10, 8, 200)
    pd = PreparedData(
        users=users.astype(np.int32), items=items.astype(np.int32),
        ratings=vals,
        user_bimap=BiMap({f"u{k}": k for k in range(10)}),
        item_bimap=BiMap({f"i{k}": k for k in range(8)}),
        item_years={}, item_categories={})
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=4, num_iterations=2,
                                           seed=1))
    ok_model = ALSModel(
        user_factors=np.zeros((10, 4), np.float32),
        item_factors=np.zeros((8, 4), np.float32),
        user_bimap=pd.user_bimap, item_bimap=pd.item_bimap,
        item_years={}, item_categories={})
    assert algo._continuation_seed(pd, ok_model) is not None
    # rank mismatch
    bad_rank = ALSModel(
        user_factors=np.zeros((10, 6), np.float32),
        item_factors=np.zeros((8, 6), np.float32),
        user_bimap=pd.user_bimap, item_bimap=pd.item_bimap,
        item_years={}, item_categories={})
    assert algo._continuation_seed(pd, bad_rank) is None
    # broken prefix (reordered id space)
    bad_map = ALSModel(
        user_factors=np.zeros((10, 4), np.float32),
        item_factors=np.zeros((8, 4), np.float32),
        user_bimap=BiMap({f"u{k}": (k + 1) % 10 for k in range(10)}),
        item_bimap=pd.item_bimap, item_years={}, item_categories={})
    assert algo._continuation_seed(pd, bad_map) is None
    # foreign model
    assert algo._continuation_seed(pd, object()) is None
    # the public hook falls back to a working fresh train
    from incubator_predictionio_tpu.parallel.context import RuntimeContext

    model = algo.train_with_previous(RuntimeContext(), pd, object())
    assert np.asarray(model.user_factors).shape == (10, 4)


@pytest.fixture
def rec_app():
    from incubator_predictionio_tpu.data.datamap import DataMap
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.storage import App, Storage

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    Storage.get_meta_data_apps().insert(App(0, "contapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("contapp").id
    dao = Storage.get_events()
    rng = np.random.default_rng(0)
    for u in range(8):
        for i in range(6):
            if rng.random() < 0.8:
                dao.insert(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap(
                        {"rating": float(rng.integers(1, 6))}),
                ), app_id)
    yield app_id
    Storage.reset()


def _rec_params(lambda_=0.05):
    from incubator_predictionio_tpu.core import EngineParams
    from incubator_predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams,
        DataSourceParams,
    )

    return EngineParams(
        data_source_params=("", DataSourceParams(app_name="contapp")),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=4, num_iterations=3,
                                       lambda_=lambda_, seed=7))
        ],
    )


def test_workflow_explicit_prev_models_seam(rec_app):
    """The explicit ``run_train(prev_models=)`` override: a caller
    that already holds models seeds the continuation directly, even
    where the implicit lookup could not help (a variant with no prior
    COMPLETED instance)."""
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.workflow import CoreWorkflow

    engine = RecommendationEngine().apply()
    iid1 = CoreWorkflow.run_train(engine, _rec_params(),
                                  engine_variant="seam-a")
    models = CoreWorkflow.load_models(iid1)
    # a FRESH variant: the implicit continuation has nothing to find
    before = _sweep_counter("continue")
    CoreWorkflow.run_train(engine, _rec_params(),
                           engine_variant="seam-b")
    assert _sweep_counter("continue") == before
    # the explicit seam seeds anyway — the caller owns compatibility
    CoreWorkflow.run_train(engine, _rec_params(),
                           engine_variant="seam-c",
                           prev_models=models)
    assert _sweep_counter("continue") > before


def test_workflow_continuation_and_spec_change_auto_disable(rec_app):
    from incubator_predictionio_tpu.data.datamap import DataMap
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.models.recommendation import (
        Query,
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.workflow import CoreWorkflow

    engine = RecommendationEngine().apply()
    before = _sweep_counter("continue")
    iid1 = CoreWorkflow.run_train(engine, _rec_params(),
                                  engine_variant="cont")
    assert _sweep_counter("continue") == before  # first train is fresh

    # append a tail, retrain with identical params → continuation engages
    dao = Storage.get_events()
    for u in range(8):
        dao.insert(Event(
            event="rate", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id="i6",
            properties=DataMap({"rating": 5.0})), rec_app)
    iid2 = CoreWorkflow.run_train(engine, _rec_params(),
                                  engine_variant="cont")
    after = _sweep_counter("continue")
    assert after > before, "continuation retrain never engaged"
    assert iid2 != iid1
    # the continued model still serves
    models = CoreWorkflow.load_models(iid2, engine, _rec_params())
    algo = engine.algorithms(_rec_params())[0]
    assert algo.predict(models[0], Query(user="u1", num=3)).item_scores

    # spec change (λ) → auto-disabled, trains fresh
    CoreWorkflow.run_train(engine, _rec_params(lambda_=0.2),
                           engine_variant="cont")
    assert _sweep_counter("continue") == after

    # knob off → disabled even with identical params
    import os

    os.environ["PIO_RETRAIN_CONTINUE"] = "0"
    try:
        CoreWorkflow.run_train(engine, _rec_params(lambda_=0.2),
                               engine_variant="cont")
        assert _sweep_counter("continue") == after
    finally:
        os.environ.pop("PIO_RETRAIN_CONTINUE", None)


# ---------------------------------------------------------------------------
# satellites riding along
# ---------------------------------------------------------------------------

def test_batch_score_top_k_empty_batch():
    from incubator_predictionio_tpu.ops.topk import batch_score_top_k

    uf = jnp.ones((4, 3), jnp.float32)
    vf = jnp.ones((5, 3), jnp.float32)
    out = np.asarray(batch_score_top_k(uf, vf, [], 3))
    assert out.shape[0] == 2 and out.shape[1] == 0


def test_batch_score_top_k_accepts_ndarray_rows():
    from incubator_predictionio_tpu.ops.topk import batch_score_top_k

    uf = jnp.asarray(np.eye(4, 3, dtype=np.float32))
    vf = jnp.asarray(np.eye(5, 3, dtype=np.float32))
    a = np.asarray(batch_score_top_k(uf, vf, np.asarray([1, 2, 3]), 2))
    b = np.asarray(batch_score_top_k(uf, vf, [1, 2, 3], 2))
    np.testing.assert_array_equal(a, b)
    assert a.shape[1] == 4  # padded to the next power of two


@pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native",
               fromlist=["load"]).load() is None,
    reason="native library unavailable")
def test_cpplog_tail_fold_stats_and_plan(tmp_path, monkeypatch):
    """The scan layer's continuation telemetry: a cache-served scan
    reports its source and the event delta, maintains the prep-plan
    sidecar O(delta), and the folded id tables keep the originals as an
    exact byte prefix (the continuation contract end to end)."""
    from incubator_predictionio_tpu.data.storage import (
        StorageClientConfig,
        cpplog,
        traincache,
    )
    from incubator_predictionio_tpu.data.storage.base import Interactions

    monkeypatch.setattr(traincache, "MIN_NNZ", 4)
    client = cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(tmp_path)}))
    ev = cpplog.CppLogEvents(client, None, prefix="t_")
    try:
        def imp(users, items, t0):
            inter = Interactions(
                user_idx=np.asarray(users, np.int32),
                item_idx=np.asarray(items, np.int32),
                values=np.arange(1, len(users) + 1, dtype=np.float32),
                user_ids=[f"u{k}" for k in range(max(users) + 1)],
                item_ids=[f"i{k}" for k in range(max(items) + 1)],
            )
            assert ev.import_interactions(
                inter, 1, times=t0 + np.arange(len(users), dtype=np.int64),
            ) == len(users)

        imp([0, 1, 2, 0, 1, 2], [0, 1, 0, 1, 2, 2], 1000)
        s0: dict = {}
        first = ev.scan_interactions(
            app_id=1, event_names=("rate",), value_prop="rating",
            stats=s0)
        # the columnar import maintains the projection as it lands, so
        # even the first scan is cache-served — with a zero delta
        assert s0["scan_source"] == "cache"
        assert s0["scan_tail_rows"] == 0

        # tail with one new user and one new item. Import-time cache
        # maintenance is disabled for this batch so the SCAN-time tail
        # fold (the O(delta) retrain read path) is what's exercised.
        monkeypatch.setattr(
            cpplog.CppLogEvents, "_maintain_cache_after_import",
            lambda *a, **k: None)
        imp([1, 3, 2], [3, 0, 1], 2000)
        s1: dict = {}
        second = ev.scan_interactions(
            app_id=1, event_names=("rate",), value_prop="rating",
            stats=s1)
        assert s1["scan_source"] == "cache"
        assert s1["scan_tail_rows"] == 3
        # folded tables keep the first scan's as an exact byte prefix
        assert bytes(second.user_ids.blob).startswith(
            bytes(first.user_ids.blob))
        assert bytes(second.item_ids.blob).startswith(
            bytes(first.item_ids.blob))
        np.testing.assert_array_equal(
            second.user_idx[:len(first.user_idx)], first.user_idx)
        # the plan histograms match exact bincounts of the merged data
        np.testing.assert_array_equal(
            s1["plan_user_degrees"],
            np.bincount(second.user_idx, minlength=len(second.user_ids)))
        np.testing.assert_array_equal(
            s1["plan_item_degrees"],
            np.bincount(second.item_idx, minlength=len(second.item_ids)))
        # retrain-delta gauge exported
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        g = obs_metrics.REGISTRY.get("pio_retrain_delta_rows")
        assert g is not None and g.value == 3
    finally:
        client.close()


def test_prep_plan_sidecar_roundtrip(tmp_path):
    from incubator_predictionio_tpu.data.storage import traincache

    spec = traincache.Spec("user", "item", "rate", "rating")
    p = traincache.plan_path_for(tmp_path / "x.log")
    ud = np.arange(5, dtype=np.int64)
    id_ = np.arange(3, dtype=np.int64) * 2
    traincache.save_plan(p, spec, 100, 0, ud, id_)
    got = traincache.load_plan(p, spec, 100, 0)
    assert got is not None
    np.testing.assert_array_equal(got[0], ud)
    np.testing.assert_array_equal(got[1], id_)
    # any key mismatch reads as "no plan"
    assert traincache.load_plan(p, spec, 101, 0) is None
    assert traincache.load_plan(p, spec, 100, 1) is None
    assert traincache.load_plan(
        p, traincache.Spec("user", "item", "view", "rating"), 100, 0) is None
