"""Randomized differential storage test: the same operation sequence must
be observably identical on every events backend.

The conformance suite (test_storage_conformance.py) pins each behavior
deterministically; this test drives random interleavings of
insert/upsert/delete/find/aggregate against the in-memory model and the
native cpplog + sqlite backends and requires identical results — the
cross-backend contract under sequences nobody thought to write down
(reference counterpart: the storage spec's property of interchangeable
HBase/JDBC/ES drivers).
"""

from datetime import timedelta

import pytest

pytest.importorskip(
    "hypothesis", reason="property-based differential needs hypothesis")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import StorageClientConfig
from incubator_predictionio_tpu.data.storage import memory as memory_backend
from incubator_predictionio_tpu.data.storage import sqlite as sqlite_backend
from incubator_predictionio_tpu.utils.times import parse_iso8601

T0 = parse_iso8601("2022-01-01T00:00:00Z")

_ENTITIES = ("u1", "u2")
_ITEMS = ("i1", "i2")
_NAMES = ("rate", "view", "$set", "$unset", "$delete")
_PROPS = ("rating", "color")

_insert = st.fixed_dictionaries({
    "op": st.just("insert"),
    "name": st.sampled_from(_NAMES),
    "eid": st.sampled_from(_ENTITIES),
    "target": st.one_of(st.none(), st.sampled_from(_ITEMS)),
    "minutes": st.integers(0, 5),
    # sub-millisecond offsets: durable backends store epoch millis, so
    # events differing only at microsecond precision are TIES and must
    # order by insertion everywhere (the memory model once ordered them
    # by microsecond — caught by exactly this)
    "micros": st.sampled_from((0, 400, 900)),
    "prop": st.sampled_from(_PROPS),
    "value": st.one_of(st.integers(0, 3), st.just("red")),
    # a small explicit-id pool forces upsert collisions
    "explicit": st.one_of(st.none(), st.integers(0, 2)),
})
_delete = st.fixed_dictionaries({
    "op": st.just("delete"),
    "which": st.integers(0, 6),  # index into ids seen so far (mod len)
})
#: shared optional time-window bounds (minutes from T0) — window-edge
#: semantics (inclusive start, exclusive until, millis granularity) must
#: agree across backends for BOTH find and aggregate replay
_WINDOW_LO = st.one_of(st.none(), st.integers(0, 4))
_WINDOW_HI = st.one_of(st.none(), st.integers(1, 6))
_find = st.fixed_dictionaries({
    "op": st.just("find"),
    "etype": st.one_of(st.none(), st.just("user")),
    "eid": st.one_of(st.none(), st.sampled_from(_ENTITIES)),
    "names": st.one_of(st.none(), st.just(("rate",)),
                       st.just(("rate", "view"))),
    "lo": _WINDOW_LO,
    "hi": _WINDOW_HI,
    "limit": st.one_of(st.none(), st.integers(1, 4)),
    "reversed": st.booleans(),
})
_aggregate = st.fixed_dictionaries({
    "op": st.just("aggregate"),
    "lo": _WINDOW_LO,
    "hi": _WINDOW_HI,
})

_ops = st.lists(st.one_of(_insert, _delete, _find, _aggregate),
                min_size=1, max_size=25)


def _window_time(op, key):
    return None if op[key] is None else T0 + timedelta(minutes=op[key])


def _canon(e: Event):
    from incubator_predictionio_tpu.utils.times import to_millis

    # times compare at epoch-millis — the durable storage granularity
    # (memory hands back the original microseconds; sqlite/cpplog store
    # millis — equal under the contract)
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, dict(e.properties.to_jsonable()),
            to_millis(e.event_time))


def _apply(ops, events_dao):
    """Run the op list; return the observable outputs for comparison."""
    out = []
    ids: list = []
    for op in ops:
        kind = op["op"]
        if kind == "insert":
            target = op["target"]
            if op["name"].startswith("$"):
                target = None  # $-events carry no target entity
            event = Event(
                event=op["name"], entity_type="user", entity_id=op["eid"],
                target_entity_type="item" if target else None,
                target_entity_id=target,
                properties=DataMap({op["prop"]: op["value"]}),
                event_time=T0 + timedelta(minutes=op["minutes"],
                                          microseconds=op["micros"]),
                event_id=(None if op["explicit"] is None
                          else f"{op['explicit']:032d}"),
            )
            ids.append(events_dao.insert(event, 1))
        elif kind == "delete":
            if ids:
                out.append(
                    ("delete",
                     events_dao.delete(ids[op["which"] % len(ids)], 1)))
        elif kind == "find":
            found = list(events_dao.find(
                app_id=1,
                entity_type=op["etype"],
                entity_id=op["eid"],
                event_names=op["names"],
                start_time=_window_time(op, "lo"),
                until_time=_window_time(op, "hi"),
                limit=op["limit"],
                reversed=op["reversed"],
            ))
            out.append(("find", [_canon(e) for e in found]))
        else:
            agg = events_dao.aggregate_properties(
                app_id=1, entity_type="user",
                start_time=_window_time(op, "lo"),
                until_time=_window_time(op, "hi"))
            out.append(("aggregate", {
                k: dict(v.to_jsonable()) for k, v in sorted(agg.items())
            }))
    # closing snapshot: the full store in time order
    out.append(("final", [_canon(e) for e in events_dao.find(app_id=1)]))
    return out


def _events_for(mod, tmpdir):
    cfg = StorageClientConfig(
        test=True,
        properties={"PATH": (":memory:" if mod is sqlite_backend
                             else str(tmpdir))})
    client = mod.StorageClient(cfg)
    name = mod.__name__.rsplit(".", 1)[1]
    factory = mod.DATA_OBJECTS["Events"]
    return client, factory(client, cfg, prefix=f"diff_{name}_")


@pytest.mark.parametrize("other_name", ["cpplog", "sqlite", "remote"])
@settings(max_examples=30, deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(ops=_ops)
def test_backends_agree_on_random_op_sequences(tmp_path_factory, other_name,
                                               ops):
    srv = None
    if other_name == "cpplog":
        from incubator_predictionio_tpu import native

        if native.load() is None:
            pytest.skip("native library unavailable")
        from incubator_predictionio_tpu.data.storage import cpplog as other

        oth_client, oth_dao = _events_for(
            other, tmp_path_factory.mktemp("diff") / "oth")
    elif other_name == "remote":
        # the wire protocol must transport the order contract verbatim
        from incubator_predictionio_tpu.data.storage import (
            remote as remote_backend,
        )
        from incubator_predictionio_tpu.data.storage.server import (
            StorageServer,
        )

        back_cfg = StorageClientConfig(test=True, properties={})
        back_client = memory_backend.StorageClient(back_cfg)
        srv = StorageServer(memory_backend, back_client, back_cfg,
                            host="127.0.0.1", port=0)
        port = srv.start_background()
        cfg = StorageClientConfig(
            test=True, properties={"URL": f"http://127.0.0.1:{port}"})
        oth_client = remote_backend.StorageClient(cfg)
        oth_dao = remote_backend.DATA_OBJECTS["Events"](
            oth_client, cfg, prefix="diff_remote_")
    else:
        oth_client, oth_dao = _events_for(
            sqlite_backend, tmp_path_factory.mktemp("diff") / "oth")

    mem_client, mem_dao = _events_for(
        memory_backend, tmp_path_factory.mktemp("diff") / "mem")
    try:
        assert _apply(ops, mem_dao) == _apply(ops, oth_dao)
    finally:
        mem_client.close()
        oth_client.close()
        if srv is not None:
            srv.stop()
