"""obs telemetry layer: registry semantics, Prometheus exposition
conformance, /metrics on every server, and trace-ID propagation.

The exposition tests parse the text format with a strict mini-parser
(line grammar + histogram invariants) rather than string-matching, so a
malformed scrape fails loudly. The e2e test drives the real four-server
stack: event ingest and prediction queries carry an ``X-PIO-Trace-Id``
header that must come back on the response AND appear in the JSON span
log line (the docs/observability.md propagation contract).
"""

import json
import logging
import re
import threading
import urllib.error
import urllib.request

import pytest

from fake_engine import AP, make_engine, params
from incubator_predictionio_tpu import native
from incubator_predictionio_tpu.data.storage import AccessKey, App, Storage
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.obs.metrics import Registry
from incubator_predictionio_tpu.servers.admin import AdminServer
from incubator_predictionio_tpu.servers.dashboard import DashboardServer
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)
from incubator_predictionio_tpu.servers.prediction_server import (
    PredictionServer,
    ServerConfig,
)
from incubator_predictionio_tpu.workflow import CoreWorkflow

# -- exposition mini-parser (the conformance oracle) ------------------------
# PROMOTED into obs/expofmt.py when the federation layer needed to
# consume worker scrapes: one strict parser is now both the test oracle
# and the production ingest path (obs/federate.py), so the emitter and
# parser cannot drift apart silently. Malformed input raises
# MalformedExposition (an AssertionError subclass — same failure signal
# the inlined oracle produced).
from incubator_predictionio_tpu.obs.expofmt import (  # noqa: E402
    MalformedExposition,
    histogram_series,
    parse_exposition,
)


def test_promoted_parser_rejects_malformed_lines():
    with pytest.raises(MalformedExposition):
        parse_exposition("no_type_declared 1")
    with pytest.raises(MalformedExposition):
        parse_exposition("# TYPE t gauge\nt{bad 1")
    with pytest.raises(MalformedExposition):
        parse_exposition("# TYPE t nonsense\nt 1")


def scrape(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        return resp.read().decode("utf-8")


# -- registry unit behavior -------------------------------------------------

def test_exposition_format_conformance():
    reg = Registry()
    c = reg.counter("t_requests_total", "requests", labels=("route",))
    c.labels(route="/a").inc(3)
    c.labels(route='/with"quote').inc()
    g = reg.gauge("t_depth", "queue depth")
    g.set(7.5)
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    types, samples = parse_exposition(reg.expose())
    assert types["t_requests_total"] == "counter"
    assert types["t_depth"] == "gauge"
    assert types["t_lat_seconds"] == "histogram"
    assert samples[("t_requests_total",
                    frozenset({("route", "/a")}))] == 3
    assert samples[("t_depth", frozenset())] == 7.5
    buckets, s, total = histogram_series(samples, "t_lat_seconds")
    assert total == 2 and s == pytest.approx(5.05)
    # cumulative buckets are monotone and +Inf equals the count
    assert [b for b, _ in buckets] == [0.1, 1.0, float("inf")]
    assert [v for _, v in buckets] == [1, 1, 2]


def test_metric_and_label_name_validation():
    reg = Registry()
    with pytest.raises(ValueError):
        reg.counter("bad-name", "x")
    with pytest.raises(ValueError):
        reg.counter("ok_name", "x", labels=("bad-label",))


def test_get_or_create_and_kind_mismatch():
    reg = Registry()
    a = reg.counter("t_total", "x")
    assert reg.counter("t_total", "x") is a
    with pytest.raises(ValueError):
        reg.gauge("t_total", "x")
    with pytest.raises(ValueError):
        reg.counter("t_total", "x", labels=("l",))
    # a histogram bucket-layout mismatch raises too (silently sharing
    # a series binned by the wrong bounds would produce lying quantiles)
    h = reg.histogram("t_b_seconds", "x", buckets=(1.0, 2.0))
    assert reg.histogram("t_b_seconds", "x") is h           # no opinion
    assert reg.histogram("t_b_seconds", "x", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("t_b_seconds", "x", buckets=(1.0, 4.0))


def test_counter_rejects_negative_and_labels_mismatch():
    reg = Registry()
    c = reg.counter("t_n_total", "x")
    with pytest.raises(ValueError):
        c.inc(-1)
    lc = reg.counter("t_l_total", "x", labels=("a",))
    with pytest.raises(ValueError):
        lc.labels(b="1")


def test_histogram_bucket_math_and_quantiles():
    reg = Registry()
    h = reg.histogram("t_h_seconds", "x", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 10.0):
        h.observe(v)
    _types, samples = parse_exposition(reg.expose())
    buckets, s, total = histogram_series(samples, "t_h_seconds")
    assert [v for _, v in buckets] == [1, 2, 3, 4]
    assert total == 4 and s == pytest.approx(15.0)
    # boundary value lands in its own le bucket (le semantics)
    h2 = reg.histogram("t_h2_seconds", "x", buckets=(1.0, 2.0))
    h2.observe(1.0)
    assert h2._solo().snapshot()[0] == [1, 0, 0]
    # quantiles: linear interpolation inside the bucket
    assert h.quantile(0.5) == pytest.approx(2.0)
    assert h.quantile(1.0) == pytest.approx(4.0)  # overflow clamps
    assert reg.histogram("t_empty_seconds", "x").quantile(0.5) is None


def test_weighted_observe_counts_n():
    reg = Registry()
    h = reg.histogram("t_w_seconds", "x", buckets=(1.0,))
    h.observe(0.5, 64)
    assert h.count == 64 and h.sum == pytest.approx(32.0)
    assert h.quantile(0.99) <= 1.0


def test_concurrent_increment_correctness():
    reg = Registry()
    c = reg.counter("t_conc_total", "x")
    h = reg.histogram("t_conc_seconds", "x", buckets=(1.0,))
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert h.sum == pytest.approx(0.5 * n_threads * per_thread)


def test_collector_runs_at_scrape_and_replaces_by_name():
    reg = Registry()
    g = reg.gauge("t_coll", "x")
    reg.register_collector("k", lambda: g.set(1))
    reg.register_collector("k", lambda: g.set(2))  # replaces
    reg.expose()
    assert g.value == 2
    # a failing collector is skipped, never fails the scrape
    reg.register_collector("boom", lambda: 1 / 0)
    assert "t_coll" in reg.expose()


def test_trace_id_accept_and_generate():
    assert obs_trace.accept_trace_id("abc-123.X:ok") == "abc-123.X:ok"
    fresh = obs_trace.accept_trace_id(None)
    assert re.fullmatch(r"[0-9a-f]{16}", fresh)
    # malformed (spaces / too long / log-breaking bytes) is REPLACED
    assert obs_trace.accept_trace_id("has space") != "has space"
    assert obs_trace.accept_trace_id("x" * 200) != "x" * 200
    assert obs_trace.accept_trace_id('inj"ect\n') != 'inj"ect\n'


# -- the four-server stack --------------------------------------------------

@pytest.fixture(scope="module")
def stack():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = Storage.get_meta_data_apps().insert(App(0, "obs-app"))
    Storage.get_meta_data_access_keys().insert(AccessKey("obskey", app_id))
    engine = make_engine()
    # run_train exports the workflow-phase gauges as a side effect
    CoreWorkflow.run_train(engine, params(ds=9, algos=[("algo0", AP(1))]),
                           engine_variant="obs")
    es = EventServer(EventServerConfig(ip="127.0.0.1", port=0, stats=True))
    ps = PredictionServer(engine, ServerConfig(
        ip="127.0.0.1", port=0, engine_variant="obs"))
    ad = AdminServer(ip="127.0.0.1", port=0)
    db = DashboardServer(ip="127.0.0.1", port=0)
    # the FIFTH server: a storage server over its own memory backend, so
    # the trace/metrics contracts are pinned on every server this repo
    # runs (the cross-process hop target of data/storage/remote.py)
    from incubator_predictionio_tpu.data.storage import (
        StorageClientConfig,
    )
    from incubator_predictionio_tpu.data.storage import (
        memory as memory_backend,
    )
    from incubator_predictionio_tpu.data.storage.server import (
        StorageServer,
    )

    st_config = StorageClientConfig(test=True, properties={})
    st = StorageServer(memory_backend,
                       memory_backend.StorageClient(st_config), st_config,
                       host="127.0.0.1", port=0)
    ports = {
        "event": es.start_background(),
        "prediction": ps.start_background(),
        "admin": ad.start_background(),
        "dashboard": db.start_background(),
        "storage": st.start_background(),
    }
    yield ports
    for srv in (es, ps, ad, db, st):
        srv.stop()
    Storage.reset()


def post(port, path, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"null")


EV = {"event": "rate", "entityType": "user", "entityId": "u1",
      "targetEntityType": "item", "targetEntityId": "i1",
      "properties": {"rating": 5}}


def test_metrics_route_on_all_four_servers(stack):
    # exercise ingest + batch + query first so the scrape has content
    status, _h, _b = post(stack["event"], "/events.json?accessKey=obskey",
                          EV)
    assert status == 201
    status, _h, _b = post(stack["event"],
                          "/batch/events.json?accessKey=obskey", [EV, EV])
    assert status == 200
    status, _h, body = post(stack["prediction"], "/queries.json", {"qx": 4})
    assert status == 200 and body["qx"] == 4

    for name, port in stack.items():
        types, samples = parse_exposition(scrape(port))
        # the shared HTTP-layer metrics exist everywhere
        assert types["pio_http_requests_total"] == "counter", name
        assert types["pio_http_request_seconds"] == "histogram", name

    _types, samples = parse_exposition(scrape(stack["event"]))
    # per-event ingest counters, by route pattern and status
    assert samples[("pio_ingest_events_total", frozenset(
        {("route", "/events.json"), ("status", "201")}))] >= 1
    assert samples[("pio_ingest_events_total", frozenset(
        {("route", "/batch/events.json"), ("status", "201")}))] >= 2
    # batch-size histogram booked once for the 2-event batch
    buckets, _s, total = histogram_series(samples, "pio_ingest_batch_size")
    assert total >= 1

    types, samples = parse_exposition(scrape(stack["prediction"]))
    # per-query latency histogram + queue-depth gauge — both carry the
    # tenant label now (serving/tenancy.py); unregistered traffic books
    # under the bounded "default" child
    _buckets, lat_sum, lat_count = histogram_series(
        samples, "pio_query_latency_seconds",
        frozenset({("tenant", "default")}))
    assert lat_count >= 1 and lat_sum > 0
    assert ("pio_serve_queue_depth",
            frozenset({("tenant", "default")})) in samples
    # workflow-phase gauges exported by run_train (one scrape sees the
    # whole process: serving AND the last training run)
    assert samples[("pio_workflow_phase_seconds", frozenset(
        {("phase", "checkpoint")}))] >= 0
    assert samples[("pio_workflow_runs_total", frozenset())] >= 1


def test_compile_cache_metrics_registered(tmp_path, monkeypatch):
    from incubator_predictionio_tpu.utils import compile_cache

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
    compile_cache.enable(str(tmp_path))
    text = obs_metrics.REGISTRY.expose()
    types, samples = parse_exposition(text)
    assert types["pio_compile_cache_hits_total"] == "counter"
    assert types["pio_compile_cache_requests_total"] == "counter"
    # the miss gauge derives at scrape time (requests - hits)
    assert ("pio_compile_cache_misses", frozenset()) in samples


def test_status_page_tail_latency(stack):
    post(stack["prediction"], "/queries.json", {"qx": 7})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{stack['prediction']}/", timeout=30) as resp:
        info = json.loads(resp.read())
    # p50/p95/p99 derived from the histogram — visible without a scraper
    assert info["servingSecP50"] is not None
    assert info["servingSecP95"] is not None
    assert info["servingSecP99"] >= info["servingSecP50"] > 0


def test_trace_id_e2e_response_and_span_log(stack, caplog):
    tid = "e2e-trace-0042"
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        status, headers, _b = post(
            stack["event"], "/events.json?accessKey=obskey", EV,
            headers={"X-PIO-Trace-Id": tid})
        assert status == 201
        assert headers["X-PIO-Trace-Id"] == tid
        status, headers, _b = post(
            stack["prediction"], "/queries.json", {"qx": 1},
            headers={"X-PIO-Trace-Id": tid})
        assert status == 200
        assert headers["X-PIO-Trace-Id"] == tid
    spans = [json.loads(r.getMessage()) for r in caplog.records
             if r.name == "pio.trace"]
    mine = [s for s in spans if s["traceId"] == tid]
    routes = {(s["server"], s["route"]) for s in mine}
    assert ("event", "/events.json") in routes
    assert ("prediction", "/queries.json") in routes
    for s in mine:
        assert s["span"] == "http.request"
        assert s["durationMs"] >= 0
        assert s["status"] in (200, 201)
        # every span line carries its own span ID + wall stamp (the
        # cross-process stitching contract, scripts/trace_stitch.py)
        assert re.fullmatch(r"[0-9a-f]{8}", s["spanId"])
        assert s["ts"] > 0


def test_parent_span_header_links_spans(stack, caplog):
    """A hop that forwards X-PIO-Parent-Span gets a span line whose
    parentSpanId is the upstream span — the in-repo client contract
    (obs_trace.client_headers)."""
    tid = "parent-span-e2e-01"
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        status, headers, _b = post(
            stack["event"], "/events.json?accessKey=obskey", EV,
            headers={"X-PIO-Trace-Id": tid})
        assert status == 201
        parent = headers["X-PIO-Span-Id"]      # echoed server-side span
        status, headers2, _b = post(
            stack["prediction"], "/queries.json", {"qx": 1},
            headers={"X-PIO-Trace-Id": tid, "X-PIO-Parent-Span": parent})
        assert status == 200
    spans = [json.loads(r.getMessage()) for r in caplog.records
             if r.name == "pio.trace" and
             json.loads(r.getMessage()).get("traceId") == tid]
    child = [s for s in spans if s.get("parentSpanId")]
    assert child and child[0]["parentSpanId"] == parent
    assert child[0]["server"] == "prediction"
    # malformed parent headers are DROPPED, never echoed into linkage
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        post(stack["prediction"], "/queries.json", {"qx": 1},
             headers={"X-PIO-Trace-Id": "parent-span-e2e-02",
                      "X-PIO-Parent-Span": "bad parent!"})
    bad = [json.loads(r.getMessage()) for r in caplog.records
           if r.name == "pio.trace"
           and json.loads(r.getMessage()).get("traceId")
           == "parent-span-e2e-02"]
    assert bad and "parentSpanId" not in bad[0]


def test_trace_echo_and_span_on_error_paths(stack, caplog):
    """4xx/5xx responses from ALL FIVE servers still echo
    X-PIO-Trace-Id and emit a span line — a failing hop is the one an
    operator most needs to find in the trace tree. (Until this test the
    contract was only pinned on the happy path.)"""
    def get_err(port, path, tid):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            headers={"X-PIO-Trace-Id": tid})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    cases = {
        # (server, how to provoke an error) → expected status family
        "event": lambda p: post(p, "/events.json?accessKey=obskey",
                                {"not": "an event"},
                                headers={"X-PIO-Trace-Id": "err-event"})[:2],
        "prediction": lambda p: post(
            p, "/nope.json", {},
            headers={"X-PIO-Trace-Id": "err-prediction"})[:2],
        "admin": lambda p: post(
            p, "/cmd/app", {},
            headers={"X-PIO-Trace-Id": "err-admin"})[:2],
        "dashboard": lambda p: post(
            p, "/no/such/page", {},
            headers={"X-PIO-Trace-Id": "err-dashboard"})[:2],
        # /rpc reports DAO errors in-band (msgpack envelope, 200) by
        # design — the HTTP-layer error path is an unrouted 404
        "storage": lambda p: get_err(p, "/no/such/route", "err-storage"),
    }
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        for name, provoke in cases.items():
            status, headers = provoke(stack[name])
            assert 400 <= status < 600, (name, status)
            # the error response STILL echoes the trace ID...
            assert headers["X-PIO-Trace-Id"] == f"err-{name}", name
            assert headers["X-PIO-Span-Id"], name
    spans = [json.loads(r.getMessage()) for r in caplog.records
             if r.name == "pio.trace"]
    by_trace = {s["traceId"]: s for s in spans}
    for name in cases:
        # ...and the span line was emitted, status included
        s = by_trace.get(f"err-{name}")
        assert s is not None, (name, sorted(by_trace))
        assert s["server"] == name
        assert 400 <= s["status"] < 600


def test_trace_id_generated_when_absent(stack):
    status, headers, _b = post(
        stack["event"], "/events.json?accessKey=obskey", EV)
    assert status == 201
    assert re.fullmatch(r"[0-9a-f]{16}", headers["X-PIO-Trace-Id"])
    # malformed incoming ids are replaced, never echoed
    status, headers, _b = post(
        stack["event"], "/events.json?accessKey=obskey", EV,
        headers={"X-PIO-Trace-Id": "bad id with spaces"})
    assert headers["X-PIO-Trace-Id"] != "bad id with spaces"


def test_unmatched_routes_collapse_to_one_series(stack):
    for path in ("/nope/a", "/nope/b"):
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{stack['event']}{path}", timeout=30)
        except urllib.error.HTTPError as e:
            assert e.code == 404
    _types, samples = parse_exposition(scrape(stack["event"]))
    assert samples[("pio_http_requests_total", frozenset(
        {("server", "event"), ("method", "GET"),
         ("route", "<unmatched>"), ("status", "404")}))] >= 2
    # a method mismatch on a KNOWN path books under the real route
    # pattern, not <unmatched> — 405 traffic is not scanner noise
    status, _h, _b = post(stack["event"], "/", {})
    assert status == 405
    _types, samples = parse_exposition(scrape(stack["event"]))
    assert samples[("pio_http_requests_total", frozenset(
        {("server", "event"), ("method", "POST"),
         ("route", "/"), ("status", "405")}))] >= 1


def test_trace_sample_knob(monkeypatch):
    """PIO_TRACE_SAMPLE gates ONLY the span line; IDs keep flowing."""
    monkeypatch.setenv("PIO_TRACE_SAMPLE", "0")
    assert obs_trace.sample_rate() == 0.0
    assert obs_trace.span_sampled() is False
    monkeypatch.setenv("PIO_TRACE_SAMPLE", "1.0")
    assert obs_trace.span_sampled() is True
    monkeypatch.setenv("PIO_TRACE_SAMPLE", "not-a-number")
    assert obs_trace.sample_rate() == 1.0
    monkeypatch.setenv("PIO_TRACE_SAMPLE", "7")   # clamped
    assert obs_trace.sample_rate() == 1.0
    monkeypatch.delenv("PIO_TRACE_SAMPLE")
    assert obs_trace.sample_rate() == 1.0


def test_sampled_out_requests_keep_trace_ids(stack, monkeypatch, caplog):
    monkeypatch.setenv("PIO_TRACE_SAMPLE", "0")
    tid = "sampled-out-0001"
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        status, headers, _b = post(
            stack["event"], "/events.json?accessKey=obskey", EV,
            headers={"X-PIO-Trace-Id": tid})
    assert status == 201
    # the propagation contract is unconditional...
    assert headers["X-PIO-Trace-Id"] == tid
    # ...only the span LINE was sampled away
    spans = [json.loads(r.getMessage()) for r in caplog.records
             if r.name == "pio.trace"]
    assert not [s for s in spans if s.get("traceId") == tid]


def test_build_info_constant_gauge(stack):
    """pio_build_info{version,jax_version,backend} == 1 on every
    server's scrape (the standard join-target idiom)."""
    for name, port in stack.items():
        _types, samples = parse_exposition(scrape(port))
        hits = [(ls, v) for (n, ls), v in samples.items()
                if n == "pio_build_info"]
        assert hits, name
        labels, value = hits[0]
        assert value == 1
        keys = {k for k, _v in labels}
        assert keys == {"version", "jax_version", "backend"}


def test_latency_buckets_resolve_sub_millisecond():
    """The extended bucket floor: sub-ms observations (device fold-in
    solves) must not all collapse into the first bucket."""
    bounds = obs_metrics.DEFAULT_LATENCY_BUCKETS
    assert bounds[0] < 1e-4          # extended downward...
    assert 1e-4 in bounds            # ...keeping the old bounds aligned
    assert max(bounds) > 10.0
    reg = Registry()
    h = reg.histogram("t_subms_seconds", "x")
    h.observe(20e-6)
    h.observe(300e-6)
    counts = h._solo().snapshot()[0]
    occupied = [i for i, c in enumerate(counts) if c]
    assert len(occupied) == 2        # distinct buckets, not one heap


def test_histogram_snapshot_consistent_under_threaded_observation():
    """snapshot() must return a CONSISTENT (counts, sum, count) triple
    while writers hammer the child — sum/count never drift from the
    per-bucket totals."""
    reg = Registry()
    h = reg.histogram("t_snap_seconds", "x", buckets=(1.0,))
    stop = threading.Event()

    def work():
        while not stop.is_set():
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            counts, s, total = h._solo().snapshot()
            assert sum(counts) == total
            assert s == pytest.approx(0.5 * total)
    finally:
        stop.set()
        for t in threads:
            t.join()


def test_concurrent_scrape_during_server_shutdown():
    """Scrapes racing a server shutdown must either answer cleanly or
    fail with a connection error — never hang or corrupt the registry
    (the next scrape still parses)."""
    from incubator_predictionio_tpu.obs.http import add_metrics_route
    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    r = Router()
    add_metrics_route(r)
    srv = HttpServer(r, "127.0.0.1", 0, name="t_shutdown")
    port = srv.start_background()
    errors: list = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                # short socket timeout: a connection the dying server
                # accepted but never services must resolve well inside
                # the join window below, or a loaded box reads the
                # normal timeout as a "hang"
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=5) as resp:
                    text = resp.read().decode("utf-8")
                parse_exposition(text)
            except AssertionError as e:      # malformed exposition
                errors.append(e)
                return
            except Exception:
                return  # connection refused/reset mid-shutdown: fine

    threads = [threading.Thread(target=scraper) for _ in range(6)]
    for t in threads:
        t.start()
    srv.stop()
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "scraper hung across shutdown"
    assert not errors, errors
    # the registry survived the race: a fresh exposition still parses
    parse_exposition(obs_metrics.REGISTRY.expose())


@pytest.mark.skipif(native.load() is None,
                    reason="native library unavailable")
def test_native_storage_metrics_bridge(tmp_path):
    """cpplog's group-commit and scan counters surface as gauges on the
    process registry at scrape time."""
    import numpy as np

    from incubator_predictionio_tpu.data.storage import base, cpplog
    from incubator_predictionio_tpu.data.storage import (
        StorageClientConfig,
    )

    client = cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(tmp_path)}))
    events = cpplog.CppLogEvents(client, client.config, prefix="t_")
    try:
        ids = events.insert_interactions(
            base.Interactions(
                user_idx=np.array([0, 1], np.int32),
                item_idx=np.array([0, 0], np.int32),
                values=np.array([5.0, 3.0], np.float32),
                user_ids=["u1", "u2"], item_ids=["i1"]),
            app_id=1)
        assert len(ids) == 2
        events.scan_interactions(app_id=1, event_names=("rate",),
                                 value_prop="rating")
        types, samples = parse_exposition(obs_metrics.REGISTRY.expose())
        assert samples[("pio_group_commit_events", frozenset())] >= 2
        assert samples[("pio_group_commit_appends", frozenset())] >= 1
        assert ("pio_scan_wall_seconds", frozenset()) in samples
        assert ("pio_scan_lock_held_seconds", frozenset()) in samples
        assert samples[("pio_scan_rows", frozenset())] >= 2
        assert types["pio_scan_shards"] == "gauge"
    finally:
        obs_metrics.REGISTRY.unregister_collector("cpplog_native")
        client.close()
