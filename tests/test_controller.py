"""Self-driving freshness controller (obs/controller.py).

The pins, in the order the ISSUE promises them:

- trigger math: staleness-headroom projection acts BEFORE the bound is
  crossed; burn-rate breach acts on a measured breach; healthy fleets
  and no-data fleets never trigger;
- hysteresis (consecutive breached evaluations), cooldown after an
  action, and the capacity budget guard (reason="budget" when the
  measured fit says a retrain cannot finish inside the projected
  budget);
- dry-run: observe mode records the would-act decision, actuates
  nothing;
- THE kill-switch contract: flipping ``PIO_CONTROLLER``/POST
  ``/controller`` mid-run halts actuation within ONE evaluation period;
- the decision audit trail: every evaluation appends a structured
  record, actuation spans land under the decision's own trace ID,
  the trace ID crosses the HTTP reload hop (and the front door's
  rolling-reload choreography forwards it to every worker), and
  ``trace_stitch --decisions`` stitches the tree / flags orphan
  actuations loudly;
- ``GET /controller`` + ``POST /controller`` on the admin server.
"""

import json
import logging
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.obs import controller as ctl_mod
from incubator_predictionio_tpu.obs import slo as obs_slo
from incubator_predictionio_tpu.obs.controller import (
    ControllerConfig,
    FreshnessController,
    capacity_budget_fn,
    http_reload_fn,
)
from incubator_predictionio_tpu.obs.metrics import Registry
from incubator_predictionio_tpu.obs.slo import SLOEngine, SLOSpec
from incubator_predictionio_tpu.utils.times import FakeClock

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import trace_stitch  # noqa: E402


# ---------------------------------------------------------------------------
# harness: a planted fleet signal (staleness gauge SLO on a fresh
# registry, fake clock, no sleeps)
# ---------------------------------------------------------------------------

def planted_engine(clock, threshold=100.0):
    reg = Registry()
    gauge = reg.gauge("pio_model_staleness_seconds", "x")
    spec = SLOSpec(name="staleness",
                   metric="pio_model_staleness_seconds",
                   threshold=threshold, target=0.99, kind="gauge")
    eng = SLOEngine(specs=(spec,), registry=reg, clock=clock,
                    fast_window_s=60.0, slow_window_s=600.0,
                    min_tick_interval_s=0.0, export_gauges=False)
    return eng, gauge


def make_controller(clock, engine, horizon=10.0, breach_evals=1,
                    cooldown=0.0, interval=0.05, **kw):
    calls = {"retrain": 0, "reload": 0}

    def retrain():
        calls["retrain"] += 1
        return f"inst-{calls['retrain']}"

    def reload():
        calls["reload"] += 1
        return {"reloaded": 2}

    ctl = FreshnessController(
        engine=engine,
        retrain_fn=kw.pop("retrain_fn", retrain),
        reload_fn=kw.pop("reload_fn", reload),
        config=ControllerConfig(interval_s=interval,
                                breach_evals=breach_evals,
                                cooldown_s=cooldown,
                                horizon_s=horizon, ring=64),
        clock=clock, mode=kw.pop("mode", "act"), **kw)
    return ctl, calls


# ---------------------------------------------------------------------------
# trigger math
# ---------------------------------------------------------------------------

def test_healthy_fleet_never_triggers():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng)
    gauge.set(5.0)                     # headroom 95 >> horizon 10
    d = ctl.evaluate_once()
    assert d["action"] == "none"
    assert d["reason"] == "healthy"
    assert calls == {"retrain": 0, "reload": 0}
    assert d["projection"]["stalenessHeadroomS"] == pytest.approx(95.0)
    assert d["inputs"]["slos"]["staleness"]["fastBurn"] == 0.0


def test_no_data_is_a_skip_not_a_trigger():
    clock = FakeClock(100.0)
    reg = Registry()                   # gauge never registered/set
    spec = SLOSpec(name="staleness",
                   metric="pio_model_staleness_seconds",
                   threshold=100.0, target=0.99, kind="gauge")
    eng = SLOEngine(specs=(spec,), registry=reg, clock=clock,
                    min_tick_interval_s=0.0, export_gauges=False)
    ctl, calls = make_controller(clock, eng)
    d = ctl.evaluate_once()
    assert d["reason"] == "no_data"
    assert calls == {"retrain": 0, "reload": 0}


def test_staleness_headroom_projection_acts_before_the_bound():
    """The controller's whole point: the gauge grows 1 s/s, so it must
    act when threshold − value falls under the horizon — BEFORE the
    SLO ever records a bad tick."""
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock, threshold=100.0)
    ctl, calls = make_controller(clock, eng, horizon=10.0)
    gauge.set(95.0)                    # still UNDER the bound
    d = ctl.evaluate_once()
    assert d["trigger"] == "staleness_projection"
    assert d["action"] == "retrain+reload"
    assert d["outcome"]["actuated"] is True
    assert d["outcome"]["retrain"]["ok"] is True
    assert d["outcome"]["reload"]["ok"] is True
    assert calls == {"retrain": 1, "reload": 1}
    # the SLO itself never breached — the projection did the work
    assert d["inputs"]["slos"]["staleness"]["fastBurn"] == 0.0
    assert d["projection"]["projectionS"] == pytest.approx(5.0)


def test_burn_breach_triggers():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock, threshold=100.0)
    ctl, calls = make_controller(clock, eng, horizon=1.0)
    gauge.set(5000.0)                  # far over the bound: bad ticks
    eng.tick(force=True)
    clock.advance(5)
    d = ctl.evaluate_once()
    assert d["trigger"] == "staleness_burn"
    assert calls["retrain"] == 1


def test_projection_burn_math():
    """burnExhaustS = slow_window · budget_remaining / fast_burn (the
    projection the exported gauge carries) — checked at a sub-breach
    burn (0 < burn < 1) where every term is non-trivial."""
    clock = FakeClock(100.0)
    reg = Registry()
    h = reg.histogram("t_fresh_seconds", "x", buckets=(1.0,))
    spec = SLOSpec(name="freshness_p95", metric="t_fresh_seconds",
                   threshold=1.0, target=0.95)
    eng = SLOEngine(specs=(spec,), registry=reg, clock=clock,
                    fast_window_s=60.0, slow_window_s=600.0,
                    min_tick_interval_s=0.0, export_gauges=False)
    ctl, _calls = make_controller(clock, eng, mode="observe")
    eng.tick(force=True)               # zero baseline snapshot
    h.observe(0.5, 98)
    h.observe(5.0, 2)                  # 2% bad, allowed 5% -> burn 0.4
    clock.advance(10)
    d = ctl.evaluate_once()
    assert d["reason"] == "healthy"    # burning, but slowly
    proj = d["projection"]
    slos = d["inputs"]["slos"]["freshness_p95"]
    assert 0.0 < slos["fastBurn"] < 1.0
    expected = 600.0 * slos["budgetRemaining"] / slos["fastBurn"]
    assert proj["burnExhaustS"] == pytest.approx(expected, rel=1e-3)
    assert proj["burnExhaustS"] > ctl.config.horizon_s


# ---------------------------------------------------------------------------
# hysteresis / cooldown / budget / observe
# ---------------------------------------------------------------------------

def test_hysteresis_requires_consecutive_breaches():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng, breach_evals=3)
    gauge.set(95.0)
    assert ctl.evaluate_once()["reason"] == "hysteresis"
    assert ctl.evaluate_once()["reason"] == "hysteresis"
    d = ctl.evaluate_once()            # third consecutive: act
    assert d["outcome"]["actuated"] is True
    assert d["streak"] == 3
    assert calls["retrain"] == 1
    # a healthy evaluation RESETS the streak
    gauge.set(1.0)
    assert ctl.evaluate_once()["reason"] == "healthy"
    gauge.set(95.0)
    assert ctl.evaluate_once()["reason"] == "hysteresis"


def test_cooldown_blocks_reflap():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng, cooldown=60.0)
    gauge.set(95.0)
    assert ctl.evaluate_once()["outcome"]["actuated"] is True
    # the planted reload did not actually refresh the gauge: the
    # trigger holds, but the cooldown must hold fire
    d = ctl.evaluate_once()
    assert d["reason"] == "cooldown"
    assert d["cooldownRemainingS"] > 0
    assert calls["retrain"] == 1
    clock.advance(61.0)
    assert ctl.evaluate_once()["outcome"]["actuated"] is True
    assert calls["retrain"] == 2


def test_budget_guard_skips_when_capacity_is_binding():
    """The capacity fit says the retrain cannot finish before the
    budget empties: reason="budget" — the runbook's 'capacity, not
    freshness, is the binding constraint' signal."""
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng,
                                 capacity_fn=lambda: 1000.0)
    gauge.set(95.0)                    # projection 5 s << 1000 s wall
    d = ctl.evaluate_once()
    assert d["reason"] == "budget"
    assert d["projection"]["retrainWallEstS"] == 1000.0
    assert calls == {"retrain": 0, "reload": 0}
    # an affordable retrain passes the same gate
    ctl2, calls2 = make_controller(clock, eng,
                                   capacity_fn=lambda: 2.0)
    d = ctl2.evaluate_once()
    assert d["outcome"]["actuated"] is True
    assert d["projection"]["retrainWallEstS"] == 2.0
    assert calls2["retrain"] == 1


def test_observe_mode_is_a_dry_run():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng, mode="observe")
    gauge.set(95.0)
    d = ctl.evaluate_once()
    assert d["action"] == "retrain+reload"   # WOULD have acted
    assert d["reason"] == "observe"
    assert d["outcome"] == {"actuated": False, "dryRun": True}
    assert calls == {"retrain": 0, "reload": 0}


def test_failed_retrain_skips_the_reload():
    """A retrain that dies leaves the OLD model serving — hot-swapping
    nothing is the safe degradation, so the reload must not run."""
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)

    def bad_retrain():
        raise RuntimeError("train blew up")

    ctl, calls = make_controller(clock, eng, retrain_fn=bad_retrain)
    gauge.set(95.0)
    d = ctl.evaluate_once()
    assert d["outcome"]["retrain"]["ok"] is False
    assert d["outcome"]["reload"] == {"ok": False,
                                      "skipped": "retrain_failed"}
    assert calls["reload"] == 0


def test_capacity_budget_fn_without_inputs_is_no_guard(monkeypatch):
    monkeypatch.delenv("PIO_CONTROLLER_ROWS", raising=False)
    assert capacity_budget_fn()() is None
    # the env-wired controller reports an inert guard as ABSENT: the
    # operator must never believe retrains are capacity-guarded when
    # the guard cannot veto
    ctl_mod.reset_controller()
    try:
        ctl = ctl_mod.get_controller()
        assert ctl.stats()["actuators"]["capacityGuard"] is False
    finally:
        ctl_mod.reset_controller()


def test_slo_error_resets_hysteresis_and_projection_gauge():
    """A blind evaluation (fleet scrape failed) must break the
    CONSECUTIVE-breach chain — hysteresis cannot count across a gap it
    could not see — and the exported projection gauge goes NaN instead
    of freezing at its last pre-outage value (which a dashboard would
    read as live headroom). The scrape must survive the NaN."""
    import math

    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng, breach_evals=2)
    gauge.set(95.0)
    assert ctl.evaluate_once()["reason"] == "hysteresis"   # streak 1
    real_eval = eng.evaluate
    eng.evaluate = lambda: (_ for _ in ()).throw(
        RuntimeError("fleet down"))
    d = ctl.evaluate_once()
    assert d["reason"] == "slo_error"
    assert math.isnan(ctl_mod._PROJECTION.value)
    assert "pio_controller_budget_projection_seconds NaN" in \
        obs_metrics.REGISTRY.expose()
    eng.evaluate = real_eval
    # the chain restarted: the next trigger is streak 1 again
    d = ctl.evaluate_once()
    assert d["reason"] == "hysteresis"
    assert d["streak"] == 1
    assert calls["retrain"] == 0


# ---------------------------------------------------------------------------
# THE kill switch: halt within one evaluation period
# ---------------------------------------------------------------------------

def test_kill_switch_halts_within_one_evaluation_period():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, calls = make_controller(clock, eng)   # interval 0.05 s
    gauge.set(95.0)                            # permanent trigger
    ctl.start()
    try:
        deadline = time.monotonic() + 5.0
        while calls["retrain"] < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert calls["retrain"] >= 2, "controller loop never acted"
        ctl.set_mode("off")
        # one evaluation period for the flip to land (plus one possibly
        # in-flight action)
        time.sleep(0.15)
        frozen = calls["retrain"]
        time.sleep(0.5)                       # ten more periods
        assert calls["retrain"] == frozen, (
            "actuation continued after the kill switch")
        # flipping back resumes without a restart
        ctl.set_mode("act")
        deadline = time.monotonic() + 5.0
        while calls["retrain"] == frozen and time.monotonic() < deadline:
            time.sleep(0.01)
        assert calls["retrain"] > frozen
    finally:
        ctl.stop()
    # both flips are audit-trailed
    kinds = [d for d in ctl.decisions(limit=64)
             if d.get("kind") == "mode_change"]
    assert [(d["from"], d["to"]) for d in kinds[::-1]] == [
        ("act", "off"), ("off", "act")]


def test_timed_out_stop_cannot_resurrect_the_old_loop():
    """A stop() whose join times out on a long in-flight actuation must
    not let a later start() revive the old loop into a second
    concurrent controller: each generation owns its own stop event, so
    the old thread exits the moment its actuation returns."""
    import threading

    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    release = threading.Event()
    entered = threading.Event()

    def slow_retrain():
        entered.set()
        release.wait(10)
        return "slow"

    ctl, _calls = make_controller(clock, eng, retrain_fn=slow_retrain,
                                  reload_fn=lambda: {"ok": True})
    gauge.set(95.0)
    ctl.start()
    assert entered.wait(5)
    # the audit contract DURING a long actuation: the in-flight action
    # is already in the ring, marked as such — "the ring IS the
    # answer" must hold exactly while the retrain runs
    inflight = [d for d in ctl.decisions(limit=8)
                if (d.get("outcome") or {}).get("inFlight")]
    assert inflight and inflight[0]["action"] == "retrain+reload"
    ctl.stop(timeout=0.05)      # join times out: actuation in flight
    ctl.start()                 # new generation while the old lives
    time.sleep(0.2)             # let the new loop reach its actuation
    ctl.set_mode("off")         # idle the NEW loop before releasing
    release.set()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "pio-freshness-controller"]
        if len(alive) == 1:
            break
        time.sleep(0.02)
    assert len(alive) == 1, (
        "old controller generation kept looping after its stop")
    ctl.stop()
    assert not any(t.name == "pio-freshness-controller"
                   for t in threading.enumerate())


def test_off_mode_records_nothing_and_scrapes_nothing():
    clock = FakeClock(100.0)
    calls = {"n": 0}

    class _Exploding:
        registry = None

        def evaluate(self):
            calls["n"] += 1
            raise AssertionError("off mode must not consume signals")

    ctl = FreshnessController(engine=_Exploding(), clock=clock,
                              mode="off",
                              config=ControllerConfig(ring=8))
    assert ctl.evaluate_once() is None
    assert calls["n"] == 0
    assert ctl.decisions(limit=8) == []


# ---------------------------------------------------------------------------
# the audit trail: trace-linked actuation + the stitcher
# ---------------------------------------------------------------------------

def _captured_spans(caplog):
    return [json.loads(r.getMessage()) for r in caplog.records
            if r.name == "pio.trace"]


def test_actuation_spans_land_under_the_decision_trace(caplog):
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, _calls = make_controller(clock, eng)
    gauge.set(95.0)
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is True
    spans = [s for s in _captured_spans(caplog)
             if str(s.get("span", "")).startswith("controller.")]
    by_name = {s["span"]: s for s in spans}
    assert set(by_name) == {"controller.decision", "controller.retrain",
                            "controller.reload"}
    root = by_name["controller.decision"]
    assert root["traceId"] == d["traceId"]
    assert root["spanId"] == d["spanId"]
    assert root["decisionId"] == d["id"]
    for child in ("controller.retrain", "controller.reload"):
        assert by_name[child]["traceId"] == d["traceId"]
        assert by_name[child]["parentSpanId"] == root["spanId"]


def test_http_reload_hop_carries_the_decision_trace():
    """The reload actuator's POST forwards X-PIO-Trace-Id (the decision
    trace) + X-PIO-Parent-Span (the decision span) — what lets the
    front door and every worker behind it link their reload spans under
    the decision."""
    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Request,
        Response,
        Router,
    )

    seen = {}
    r = Router()

    @r.post("/reload")
    def reload_route(request: Request) -> Response:
        seen.update(request.headers)
        return Response(200, {"reloaded": 1})

    srv = HttpServer(r, "127.0.0.1", 0, name="fakedoor")
    port = srv.start_background()
    try:
        clock = FakeClock(100.0)
        eng, gauge = planted_engine(clock)
        ctl, _calls = make_controller(
            clock, eng,
            reload_fn=http_reload_fn(f"http://127.0.0.1:{port}/reload"))
        gauge.set(95.0)
        d = ctl.evaluate_once()
        assert d["outcome"]["reload"]["ok"] is True
        assert seen.get("x-pio-trace-id") == d["traceId"]
        assert seen.get("x-pio-parent-span") == d["spanId"]
    finally:
        srv.stop()


def test_frontdoor_rolling_reload_forwards_the_trace():
    """Through the REAL front door: a traced POST /reload fans the same
    trace ID to every worker's reload — the cross-process leg of the
    decision tree."""
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )
    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Request,
        Response,
        Router,
    )

    worker_headers = []
    servers = []
    ports = []
    for _i in range(2):
        r = Router()

        @r.post("/reload")
        def reload_route(request: Request) -> Response:
            worker_headers.append(dict(request.headers))
            return Response(200, {"ok": True})

        @r.get("/")
        def status(request: Request) -> Response:
            return Response(200, {"status": "alive"})

        srv = HttpServer(r, "127.0.0.1", 0, name="miniworker")
        servers.append(srv)
        ports.append(srv.start_background())
    fd = FrontDoor([("127.0.0.1", p) for p in ports],
                   FrontDoorConfig(probe_interval_s=0.2))
    fport = fd.start_background()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/reload", data=b"",
            method="POST",
            headers={"X-PIO-Trace-Id": "ctl-e2e-0001"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["reloaded"] == 2
        assert len(worker_headers) == 2
        for h in worker_headers:
            assert h.get("x-pio-trace-id") == "ctl-e2e-0001"
            assert h.get("x-pio-parent-span")   # the door's span
    finally:
        fd.stop()
        for srv in servers:
            srv.stop()


def test_trace_stitch_decisions_view(tmp_path, caplog, capsys):
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, _calls = make_controller(clock, eng)
    gauge.set(95.0)
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        d = ctl.evaluate_once()
    log = tmp_path / "spans.log"
    log.write_text("noise line\n" + "\n".join(
        r.getMessage() for r in caplog.records if r.name == "pio.trace")
        + "\n")
    assert trace_stitch.main([str(log), "--decisions"]) == 0
    out = capsys.readouterr().out
    assert f"decision #{d['id']}" in out
    assert "controller.retrain" in out
    assert "controller.reload" in out
    assert d["traceId"] in out


def test_trace_stitch_flags_orphan_actuations(tmp_path, capsys):
    """An actuation span whose trace has no decision root is exactly
    the unaudited-mutation class the lint rule + stitcher exist to
    catch: loud stderr, exit 1."""
    log = tmp_path / "orphan.log"
    log.write_text(json.dumps({
        "span": "controller.reload", "traceId": "ctl-orphan",
        "spanId": "ab12cd34", "ts": 1000.0, "durationMs": 5.0,
    }) + "\n")
    assert trace_stitch.main([str(log), "--decisions"]) == 1
    err = capsys.readouterr().err
    assert "ORPHAN ACTUATION" in err
    assert "ctl-orphan" in err


# ---------------------------------------------------------------------------
# admin server hosting: GET/POST /controller
# ---------------------------------------------------------------------------

@pytest.fixture
def admin_with_controller():
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.servers.admin import AdminServer

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    # a long interval: the admin-hosted loop evaluates once at start,
    # then the tests drive evaluate_once explicitly (no racing ticks)
    ctl, calls = make_controller(clock, eng, mode="observe",
                                 interval=60.0)
    ad = AdminServer(ip="127.0.0.1", port=0, controller=ctl)
    port = ad.start_background()
    try:
        yield {"port": port, "gauge": gauge, "ctl": ctl,
               "calls": calls}
    finally:
        ad.stop()
        ctl_mod.reset_controller()
        Storage.reset()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_controller_routes_on_admin(admin_with_controller):
    port = admin_with_controller["port"]
    gauge = admin_with_controller["gauge"]
    gauge.set(95.0)
    admin_with_controller["ctl"].evaluate_once()
    status, body = _get(port, "/controller?limit=10")
    assert status == 200
    assert body["mode"] == "observe"
    assert body["running"] is True          # the admin started the loop
    assert body["actuators"] == {"retrain": True, "reload": True,
                                 "capacityGuard": False}
    decisions = body["decisions"]
    assert decisions and decisions[0]["kind"] == "evaluation"
    assert decisions[0]["reason"] == "observe"   # dry-run recorded
    assert decisions[0]["traceId"].startswith("ctl-")
    # the LIVE kill switch flip
    status, body = _post(port, "/controller", {"mode": "act"})
    assert status == 200 and body["mode"] == "act"
    status, body = _post(port, "/controller", {"mode": "sideways"})
    assert status == 400
    status, body = _post(port, "/controller", "off")  # non-object JSON
    assert status == 400
    status, body = _get(port, "/controller")
    assert body["mode"] == "act"
    # the flip landed in the audit ring
    assert any(d.get("kind") == "mode_change" and d["to"] == "act"
               for d in body["decisions"])


def test_controller_metrics_exported(admin_with_controller):
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    gauge = admin_with_controller["gauge"]
    ctl = admin_with_controller["ctl"]
    gauge.set(95.0)
    before = ctl_mod._SKIPS.labels(reason="observe").value
    ctl.evaluate_once()
    assert ctl_mod._SKIPS.labels(reason="observe").value == before + 1
    assert ctl_mod._STATE.value == 1.0      # observe
    assert ctl_mod._PROJECTION.value == pytest.approx(5.0)
    text = obs_metrics.REGISTRY.expose()
    for name in ("pio_controller_evaluations_total",
                 "pio_controller_skips_total",
                 "pio_controller_state",
                 "pio_controller_budget_projection_seconds"):
        assert name in text


def test_decision_ring_is_bounded():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock)
    ctl, _calls = make_controller(clock, eng, mode="observe")
    gauge.set(1.0)
    for _ in range(200):
        ctl.evaluate_once()
    ds = ctl.decisions(limit=1000)
    assert len(ds) == 64
    # newest first
    assert ds[0]["id"] > ds[-1]["id"]


# ---------------------------------------------------------------------------
# lock discipline (regressions for the races pio-lint's
# unguarded-shared-state pass surfaced: _mode_override read outside the
# lock by the loop-side `mode` property, _cooldown_until read/written
# outside the lock around actuation)
# ---------------------------------------------------------------------------

class _AuditedController(FreshnessController):
    """Asserts the controller lock is held for every post-init write of
    the attributes the race fix moved under it."""

    _AUDITED = frozenset({"_mode_override", "_cooldown_until", "_streak"})

    def __setattr__(self, name, value):
        if name in self._AUDITED and getattr(self, "_audit_on", False):
            assert self._lock.locked(), (
                f"write of {name} without the controller lock")
        object.__setattr__(self, name, value)


def _audited_controller(clock, engine, cooldown=30.0):
    return _AuditedController(
        engine=engine,
        retrain_fn=lambda: "inst-1",
        reload_fn=lambda: {"reloaded": 1},
        config=ControllerConfig(interval_s=0.05, breach_evals=1,
                                cooldown_s=cooldown, horizon_s=10.0,
                                ring=64),
        clock=clock, mode="act")


def _join_or_fail(fn, timeout=10.0):
    """Run ``fn`` on a thread and fail loudly instead of hanging the
    suite if it deadlocks (the regression this guards against)."""
    import threading as _threading
    out = {}

    def run():
        out["value"] = fn()

    t = _threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "deadlocked: lock-discipline regression"
    return out["value"]


def test_mode_property_and_stats_are_deadlock_free():
    clock = FakeClock(100.0)
    eng, _gauge = planted_engine(clock)
    ctl = _audited_controller(clock, eng)
    ctl._audit_on = True
    ctl.set_mode("observe")
    # `mode` now takes the lock; stats() reads mode while HOLDING the
    # lock (inlined, not via the property) — both must complete
    assert _join_or_fail(lambda: ctl.mode) == "observe"
    st = _join_or_fail(ctl.stats)
    assert st["mode"] == "observe"
    # set_mode's prev-mode read is also inlined under the lock — the
    # audit record must still capture the transition correctly
    ctl.set_mode("act")
    ds = ctl.decisions(limit=1)
    assert ds[0]["kind"] == "mode_change"
    assert ds[0]["from"] == "observe" and ds[0]["to"] == "act"


def test_cooldown_and_streak_writes_hold_the_lock():
    clock = FakeClock(100.0)
    eng, gauge = planted_engine(clock, threshold=100.0)
    ctl = _audited_controller(clock, eng, cooldown=60.0)
    ctl._audit_on = True
    gauge.set(95.0)                    # headroom 5 < horizon 10: act
    d = ctl.evaluate_once()
    assert d["action"] == "retrain+reload"
    # the post-actuation _cooldown_until/_streak writes ran (under the
    # lock, or _AuditedController would have failed above)
    d2 = ctl.evaluate_once()
    assert d2["reason"] == "cooldown"
    assert d2["cooldownRemainingS"] > 0
