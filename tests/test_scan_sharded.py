"""Sharded parallel event-log scan (data/storage/cpplog.py).

The contract under test: the sharded scan is byte-identical to the
sequential scan — same rows in the same order, same values, same
first-seen id tables down to the blob bytes — for every shard count,
across deletes/dead entries, out-of-order event times, time windows, and
the traincache tail-fold path; and the scan no longer holds the client
lock, so event writes proceed while a training scan is in flight (the
lock-narrowing invariant pio-lint's ``lock-native-scan`` rule encodes).
"""

import threading
import time

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    StorageClientConfig,
    cpplog,
    traincache,
)
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.utils.times import from_millis

pytestmark = pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native", fromlist=["load"]).load()
    is None,
    reason="native library unavailable",
)

SHARD_COUNTS = (1, 2, 7)


@pytest.fixture
def events(tmp_path, monkeypatch):
    monkeypatch.setattr(traincache, "MIN_NNZ", 4)
    client = cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(tmp_path)}))
    ev = cpplog.CppLogEvents(client, None, prefix="t_")
    yield ev
    client.close()


def _cache_path(events, app_id=1):
    return traincache.path_for(
        events.client._file(events.ns, app_id, None))


def _scan(events, shards, monkeypatch, **kw):
    monkeypatch.setenv("PIO_SCAN_SHARDS", str(shards))
    kw.setdefault("entity_type", "user")
    kw.setdefault("target_entity_type", "item")
    kw.setdefault("event_names", ("rate",))
    kw.setdefault("value_prop", "rating")
    return events.scan_interactions(app_id=1, **kw)


def _assert_byte_identical(a, b):
    assert np.array_equal(a.user_idx, b.user_idx)
    assert np.array_equal(a.item_idx, b.item_idx)
    assert np.array_equal(a.values, b.values)
    for ta, tb in ((a.user_ids, b.user_ids), (a.item_ids, b.item_ids)):
        assert bytes(ta.blob) == bytes(tb.blob)
        assert np.array_equal(ta.offsets, tb.offsets)


def _build_random_log(events, rng, n=400, unordered=True):
    """Bulk import (+unordered times) + per-event inserts with explicit-id
    upserts + deletes — every path that shapes entry numbering."""
    users = rng.integers(0, 23, n).astype(np.int32)
    items = rng.integers(0, 11, n).astype(np.int32)
    inter = Interactions(
        user_idx=users, item_idx=items,
        values=rng.random(n).astype(np.float32),
        user_ids=[f"u{k}" for k in range(23)],
        item_ids=[f"i{k}" for k in range(11)],
    )
    times = (rng.integers(0, 50_000, n) if unordered
             else 1000 + np.arange(n)).astype(np.int64)
    assert events.import_interactions(inter, 1, times=times) == n
    ids = []
    for k in range(30):
        ids.append(events.insert(Event(
            event="rate", entity_type="user", entity_id=f"x{k % 5}",
            target_entity_type="item", target_entity_id=f"i{k % 4}",
            properties=DataMap({"rating": float(k)}),
            event_time=from_millis(int(rng.integers(0, 50_000))),
            event_id=f"{k % 9:032d}",  # small pool → upsert tombstones
        ), 1))
    for eid in ids[::4]:
        events.delete(eid, 1)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_scan_byte_identical(events, monkeypatch, seed):
    rng = np.random.default_rng(seed)
    _build_random_log(events, rng, unordered=bool(seed % 2 == 0))
    ref = _scan(events, 1, monkeypatch, use_cache=False, seed_cache=False)
    assert len(ref)
    for shards in SHARD_COUNTS[1:]:
        stats = {}
        got = _scan(events, shards, monkeypatch, use_cache=False,
                    seed_cache=False, stats=stats)
        assert stats["scan_shards"] == shards
        assert len(stats["scan_shard_walls_s"]) == shards
        _assert_byte_identical(ref, got)


def test_sharded_scan_time_window_identical(events, monkeypatch):
    rng = np.random.default_rng(3)
    _build_random_log(events, rng)
    kw = dict(start_time=from_millis(10_000), until_time=from_millis(40_000),
              use_cache=False, seed_cache=False)
    ref = _scan(events, 1, monkeypatch, **kw)
    assert 0 < len(ref)
    for shards in SHARD_COUNTS[1:]:
        _assert_byte_identical(ref, _scan(events, shards, monkeypatch, **kw))


@pytest.mark.parametrize("shards", SHARD_COUNTS)
def test_warm_traincache_tail_fold_identical(events, monkeypatch, shards):
    """Cache written at import, tail appended via the REST path: the
    cache-served scan (tail folded through the sharded scanner) must be
    byte-identical to a cold full scan at every shard count."""
    n = 12
    inter = Interactions(
        user_idx=(np.arange(n, dtype=np.int32) % 5),
        item_idx=(np.arange(n, dtype=np.int32) % 3),
        values=np.arange(1, n + 1, dtype=np.float32),
        user_ids=[f"u{k}" for k in range(5)],
        item_ids=[f"i{k}" for k in range(3)],
    )
    assert events.import_interactions(
        inter, 1, times=1000 + np.arange(n, dtype=np.int64)) == n
    assert _cache_path(events).exists()
    for k in range(3):
        events.insert(Event(
            event="rate", entity_type="user", entity_id=f"tail{k}",
            target_entity_type="item", target_entity_id="i0",
            properties=DataMap({"rating": 9.0 + k}),
            event_time=from_millis(5000 + k)), 1)
    warm = _scan(events, shards, monkeypatch)  # cache + tail fold
    assert len(warm) == n + 3
    _cache_path(events).unlink()
    cold = _scan(events, shards, monkeypatch)  # full scan, reseeds
    _assert_byte_identical(warm, cold)


def test_insert_proceeds_during_inflight_scan(events, monkeypatch):
    """The lock-narrowing invariant: while a scan is mid-flight (the
    native call deliberately stalled), insert_batch must complete —
    before the narrowing it would block on client.lock for the whole
    scan. The mid-scan insert lands AFTER the scan's snapshot bound, so
    the scan result must not contain it."""
    _build_random_log(events, np.random.default_rng(5), n=50,
                      unordered=False)
    n_before = len(_scan(events, 1, monkeypatch, use_cache=False,
                         seed_cache=False))
    orig = cpplog.CppLogEvents._scan_native
    started, release = threading.Event(), threading.Event()

    def slow_scan(self, *a, **kw):
        started.set()
        assert release.wait(timeout=30)
        return orig(self, *a, **kw)

    monkeypatch.setattr(cpplog.CppLogEvents, "_scan_native", slow_scan)
    out = {}
    t = threading.Thread(target=lambda: out.setdefault("inter", _scan(
        events, 1, monkeypatch, use_cache=False, seed_cache=False)))
    t.start()
    try:
        assert started.wait(10)
        t0 = time.perf_counter()
        ids = events.insert_batch([Event(
            event="rate", entity_type="user", entity_id="concurrent",
            target_entity_type="item", target_entity_id="i0",
            properties=DataMap({"rating": 1.0}),
            event_time=from_millis(99_999))], 1)
        insert_wall = time.perf_counter() - t0
    finally:
        release.set()
    t.join(30)
    assert not t.is_alive()
    assert len(ids) == 1
    # the scan is stalled for up to 30 s; a blocked writer would sit on
    # client.lock that whole time — seconds of margin, not a tight race
    assert insert_wall < 5.0, insert_wall
    # snapshot semantics: the concurrent insert is past the end bound
    assert len(out["inter"]) == n_before


def test_delete_during_scan_skips_stale_cache_seed(events, monkeypatch):
    """Revalidation: a delete landing during the lock-free scan must
    prevent the scan result from seeding the projection cache (it still
    carries the now-dead row)."""
    _build_random_log(events, np.random.default_rng(6), n=40,
                      unordered=False)
    cpath = _cache_path(events)
    cpath.unlink(missing_ok=True)
    victim = next(iter(events.find(app_id=1))).event_id
    orig = cpplog.CppLogEvents._scan_native
    started, release = threading.Event(), threading.Event()

    def slow_scan(self, *a, **kw):
        started.set()
        assert release.wait(timeout=30)
        return orig(self, *a, **kw)

    monkeypatch.setattr(cpplog.CppLogEvents, "_scan_native", slow_scan)
    t = threading.Thread(
        target=lambda: _scan(events, 1, monkeypatch, use_cache=False))
    t.start()
    try:
        assert started.wait(10)
        assert events.delete(victim, 1)
    finally:
        release.set()
    t.join(30)
    assert not t.is_alive()
    assert not cpath.exists(), \
        "stale scan result must not seed the projection cache"
    # and the next scan (fresh snapshot) reflects the delete and reseeds
    monkeypatch.setattr(cpplog.CppLogEvents, "_scan_native", orig)
    after = _scan(events, 2, monkeypatch)
    assert len(after) == 40 + 30 - 8 - 1 - (30 - 9)  # see _build_random_log


def test_streaming_prep_matches_serial_prep(events, monkeypatch):
    """The pipelined scan→prep path (shard_sink → StreamingPrep with
    degree histograms accumulated during the scan) must produce buckets
    byte-identical to the serial build_both_sides."""
    from incubator_predictionio_tpu.ops.sparse import (
        StreamingPrep,
        build_both_sides,
    )

    rng = np.random.default_rng(7)
    _build_random_log(events, rng, n=600, unordered=False)
    prep = StreamingPrep()
    stats = {}
    inter = _scan(events, 3, monkeypatch, use_cache=False, seed_cache=False,
                  stats=stats, shard_sink=prep.add_shard)
    assert prep.shards == 3
    piped = prep.finish(inter, max_width=8,
                        reordered=bool(stats["scan_reordered"]))
    serial = build_both_sides(
        inter.user_idx, inter.item_idx, inter.values,
        len(inter.user_ids), len(inter.item_ids), max_width=8)

    def flatten(sides):
        out = []
        for light, heavy in sides:
            for b in light:
                out.append((b.row_ids, b.cols, b.vals, b.mask))
            if heavy is not None:
                out.append((heavy.seg_ids, heavy.row_ids, heavy.cols,
                            heavy.vals, heavy.mask))
        return out

    a, b = flatten(piped), flatten(serial)
    assert len(a) == len(b)
    for xs, ys in zip(a, b):
        for x, y in zip(xs, ys):
            assert np.array_equal(x, y)


def test_degree_plan_mismatch_falls_back_to_exact(events):
    """A wrong degree histogram must never corrupt buckets: the native
    fill rejects it (bound check / segment total) and the builder redoes
    the exact plan."""
    from incubator_predictionio_tpu.native.csr import build_buckets_native

    rows = np.array([0, 0, 0, 0, 1], np.int32)
    cols = np.arange(5, dtype=np.int32)
    vals = np.ones(5, np.float32)
    exact = build_buckets_native(rows, cols, vals, 2, 2, 8)
    for bad in (np.array([1, 4], np.int64),    # wrong multiset, right sum
                np.array([2, 2, 1], np.int64),  # wrong length
                np.array([5, 0], np.int64)):    # over-allocates bucket
        got = build_buckets_native(rows, cols, vals, 2, 2, 8, degrees=bad)
        assert len(got) == len(exact)
        for (w1, *a1), (w2, *a2) in zip(got, exact):
            assert w1 == w2
            for x, y in zip(a1, a2):
                assert np.array_equal(x, y)


def test_concurrent_cache_stages_use_distinct_tmp_files(tmp_path):
    """Cache serialization runs OUTSIDE the storage lock, so two
    concurrent seeds of the same cache must stage to distinct temp
    files — a shared name would truncate/interleave the bytes one of
    them later renames into the live cache."""
    spec = traincache.Spec("user", "item", "rate", "rating")

    def make(val):
        return traincache.TrainCache(
            spec=spec,
            uidx=np.zeros(4, np.int32), iidx=np.zeros(4, np.int32),
            vals=np.full(4, val, np.float32),
            times=np.arange(4, dtype=np.int64),
            user_tab=traincache._build_table([b"u0"]),
            item_tab=traincache._build_table([b"i0"]),
            raw_count=4, dead_count=0)

    cpath = tmp_path / "log.traincache"
    a = traincache.stage(cpath, make(1.0))
    b = traincache.stage(cpath, make(2.0))  # before a commits
    assert a._tmp != b._tmp
    a.commit()
    b.commit()  # last writer wins, never FileNotFoundError
    loaded = traincache.load(cpath)
    assert loaded is not None and loaded.vals[0] == 2.0
    assert not list(tmp_path.glob("*.tmp*"))  # no stray temp files


def test_scan_stats_report_lock_narrowing(events, monkeypatch):
    """The stats channel the bench records: shard walls and the native
    lock-held wall must be present and the lock-held share must be far
    below the scan wall at any real size (here just sanity > 0 keys)."""
    _build_random_log(events, np.random.default_rng(8), n=200,
                      unordered=False)
    stats = {}
    _scan(events, 2, monkeypatch, use_cache=False, seed_cache=False,
          stats=stats)
    assert stats["scan_shards"] == 2
    assert len(stats["scan_shard_walls_s"]) == 2
    assert stats["scan_lock_held_s"] >= 0.0
    assert stats["scan_rows"] == len(_scan(events, 1, monkeypatch,
                                           use_cache=False,
                                           seed_cache=False))
