"""$set/$unset/$delete replay (parity: data/src/test/.../storage/LEventAggregatorSpec.scala)."""

from datetime import timedelta

from incubator_predictionio_tpu.data.aggregator import (
    aggregate_properties,
    aggregate_properties_single,
)
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.utils.times import parse_iso8601

T0 = parse_iso8601("2020-01-01T00:00:00Z")


def sev(name, entity_id, props, minutes):
    return Event(
        event=name,
        entity_type="user",
        entity_id=entity_id,
        properties=DataMap(props),
        event_time=T0 + timedelta(minutes=minutes),
    )


def test_set_merge_right_biased_by_time():
    # Deliberately out of order; aggregation must sort by event_time.
    events = [
        sev("$set", "u1", {"a": 1, "b": "old"}, 0),
        sev("$set", "u1", {"b": "new", "c": True}, 10),
    ]
    pm = aggregate_properties_single(reversed(events))
    assert pm is not None
    assert pm.fields == {"a": 1, "b": "new", "c": True}
    assert pm.first_updated == T0
    assert pm.last_updated == T0 + timedelta(minutes=10)


def test_unset_removes_keys():
    events = [
        sev("$set", "u1", {"a": 1, "b": 2}, 0),
        sev("$unset", "u1", {"b": None}, 5),
    ]
    pm = aggregate_properties_single(events)
    assert pm.fields == {"a": 1}


def test_delete_resets_entity():
    events = [
        sev("$set", "u1", {"a": 1}, 0),
        sev("$delete", "u1", {}, 5),
    ]
    assert aggregate_properties_single(events) is None
    # set after delete resurrects with only the new props
    events.append(sev("$set", "u1", {"z": 9}, 6))
    pm = aggregate_properties_single(events)
    assert pm.fields == {"z": 9}
    # first/last track all special events, including the delete
    assert pm.first_updated == T0
    assert pm.last_updated == T0 + timedelta(minutes=6)


def test_non_special_events_ignored():
    events = [
        sev("$set", "u1", {"a": 1}, 0),
        sev("rate", "u1", {"rating": 5}, 1),
    ]
    pm = aggregate_properties_single(events)
    assert pm.fields == {"a": 1}
    assert pm.last_updated == T0


def test_grouping_and_deleted_entities_filtered():
    events = [
        sev("$set", "u1", {"a": 1}, 0),
        sev("$set", "u2", {"a": 2}, 0),
        sev("$delete", "u2", {}, 1),
    ]
    out = aggregate_properties(events)
    assert set(out) == {"u1"}
    assert out["u1"].fields == {"a": 1}


def test_unset_before_any_set():
    events = [sev("$unset", "u1", {"a": None}, 0)]
    assert aggregate_properties_single(events) is None
