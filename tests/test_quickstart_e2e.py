"""End-to-end quickstart: REST ingest → build → train → deploy → query.

Parity with the reference's Python integration tier
(tests/pio_tests/tests.py QuickStartTest: app new → import → build → train
→ deploy → query the recommendation engine), run fully in-process against
the real framework stack — CLI verbs, EventServer REST ingest, the training
workflow, and a live PredictionServer.
"""

import json
import random
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.cli.commands import engine_id_for_variant_path
from incubator_predictionio_tpu.cli.main import main
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)
from incubator_predictionio_tpu.servers.prediction_server import (
    PredictionServer,
    ServerConfig,
)

VARIANT = {
    "id": "default",
    "engineFactory":
        "incubator_predictionio_tpu.models.recommendation:"
        "RecommendationEngine",
    "datasource": {"params": {"appName": "QsApp"}},
    "algorithms": [{"name": "als", "params": {
        "rank": 8, "numIterations": 5, "lambda": 0.05, "seed": 3,
    }}],
}


def post(url, body):
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read() or b"null")


@pytest.fixture
def storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_quickstart_full_pipeline(storage, tmp_path, monkeypatch, capsys):
    # 1. app new (CLI) — prints the generated access key
    assert main(["app", "new", "QsApp"]) == 0
    out = capsys.readouterr().out
    key = next(line.split(":")[1].strip() for line in out.splitlines()
               if "Access Key" in line)

    # 2. REST batch ingest through a live event server (50-event cap parity)
    es = EventServer(EventServerConfig(ip="127.0.0.1", port=0))
    es_port = es.start_background()
    try:
        random.seed(0)
        events = []
        for u in range(25):
            for i in random.sample(range(40), 10):
                events.append({
                    "event": "rate", "entityType": "user",
                    "entityId": f"u{u}", "targetEntityType": "item",
                    "targetEntityId": f"i{i}",
                    "properties": {"rating": float(random.randint(1, 5))},
                })
        base = f"http://127.0.0.1:{es_port}"
        for s in range(0, len(events), 50):
            status, body = post(
                f"{base}/batch/events.json?accessKey={key}",
                events[s:s + 50])
            assert status == 200
        # oversized batch is rejected (EventServer.scala:71 cap)
        with pytest.raises(urllib.error.HTTPError) as err:
            post(f"{base}/batch/events.json?accessKey={key}",
                 [events[0]] * 51)
        assert err.value.code == 400
    finally:
        es.stop()

    # 3. build + train from an engine.json on disk (CLI)
    (tmp_path / "engine.json").write_text(json.dumps(VARIANT))
    monkeypatch.chdir(tmp_path)
    assert main(["build"]) == 0
    assert main(["train"]) == 0
    assert "Engine instance ID:" in capsys.readouterr().out

    # 4. deploy the latest completed instance and query it
    from incubator_predictionio_tpu.cli.commands import engine_from_variant
    engine, _ = engine_from_variant(VARIANT)
    ps = PredictionServer(engine, ServerConfig(
        ip="127.0.0.1", port=0,
        engine_id=engine_id_for_variant_path(
            str(tmp_path / "engine.json"), VARIANT),
        engine_variant="default",
    ))
    ps_port = ps.start_background()
    try:
        status, body = post(
            f"http://127.0.0.1:{ps_port}/queries.json",
            {"user": "u1", "num": 4})
        assert status == 200
        scores = body["itemScores"]
        assert len(scores) == 4
        assert all(s["item"].startswith("i") for s in scores)
        # ranked descending
        vals = [s["score"] for s in scores]
        assert vals == sorted(vals, reverse=True)
        # unknown user → empty result, not an error (template parity)
        status, body = post(
            f"http://127.0.0.1:{ps_port}/queries.json",
            {"user": "ghost", "num": 4})
        assert status == 200
        assert body["itemScores"] == []
    finally:
        ps.stop()

    # 5. export the ingested events back out (CLI)
    out_file = tmp_path / "export.jsonl"
    assert main(["export", "--appid-or-name", "QsApp",
                 "--output", str(out_file)]) == 0
    lines = out_file.read_text().splitlines()
    assert len(lines) == 250
