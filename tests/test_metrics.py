"""Metric family + MetricEvaluator (parity: MetricTest.scala, MetricEvaluatorTest.scala)."""

import math

import pytest

from fake_engine import AP, make_engine, params
from incubator_predictionio_tpu.core import (
    AverageMetric,
    EngineParams,
    MetricEvaluator,
    OptionAverageMetric,
    OptionStdevMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.parallel.context import RuntimeContext


class ScoreMetric(AverageMetric):
    def calculate_qpa(self, q, p, a) -> float:
        return float(q)


class OptScoreMetric(OptionAverageMetric):
    def calculate_qpa(self, q, p, a):
        return float(q) if q is not None else None


class StdevQ(StdevMetric):
    def calculate_qpa(self, q, p, a) -> float:
        return float(q)


class OptStdevQ(OptionStdevMetric):
    def calculate_qpa(self, q, p, a):
        return float(q) if q is not None else None


class SumQ(SumMetric):
    def calculate_qpa(self, q, p, a) -> float:
        return float(q)


CTX = RuntimeContext()


def eds(*qs):
    """One eval set whose 'queries' are the scores themselves."""
    return [(None, [(q, None, None) for q in qs])]


def test_average_metric():
    assert ScoreMetric().calculate(CTX, eds(1, 2, 3, 6)) == 3.0
    # across multiple eval sets
    two_sets = eds(1, 2) + eds(3, 6)
    assert ScoreMetric().calculate(CTX, two_sets) == 3.0


def test_option_average_skips_none():
    assert OptScoreMetric().calculate(CTX, eds(1, None, 3, None, 5)) == 3.0
    assert math.isnan(OptScoreMetric().calculate(CTX, eds(None, None)))


def test_stdev_metrics():
    assert StdevQ().calculate(CTX, eds(2, 2, 2)) == 0.0
    assert StdevQ().calculate(CTX, eds(1, 3)) == 1.0
    assert OptStdevQ().calculate(CTX, eds(1, None, 3)) == 1.0


def test_sum_and_zero():
    assert SumQ().calculate(CTX, eds(1, 2, 3)) == 6.0
    assert ZeroMetric().calculate(CTX, eds(1, 2)) == 0.0


def test_compare_ordering():
    m = ScoreMetric()
    assert m.compare(2.0, 1.0) > 0
    assert m.compare(1.0, 2.0) < 0
    assert m.compare(1.0, 1.0) == 0


def test_metric_evaluator_picks_best(tmp_path):
    from fake_engine import QxMetric

    engine = make_engine()
    # candidates with ap_id 1, 5, 3 — QxMetric scores = ap_id, so best is 5
    eps = [params(algos=[("algo0", AP(i))]) for i in (1, 5, 3)]
    data = engine.batch_eval(CTX, eps)
    best_json = tmp_path / "best.json"
    evaluator = MetricEvaluator(QxMetric(), output_path=str(best_json))
    result = evaluator.evaluate(CTX, None, data)
    assert result.best_idx == 1
    assert result.best_score.score == 5.0
    assert result.best_engine_params.algorithm_params_list[0][1].id == 5
    assert best_json.exists()
    assert "5" in result.to_one_liner()
    assert "<table" in result.to_html()
    assert result.to_jsonable()["bestIdx"] == 1


def test_evaluation_dsl_wiring():
    from fake_engine import QxMetric

    ev = Evaluation()
    engine = make_engine()
    ev.engine_metric = (engine, QxMetric())
    eng, evaluator = ev.engine_evaluator
    assert eng is engine
    assert isinstance(evaluator, MetricEvaluator)
    with pytest.raises(RuntimeError):
        ev.engine = engine  # assign-once


def test_evaluation_requires_assignment():
    ev = Evaluation()
    with pytest.raises(RuntimeError):
        _ = ev.engine
    with pytest.raises(RuntimeError):
        _ = ev.evaluator
