"""Network storage backend: the multi-box deployment topology.

The reference's deployment story is N servers sharing state through
external services (PostgreSQL via jdbc/StorageClient.scala:35-60, HBase,
Elasticsearch). Here the same topology runs through the framework's own
StorageServer + ``remote`` backend: these tests prove (a) the registry
resolves ``PIO_STORAGE_SOURCES_<N>_TYPE=remote``, (b) two independent
clients see one store, (c) shared-key auth gates the RPC surface, and
(d) a real ``pio storageserver`` child process serves a client in this
process — the actual two-process topology, not a loopback simulation.
"""

import os
import subprocess
import sys
import threading
import time
from datetime import timedelta

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    App,
    Storage,
    StorageClientConfig,
    StorageError,
)
from incubator_predictionio_tpu.data.storage import memory as memory_backend
from incubator_predictionio_tpu.data.storage import remote as remote_backend
from incubator_predictionio_tpu.data.storage.server import StorageServer
from incubator_predictionio_tpu.utils.times import parse_iso8601

T0 = parse_iso8601("2022-01-01T00:00:00Z")


@pytest.fixture
def shared_server():
    config = StorageClientConfig(test=True, properties={})
    client = memory_backend.StorageClient(config)
    srv = StorageServer(memory_backend, client, config,
                        host="127.0.0.1", port=0)
    port = srv.start_background()
    yield srv, port
    srv.stop()


def _client(port, **props):
    config = StorageClientConfig(
        test=True, properties={"URL": f"http://127.0.0.1:{port}", **props})
    return remote_backend.StorageClient(config)


def ev(name, eid, minutes=0, target=None, props=None):
    return Event(
        event=name, entity_type="user", entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target, properties=DataMap(props or {}),
        event_time=T0 + timedelta(minutes=minutes),
    )


def test_two_clients_share_one_store(shared_server):
    """Box A (eventserver) writes, box B (trainer) reads — one store."""
    _srv, port = shared_server
    box_a = _client(port)
    box_b = _client(port)
    try:
        events_a = remote_backend.RemoteEvents(
            box_a, box_a.config, prefix="pio_event_")
        events_b = remote_backend.RemoteEvents(
            box_b, box_b.config, prefix="pio_event_")
        events_a.init(1)
        events_a.insert(ev("rate", "u1", 0, target="i1",
                           props={"rating": 4.0}), 1)
        events_a.insert(ev("rate", "u2", 1, target="i1",
                           props={"rating": 3.0}), 1)
        got = list(events_b.find(app_id=1))
        assert {e.entity_id for e in got} == {"u1", "u2"}
        # columnar scan crosses the wire as array buffers
        inter = events_b.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        assert len(inter) == 2
        assert list(inter.user_ids) == ["u1", "u2"]
        assert inter.values.dtype == np.float32
    finally:
        box_a.close()
        box_b.close()


def test_metadata_and_models_over_the_wire(shared_server):
    _srv, port = shared_server
    client = _client(port)
    try:
        apps = remote_backend.RemoteApps(client, client.config,
                                         prefix="pio_meta_")
        app_id = apps.insert(App(id=0, name="remoteapp"))
        assert apps.get_by_name("remoteapp").id == app_id
        models = remote_backend.RemoteModels(client, client.config,
                                             prefix="pio_model_")
        from incubator_predictionio_tpu.data.storage import Model
        models.insert(Model(id="m1", models=b"\x00\x01binary"))
        assert models.get("m1").models == b"\x00\x01binary"
    finally:
        client.close()


def test_registry_resolves_remote_type(shared_server):
    _srv, port = shared_server
    Storage.configure({
        "PIO_STORAGE_SOURCES_NET_TYPE": "remote",
        "PIO_STORAGE_SOURCES_NET_URL": f"http://127.0.0.1:{port}",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET",
    })
    try:
        assert Storage.verify_all_data_objects()
        apps = Storage.get_meta_data_apps()
        app_id = apps.insert(App(id=0, name="viaregistry"))
        assert Storage.get_meta_data_apps().get(app_id).name == "viaregistry"
    finally:
        Storage.reset()


def test_auth_key_required():
    config = StorageClientConfig(test=True, properties={})
    client = memory_backend.StorageClient(config)
    srv = StorageServer(memory_backend, client, config,
                        host="127.0.0.1", port=0, auth_key="s3cret")
    port = srv.start_background()
    try:
        bad = _client(port)
        apps = remote_backend.RemoteApps(bad, bad.config, prefix="m_")
        with pytest.raises(StorageError):
            apps.get_all()
        bad.close()
        good = _client(port, AUTHKEY="s3cret")
        apps = remote_backend.RemoteApps(good, good.config, prefix="m_")
        assert apps.get_all() == []
        good.close()
    finally:
        srv.stop()


def test_find_streams_in_chunks(shared_server, monkeypatch):
    """A find larger than one chunk streams through the server cursor
    protocol instead of materializing one response (server._find_rpc)."""
    from incubator_predictionio_tpu.data.storage import server as srv_mod

    monkeypatch.setattr(srv_mod, "FIND_CHUNK", 4)
    _srv, port = shared_server
    client = _client(port)
    try:
        events = remote_backend.RemoteEvents(client, client.config,
                                             prefix="pio_event_")
        events.init(1)
        for i in range(11):
            events.insert(ev("view", f"u{i}", minutes=i), 1)
        got = list(events.find(app_id=1))
        assert [e.entity_id for e in got] == [f"u{i}" for i in range(11)]
        # abandoning an iteration mid-way frees the server-side cursor
        it = events.find(app_id=1)
        next(it)
        it.close()
        assert _srv._cursors == {}
    finally:
        client.close()


def test_typed_errors_cross_the_wire(shared_server):
    _srv, port = shared_server
    client = _client(port)
    try:
        events = remote_backend.RemoteEvents(client, client.config,
                                             prefix="pio_event_")
        events.init(1)
        # non-exported method name → StorageError, not a crash
        with pytest.raises(StorageError):
            client.rpc("Events", "pio_event_", "unknown_method", (), {})
    finally:
        client.close()


def test_real_two_process_topology(tmp_path):
    """Spawn `pio storageserver` as a CHILD PROCESS (sqlite-backed) and run
    a client from this process — the actual box-A/box-B deployment.
    ``--port 0``: the CHILD binds an ephemeral port and announces the
    kernel's choice — no pre-picked "free" port to race other
    processes for (utils/http.py HttpServer ephemeral-bind contract)."""
    env = dict(
        os.environ,
        PIO_STORAGE_SOURCES_DISK_TYPE="sqlite",
        PIO_STORAGE_SOURCES_DISK_PATH=str(tmp_path / "shared.db"),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_predictionio_tpu.cli.main",
         "storageserver", "--ip", "127.0.0.1", "--port", "0",
         "--source", "DISK"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        port = _parse_announced_port(proc)
        _wait_alive(port, proc)
        client = _client(port)
        events = remote_backend.RemoteEvents(client, client.config,
                                             prefix="pio_event_")
        events.init(1)
        eid = events.insert(ev("buy", "u9", target="i3"), 1)
        got = events.get(eid, 1)
        assert got is not None and got.entity_id == "u9"
        client.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _parse_announced_port(proc, timeout: float = 60.0) -> int:
    """Read the storageserver CLI's post-bind announcement line →
    the kernel-assigned port (the ephemeral-bind handshake)."""
    holder: list = []

    def read() -> None:
        # stderr is merged into stdout: skip any warning/log chatter
        # until the announcement line (or EOF) shows up
        while True:
            line = proc.stdout.readline().decode(errors="replace")
            if not line:
                return
            if "http://" in line:
                holder.append(int(line.rsplit(":", 1)[1]))
                return

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout=timeout)
    if not holder:
        proc.kill()
        raise AssertionError("storageserver never announced its port")
    return holder[0]


def _wait_alive(port: int, proc, timeout: float = 30.0) -> None:
    import urllib.request

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read().decode(errors="replace")
            raise AssertionError(f"storageserver died:\n{out}")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=2) as resp:
                if resp.status == 200:
                    return
        except OSError:
            time.sleep(0.2)
    raise AssertionError("storageserver did not come up")


def test_insert_interactions_over_the_wire(tmp_path):
    """Columnar id-returning insert crosses the RPC: over a cpplog-backed
    box it returns the stored ids (the EventServer batch fast path on a
    storage-box topology); over a memory-backed box it answers a TYPED
    UnsupportedMethodError exactly once and the proxy caches the answer
    (no per-batch round trips afterward)."""
    from incubator_predictionio_tpu.data.storage import (
        UnsupportedMethodError,
        base,
    )
    from incubator_predictionio_tpu.data.storage import cpplog as cpplog_backend

    inter = base.Interactions(
        user_idx=np.arange(6, dtype=np.int32),
        item_idx=np.arange(6, dtype=np.int32),
        values=np.ones(6, np.float32),
        user_ids=[f"wu{k}" for k in range(6)],
        item_ids=[f"wi{k}" for k in range(6)])

    # cpplog-backed box: ids come back and resolve through the same wire
    cfg = StorageClientConfig(test=True, properties={"PATH": str(tmp_path)})
    back = cpplog_backend.StorageClient(cfg)
    srv = StorageServer(cpplog_backend, back, cfg, host="127.0.0.1", port=0)
    port = srv.start_background()
    try:
        rcfg = StorageClientConfig(
            test=True, properties={"URL": f"http://127.0.0.1:{port}"})
        rclient = remote_backend.StorageClient(rcfg)
        ev = remote_backend.RemoteEvents(rclient, rcfg)
        ids = ev.insert_interactions(inter, app_id=1)
        assert len(ids) == 6 and all(len(i) == 32 for i in ids)
        got = ev.get(ids[0], app_id=1)
        assert got is not None and got.entity_id == "wu0"
        rclient.close()
    finally:
        srv.stop()

    # memory-backed box: typed unsupported, cached after the first call
    cfg2 = StorageClientConfig(test=True, properties={})
    back2 = memory_backend.StorageClient(cfg2)
    srv2 = StorageServer(memory_backend, back2, cfg2,
                         host="127.0.0.1", port=0)
    port2 = srv2.start_background()
    try:
        rcfg2 = StorageClientConfig(
            test=True, properties={"URL": f"http://127.0.0.1:{port2}"})
        rclient2 = remote_backend.StorageClient(rcfg2)
        ev2 = remote_backend.RemoteEvents(rclient2, rcfg2)
        with pytest.raises(UnsupportedMethodError):
            ev2.insert_interactions(inter, app_id=1)
        assert ev2._columnar_insert_unsupported
        srv2.stop()  # server gone: a cached answer must not need the wire
        with pytest.raises(UnsupportedMethodError):
            ev2.insert_interactions(inter, app_id=1)
        rclient2.close()
    finally:
        srv2.stop()
