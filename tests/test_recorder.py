"""Flight recorder + exemplars + incident capture (obs/recorder.py).

The PR's contract, pinned end to end:

- ring retention/eviction math and delta-encode/decode identity on a
  FakeClock (the history is exact, not approximate);
- exemplar reservoir determinism under seeded load, the emit→parse→
  re-emit pass-through (byte-stable through federation, unknown
  annotations included), and federation's kind-mismatch / exemplar-free
  -worker degradation with exemplars present;
- a planted SLO breach autonomously produces EXACTLY one bundle
  (cooldown pinned), and a two-REAL-worker fleet breach produces one
  bundle holding both instances' pre-breach windows, ≥1 exemplar trace
  ID the trace_stitch machinery reconstructs cross-process, and the
  in-window controller decisions (the acceptance bar);
- /recorder + /incidents + POST /incident e2e over a real HttpServer;
- recorder-off zero overhead (PIO_RECORDER=0 → no sampler thread) and
  the p99-unchanged-with-recorder-on bound on the observe hot path.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.obs import expofmt, federate
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import recorder as obs_recorder
from incubator_predictionio_tpu.obs import slo as obs_slo
from incubator_predictionio_tpu.obs import trace as obs_trace
from incubator_predictionio_tpu.obs.metrics import Registry
from incubator_predictionio_tpu.obs.recorder import (
    FlightRecorder,
    IncidentCapture,
)
from incubator_predictionio_tpu.utils.times import FakeClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(TESTS_DIR, "fleet_worker.py")
REPORT = os.path.join(REPO, "scripts", "incident_report.py")


@pytest.fixture
def clean_singletons():
    """Recorder/capture/SLO singletons re-read env on next use."""
    obs_recorder.reset_recorder()
    obs_slo.reset_engine()
    yield
    obs_recorder.reset_recorder()
    obs_slo.reset_engine()


def _recorder(reg, clock, wall, hz=1.0, window_s=10.0, keyframe_every=4):
    return FlightRecorder(registry=reg, hz=hz, window_s=window_s,
                          clock=clock, wall=wall,
                          keyframe_every=keyframe_every)


# ---------------------------------------------------------------------------
# ring math
# ---------------------------------------------------------------------------

def test_ring_retention_and_eviction_math():
    reg = Registry()
    g = reg.gauge("t_gauge", "g")
    clock = FakeClock(100.0)
    rec = _recorder(reg, clock, clock, hz=1.0, window_s=10.0,
                    keyframe_every=4)
    # slots = window*hz + keyframe_every + 1
    assert rec.slots == 15
    for i in range(40):
        g.set(float(i))
        rec.sample_now()
        clock.advance(1.0)
    # only `slots` entries retained; the full window is reconstructable
    assert rec.index()["samples"] == rec.slots
    win = rec.window(series=["t_gauge"], window_s=10.0)
    pts = win["series"]["t_gauge"]["children"][0]["points"]
    # 11 points cover a 10 s window at 1 Hz (inclusive bounds)
    assert len(pts) == 11
    # values are the exact gauge settings of the last 11 ticks
    assert [p[1] for p in pts] == [float(i) for i in range(29, 40)]
    # a narrower window narrows the reconstruction
    win3 = rec.window(series=["t_gauge"], window_s=3.0)
    assert [p[1] for p in
            win3["series"]["t_gauge"]["children"][0]["points"]] == \
        [36.0, 37.0, 38.0, 39.0]
    # ring bytes accounting stays positive and bounded
    assert 0 < rec._ring_bytes < 10_000_000


def test_delta_encode_decode_identity():
    """Randomized mutations, reconstruction must equal the directly
    recorded truth for every retained sample — including across ring
    wrap (keyframe reachability) and for histogram bucket state."""
    import random as _random

    rng = _random.Random(7)
    reg = Registry()
    c = reg.counter("t_total", "c", labels=("route",))
    g = reg.gauge("t_depth", "g")
    h = reg.histogram("t_lat_seconds", "h", buckets=(0.1, 1.0, 10.0))
    clock = FakeClock(0.0)
    rec = _recorder(reg, clock, clock, hz=1.0, window_s=20.0,
                    keyframe_every=5)
    truth = []  # per tick: (counter a, counter b, gauge, hist count)
    for i in range(60):
        if rng.random() < 0.7:
            c.labels(route="/a").inc(rng.randint(1, 3))
        if rng.random() < 0.4:
            c.labels(route="/b").inc()
        if rng.random() < 0.8:
            g.set(rng.uniform(0, 50))
        for _ in range(rng.randint(0, 3)):
            h.observe(rng.choice([0.05, 0.5, 5.0, 50.0]))
        rec.sample_now()
        truth.append((c.labels(route="/a").value,
                      c.labels(route="/b").value,
                      g.value, h.count, h.sum))
        clock.advance(1.0)
    win = rec.window(window_s=20.0)
    n = len(win["series"]["t_depth"]["children"][0]["points"])
    assert n == 21
    expected = truth[-n:]
    by_route = {json.dumps(ch["labels"], sort_keys=True): ch["points"]
                for ch in win["series"]["t_total"]["children"]}
    pts_a = by_route['{"route": "/a"}']
    pts_b = by_route['{"route": "/b"}']
    pts_g = win["series"]["t_depth"]["children"][0]["points"]
    pts_h = win["series"]["t_lat_seconds"]["children"][0]["points"]
    for i, (va, vb, vg, hc, hs) in enumerate(expected):
        assert pts_a[i][1] == va
        assert pts_b[i][1] == vb
        assert pts_g[i][1] == pytest.approx(vg)
        assert pts_h[i][1] == hc           # cumulative count
        assert pts_h[i][2] == pytest.approx(hs, abs=1e-6)
    # interval counts sum back to the cumulative delta over the window
    interval_total = sum(p[3] for p in pts_h[1:])
    assert interval_total == pts_h[-1][1] - pts_h[0][1]


def test_histogram_interval_quantiles_reflect_that_second():
    """The recorder's histogram points answer "what did p99 look like
    THEN": interval quantiles over the per-sample bucket deltas, not
    the cumulative-forever distribution."""
    reg = Registry()
    h = reg.histogram("t_q_seconds", "h", buckets=(0.01, 0.1, 1.0))
    clock = FakeClock(0.0)
    rec = _recorder(reg, clock, clock, window_s=10.0)
    for _ in range(100):
        h.observe(0.005)               # a fast baseline second
    rec.sample_now()
    clock.advance(1.0)
    for _ in range(100):
        h.observe(0.5)                 # then a slow second
    rec.sample_now()
    win = rec.window(series=["t_q_seconds"], window_s=10.0)
    pts = win["series"]["t_q_seconds"]["children"][0]["points"]
    # first point has no interval base -> quantile over cumulative-so-far
    assert pts[0][5] <= 0.01
    # second point's interval p99 sits in the slow bucket even though
    # cumulatively half the observations were fast
    assert 0.1 < pts[1][5] <= 1.0


def test_recorder_sampler_thread_and_off_mode(monkeypatch,
                                              clean_singletons):
    monkeypatch.setenv("PIO_RECORDER", "0")
    assert obs_recorder.get_recorder() is None
    assert not [t for t in threading.enumerate()
                if t.name == "pio-flight-recorder"]
    monkeypatch.setenv("PIO_RECORDER", "1")
    monkeypatch.setenv("PIO_RECORDER_HZ", "50")
    rec = obs_recorder.get_recorder()
    assert rec is not None
    assert [t for t in threading.enumerate()
            if t.name == "pio-flight-recorder"]
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and rec.index()["samples"] < 3:
        time.sleep(0.02)
    assert rec.index()["samples"] >= 3
    # the bookkeeping series are exported
    assert rec.registry.get("pio_recorder_samples_total").value >= 3
    assert rec.registry.get("pio_recorder_ring_bytes").value > 0


def test_observe_p99_unchanged_with_recorder_on():
    """The tentpole overhead pin: a hot observe() loop's p99 wall stays
    microseconds-scale while the recorder samples concurrently — the
    sampler holds no lock the observe path waits on (generous absolute
    bound; the assertion is "no stall", not a micro-benchmark)."""
    reg = Registry()
    h = reg.histogram("t_hot_seconds", "h")
    clock = FakeClock(0.0)
    rec = FlightRecorder(registry=reg, hz=100.0, window_s=5.0,
                         clock=time.monotonic, wall=time.time)
    rec.start()
    try:
        time.sleep(0.05)  # sampler running
        walls = []
        tok = obs_trace.set_current("hot-trace")
        try:
            for i in range(20000):
                t0 = time.perf_counter()
                h.observe(0.001 * (i % 7))
                walls.append(time.perf_counter() - t0)
        finally:
            obs_trace.reset_current(tok)
        walls.sort()
        p99 = walls[int(len(walls) * 0.99)]
        assert p99 < 0.005, f"observe p99 {p99 * 1e6:.0f}us with " \
            "recorder on — the sampler is stalling the hot path"
    finally:
        rec.stop()


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------

def test_exemplar_reservoir_determinism_under_seeded_load():
    def run():
        obs_metrics.seed_exemplar_rng(42)
        reg = Registry()
        h = reg.histogram("t_ex_seconds", "h", buckets=(0.1, 1.0))
        for i in range(200):
            tok = obs_trace.set_current(f"trace-{i}")
            try:
                h.observe(0.05 if i % 2 == 0 else 0.5)
            finally:
                obs_trace.reset_current(tok)
        return h.exemplars()

    def key(exs):
        # the wall stamp is real time; determinism is about WHICH
        # observation survived the reservoir
        return [(e["le"], e["traceId"], e["value"]) for e in exs]

    a, b = run(), run()
    assert key(a) == key(b)
    # one exemplar per touched bucket, each naming a real trace
    assert len(a) == 2
    for rec_ in a:
        assert rec_["traceId"].startswith("trace-")


def test_exemplar_window_reset_and_untraced_observations(monkeypatch):
    monkeypatch.setenv("PIO_EXEMPLAR_WINDOW_S", "0.05")
    reg = Registry()
    h = reg.histogram("t_w_seconds", "h", buckets=(1.0,))
    h.observe(0.5)                     # no ambient trace: no exemplar
    assert h.exemplars() == []
    tok = obs_trace.set_current("first-window")
    try:
        h.observe(0.5)
    finally:
        obs_trace.reset_current(tok)
    time.sleep(0.1)                    # window expires
    tok = obs_trace.set_current("second-window")
    try:
        h.observe(0.5)
    finally:
        obs_trace.reset_current(tok)
    ex = h.exemplars()
    assert len(ex) == 1 and ex[0]["traceId"] == "second-window"


def test_exemplars_off_switch(monkeypatch):
    monkeypatch.setenv("PIO_EXEMPLARS", "0")
    reg = Registry()
    h = reg.histogram("t_off_seconds", "h", buckets=(1.0,))
    tok = obs_trace.set_current("should-not-appear")
    try:
        h.observe(0.5)
    finally:
        obs_trace.reset_current(tok)
    assert h.exemplars() == []
    assert "# {" not in reg.expose()


def _scrape_result(instance, text, ok=True):
    return federate.ScrapeResult(
        target=federate.Target(instance, f"http://{instance}"),
        ok=ok, wall_s=0.0,
        families=expofmt.parse_families(text) if ok else {})


def test_exemplar_emit_parse_reemit_byte_stable():
    """The round-trip satellite: raw exemplar annotations survive
    registry exposition → parse → federated re-exposition → parse,
    byte-for-byte."""
    reg = Registry()
    h = reg.histogram("t_rt_seconds", "h", buckets=(0.1, 1.0))
    tok = obs_trace.set_current("rt-trace")
    try:
        h.observe(0.05)
        h.observe(0.7)
    finally:
        obs_trace.reset_current(tok)
    text = reg.expose()
    raw_annotations = sorted(
        line.split(" # ", 1)[1] for line in text.splitlines()
        if " # {" in line)
    assert len(raw_annotations) == 2
    snap = federate.FederatedSnapshot([_scrape_result("w1", text)])
    fleet_text = snap.expose()
    fleet_annotations = sorted(
        line.split(" # ", 1)[1] for line in fleet_text.splitlines()
        if " # {" in line)
    assert ["# " + a for a in fleet_annotations] == \
        ["# " + a for a in raw_annotations]
    # and the fleet exposition itself re-parses with exemplars intact
    fams = expofmt.parse_families(fleet_text)
    child = list(fams["t_rt_seconds"].histograms.values())[0]
    assert [tid for _le, tid in child.exemplar_trace_ids()] == \
        ["rt-trace", "rt-trace"]


def test_unknown_exemplar_annotation_passes_through():
    """An annotation this parser does NOT understand must survive a
    federation round trip verbatim — pass-through, not validation."""
    weird = ('# TYPE t_f_seconds histogram\n'
             't_f_seconds_bucket{le="1"} 3 '
             '# {span_id="zz",weird="yes"} 0.5 not-a-ts extra\n'
             't_f_seconds_bucket{le="+Inf"} 3\n'
             't_f_seconds_sum 1.5\n'
             't_f_seconds_count 3\n'
             '# TYPE t_c_total counter\n'
             't_c_total 5 # {foo="bar"} 1\n')
    fams = expofmt.parse_families(weird)
    child = list(fams["t_f_seconds"].histograms.values())[0]
    raw = child.exemplars[1.0]
    assert raw == '# {span_id="zz",weird="yes"} 0.5 not-a-ts extra'
    assert expofmt.parse_exemplar(raw) is None   # not understood
    assert child.exemplar_trace_ids() == []      # and not invented
    snap = federate.FederatedSnapshot([_scrape_result("w1", weird)])
    fleet_text = snap.expose()
    assert '# {span_id="zz",weird="yes"} 0.5 not-a-ts extra' in fleet_text
    assert '# {foo="bar"} 1' in fleet_text       # counter exemplar too
    expofmt.parse_families(fleet_text)           # still well-formed


def test_federation_kind_mismatch_drop_with_exemplars_present():
    reg = Registry()
    h = reg.histogram("t_km_seconds", "h", buckets=(1.0,))
    tok = obs_trace.set_current("keep-me")
    try:
        h.observe(0.5)
    finally:
        obs_trace.reset_current(tok)
    good = reg.expose()
    bad = ('# TYPE t_km_seconds counter\n'
           't_km_seconds 7\n')
    snap = federate.FederatedSnapshot([
        _scrape_result("new", good), _scrape_result("old", bad)])
    m = snap.get("t_km_seconds")
    # the dissenting kind's children were dropped; the exemplar-bearing
    # histogram child survived with its annotation
    assert m.kind == "histogram"
    assert [(inst, tid) for inst, _le, tid
            in m.exemplar_trace_ids()] == [("new", "keep-me")]
    assert not [k for k in m.values if k[0] == "old"]


def test_exemplar_free_old_worker_federates_cleanly():
    reg_new = Registry()
    h = reg_new.histogram("t_mix_seconds", "h", buckets=(1.0,))
    tok = obs_trace.set_current("new-only")
    try:
        h.observe(0.5)
    finally:
        obs_trace.reset_current(tok)
    reg_old = Registry()
    reg_old.histogram("t_mix_seconds", "h", buckets=(1.0,)).observe(0.5)
    snap = federate.FederatedSnapshot([
        _scrape_result("new", reg_new.expose()),
        _scrape_result("old", reg_old.expose())])
    m = snap.get("t_mix_seconds")
    assert m.count == 2                          # both instances merged
    assert [(inst, tid) for inst, _le, tid
            in m.exemplar_trace_ids()] == [("new", "new-only")]
    expofmt.parse_families(snap.expose())


def test_scheduler_dispatch_carries_exemplar_trace():
    """The scheduler seam: the dispatcher thread re-installs the oldest
    traced member's trace ID around handle_batch, so the per-query
    latency histogram books exemplars for fused batches too."""
    from incubator_predictionio_tpu.serving.scheduler import (
        BatchScheduler,
    )

    reg = Registry()
    h = reg.histogram("t_sched_seconds", "h", buckets=(1.0,))

    def handle(bodies):
        h.observe(0.5, len(bodies))
        return bodies

    sched = BatchScheduler(handle, 8, shed=False)
    try:
        tok = obs_trace.set_current("sched-trace-1")
        try:
            fut = sched.submit({"q": 1})
        finally:
            obs_trace.reset_current(tok)
        assert fut.result(timeout=10) == {"q": 1}
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not h.exemplars():
            time.sleep(0.01)
        ex = h.exemplars()
        assert ex and ex[0]["traceId"] == "sched-trace-1"
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# incident capture: planted breach, cooldown, bundles
# ---------------------------------------------------------------------------

def _serve_spec(threshold=0.001):
    return (obs_slo.SLOSpec(
        name="serve_p99", metric="pio_query_latency_seconds",
        threshold=threshold, target=0.99,
        description="test objective"),)


def test_planted_breach_exactly_one_bundle_cooldown_pinned(tmp_path):
    reg = Registry()
    h = reg.histogram("pio_query_latency_seconds", "q")
    clock = FakeClock(1000.0)
    engine = obs_slo.SLOEngine(specs=_serve_spec(), registry=reg,
                               clock=clock, export_gauges=False,
                               min_tick_interval_s=0.0)
    rec = _recorder(reg, clock, clock, hz=1.0, window_s=30.0)
    cap = IncidentCapture(directory=str(tmp_path), recorder=rec,
                          cooldown_s=120.0, clock=clock, wall=clock,
                          targets_fn=lambda: [],
                          decisions_fn=lambda: [
                              {"id": 1, "kind": "evaluation",
                               "ts": 995.0, "action": "none"}])
    cap.install(engine)

    def bundles():
        return sorted(p.name for p in tmp_path.glob("inc-*.json"))

    engine.evaluate()                       # baseline snapshot: no data
    assert bundles() == []
    for step in range(5):
        clock.advance(2.0)
        for _ in range(20):
            tok = obs_trace.set_current(f"bad-{step}")
            try:
                h.observe(0.5)              # every observation is bad
            finally:
                obs_trace.reset_current(tok)
        rec.sample_now()
        engine.evaluate()                   # breached on every pass
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not bundles():
        time.sleep(0.05)
    # a SUSTAINED burn (5 breached evaluations) yielded ONE bundle
    cap.stop()
    assert len(bundles()) == 1, bundles()
    bundle = json.loads((tmp_path / bundles()[0]).read_text())
    assert bundle["trigger"] == "serve_p99"
    assert bundle["scope"] == "process"
    assert bundle["slo"]["windows"]["fast"]["burnRate"] > 1.0
    # the local recorder window + the planted decision rode along
    assert "pio_query_latency_seconds" in \
        bundle["recorder"]["instances"]["local"]["series"]
    assert bundle["exemplars"]["traceIds"]
    assert bundle["decisions"] == [{"id": 1, "kind": "evaluation",
                                    "ts": 995.0, "action": "none"}]
    # cooldown expiry re-arms: the next breach captures again
    clock.advance(200.0)
    for _ in range(20):
        h.observe(0.5)
    engine.evaluate()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(bundles()) < 2:
        time.sleep(0.05)
    assert len(bundles()) == 2, bundles()
    # and the artifact passes the report tool's --check gate
    proc = subprocess.run(
        [sys.executable, REPORT, str(tmp_path / bundles()[0]),
         "--check"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr


def test_incident_report_check_rejects_malformed(tmp_path):
    bad = tmp_path / "inc-bad.json"
    bad.write_text(json.dumps({"schema": "pio-incident-v1",
                               "id": "inc-bad"}))
    proc = subprocess.run(
        [sys.executable, REPORT, str(bad), "--check"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "MALFORMED" in proc.stderr
    notjson = tmp_path / "inc-notjson.json"
    notjson.write_text("{truncated")
    proc = subprocess.run(
        [sys.executable, REPORT, str(notjson), "--check"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1


def test_manual_trigger_bypasses_cooldown(tmp_path):
    reg = Registry()
    clock = FakeClock(0.0)
    rec = _recorder(reg, clock, clock)
    cap = IncidentCapture(directory=str(tmp_path), recorder=rec,
                          cooldown_s=3600.0, clock=clock, wall=clock,
                          targets_fn=lambda: [],
                          decisions_fn=lambda: [])
    out1 = cap.capture_now(cap.MANUAL_TRIGGER)
    # SAME wall second (FakeClock unmoved): the id must uniquify, not
    # silently clobber the first bundle
    out2 = cap.capture_now(cap.MANUAL_TRIGGER)
    assert out1["id"] != out2["id"]
    assert len(list(tmp_path.glob("inc-*.json"))) == 2
    # trigger() (the breach path) still honors cooldown per reason
    assert cap.trigger("serve_p99") is True
    assert cap.trigger("serve_p99") is False
    cap.stop()


def test_failed_capture_does_not_consume_cooldown(tmp_path):
    """A transient bundle-write failure must re-arm the trigger: the
    incident's ring evidence is aging out, and a 300 s blind window
    after ENOSPC would lose it."""
    reg = Registry()
    clock = FakeClock(0.0)
    rec = _recorder(reg, clock, clock)
    cap = IncidentCapture(directory=str(tmp_path), recorder=rec,
                          cooldown_s=3600.0, clock=clock, wall=clock,
                          targets_fn=lambda: [],
                          decisions_fn=lambda: [])
    boom = {"fail": True}
    real_capture = cap.capture_now

    def flaky(reason, slo_entry=None):
        if boom["fail"]:
            raise OSError("disk full")
        return real_capture(reason, slo_entry)

    cap.capture_now = flaky
    assert cap.trigger("serve_p99") is True
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and "serve_p99" in cap._queued:
        time.sleep(0.02)
    assert not list(tmp_path.glob("inc-*.json"))
    # the failure rolled the cooldown stamp back: the next breach
    # (storage fixed) captures immediately
    boom["fail"] = False
    assert cap.trigger("serve_p99") is True
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and \
            not list(tmp_path.glob("inc-*.json")):
        time.sleep(0.02)
    assert len(list(tmp_path.glob("inc-*.json"))) == 1
    cap.stop()


# ---------------------------------------------------------------------------
# HTTP e2e: /recorder, /incidents, POST /incident
# ---------------------------------------------------------------------------

def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read().decode())


def test_recorder_and_incident_routes_e2e(monkeypatch, tmp_path,
                                          clean_singletons):
    from incubator_predictionio_tpu.obs.http import (
        add_incident_routes,
        add_metrics_route,
        add_recorder_route,
    )
    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    monkeypatch.setenv("PIO_RECORDER", "1")
    monkeypatch.setenv("PIO_RECORDER_HZ", "20")
    monkeypatch.setenv("PIO_INCIDENT_DIR", str(tmp_path))
    h = obs_metrics.REGISTRY.histogram(
        "pio_query_latency_seconds",
        "per-query serving wall (micro-batch members share the batch "
        "wall)", labels=("tenant",))
    tok = obs_trace.set_current("e2e-trace")
    try:
        for _ in range(10):
            h.labels(tenant="default").observe(0.02)
    finally:
        obs_trace.reset_current(tok)
    r = Router()
    add_metrics_route(r)
    add_recorder_route(r)
    add_incident_routes(r)
    srv = HttpServer(r, "127.0.0.1", 0, name="admin")
    port = srv.start_background()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                _get_json(port, "/recorder")["samples"] < 3:
            time.sleep(0.05)
        idx = _get_json(port, "/recorder")
        assert idx["samples"] >= 3
        assert "pio_query_latency_seconds" in idx["series"]
        win = _get_json(
            port, "/recorder?series=pio_query_latency_seconds&window=60")
        pts = win["series"]["pio_query_latency_seconds"][
            "children"][0]["points"]
        assert pts and pts[-1][1] >= 10
        full = _get_json(port, "/recorder?all=1")
        assert any(e["traceId"] == "e2e-trace"
                   for e in full["exemplars"])
        # /metrics carries the exemplar syntax end to end
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert '# {trace_id="e2e-trace"}' in text
        # manual capture + listing + fetch
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/incident", data=b"",
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            created = json.loads(resp.read().decode())
        assert created["id"].startswith("inc-")
        listing = _get_json(port, "/incidents")
        assert [i["id"] for i in listing["incidents"]] == [created["id"]]
        bundle = _get_json(port, f"/incidents/{created['id']}")
        assert bundle["trigger"] == "manual"
        assert "local" in bundle["recorder"]["instances"]
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(port, "/incidents/inc-nope")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_recorder_route_503_when_disabled(monkeypatch, clean_singletons):
    from incubator_predictionio_tpu.obs.http import add_recorder_route
    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    monkeypatch.setenv("PIO_RECORDER", "0")
    r = Router()
    add_recorder_route(r)
    srv = HttpServer(r, "127.0.0.1", 0, name="worker")
    port = srv.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(port, "/recorder")
        assert err.value.code == 503
    finally:
        srv.stop()


def test_incident_routes_503_without_dir(monkeypatch, clean_singletons):
    from incubator_predictionio_tpu.obs.http import add_incident_routes
    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    monkeypatch.delenv("PIO_INCIDENT_DIR", raising=False)
    r = Router()
    add_incident_routes(r)
    srv = HttpServer(r, "127.0.0.1", 0, name="admin")
    port = srv.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(port, "/incidents")
        assert err.value.code == 503
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the acceptance bar: two REAL workers, fleet breach -> ONE bundle with
# instance-labeled windows, exemplar trace IDs, stitched cross-process
# ---------------------------------------------------------------------------

def _spawn_serve_worker(seed, stderr_sink):
    """Launch one serve-mode worker (returns immediately; pair with
    :func:`_await_worker_port` so two workers pay their jax imports in
    parallel). The worker's stderr — its span log — drains live into
    ``stderr_sink`` so the pipe can never fill."""
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           "PIO_RECORDER": "1",
           "PIO_RECORDER_HZ": "10",
           "PIO_SPEED_LAYER": "0"}
    env.pop("PIO_INCIDENT_DIR", None)  # workers record, the TEST captures
    proc = subprocess.Popen(
        [sys.executable, WORKER, "--mode", "serve", "--seed", str(seed),
         "--users", "60", "--items", "40", "--rank", "8",
         "--max-batch", "8"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=TESTS_DIR, env=env)

    def drain():
        for line in proc.stderr:
            stderr_sink.append(line)

    threading.Thread(target=drain, daemon=True).start()
    return proc


def _await_worker_port(proc, stderr_sink):
    port_holder = []

    def read_port():
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port_holder.append(int(line.split()[1]))

    t = threading.Thread(target=read_port, daemon=True)
    t.start()
    t.join(timeout=120)
    if not port_holder:
        proc.kill()
        raise RuntimeError(
            "worker never bound: " + "".join(stderr_sink)[-2000:])
    return port_holder[0]


def _load_trace_stitch():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import trace_stitch
    return trace_stitch


def test_two_worker_fleet_breach_acceptance(tmp_path):
    """Planted two-REAL-worker fleet SLO breach → autonomously ONE
    incident bundle with the fleet-merged pre-breach window (instance
    labels), ≥1 exemplar trace ID for the breaching histogram that the
    trace_stitch machinery reconstructs cross-process, and the
    in-window controller decisions — the PR's acceptance bar."""
    spans0, spans1 = [], []
    p0 = _spawn_serve_worker(0, spans0)
    p1 = _spawn_serve_worker(1, spans1)
    port0 = _await_worker_port(p0, spans0)
    port1 = _await_worker_port(p1, spans1)
    sent_traces = []
    try:
        targets = [
            federate.Target("w0", f"http://127.0.0.1:{port0}/metrics"),
            federate.Target("w1", f"http://127.0.0.1:{port1}/metrics"),
        ]
        fleet_reg = federate.FleetRegistry(
            targets_fn=lambda: targets, max_age_s=0.0)
        engine = obs_slo.SLOEngine(
            specs=_serve_spec(threshold=1e-6),  # every real serve is bad
            registry=fleet_reg, export_gauges=False,
            min_tick_interval_s=0.0)
        decisions = [{"id": 7, "kind": "evaluation", "mode": "act",
                      "ts": time.time(), "action": "retrain+reload",
                      "reason": "staleness_projection",
                      "traceId": "ctl-deadbeef"}]
        cap = IncidentCapture(
            directory=str(tmp_path), cooldown_s=3600.0,
            targets_fn=lambda: targets,
            decisions_fn=lambda: decisions)
        cap.install(engine)

        def query(port, i):
            tid = f"fleet-q-{port}-{i}"
            sent_traces.append(tid)
            body = json.dumps({"user": f"u{i % 60}", "num": 5}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json", data=body,
                headers={"Content-Type": "application/json",
                         "X-PIO-Trace-Id": tid,
                         "X-PIO-Parent-Span": "cafe0001"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200

        engine.evaluate()                 # baseline fleet snapshot
        for i in range(25):
            query(port0, i)
            query(port1, i)
        time.sleep(1.0)                   # worker recorders tick (10 Hz)
        engine.evaluate()                 # burn > 1 -> breach -> capture

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and \
                not list(tmp_path.glob("inc-*.json")):
            time.sleep(0.1)
        bundles = sorted(tmp_path.glob("inc-*.json"))
        assert len(bundles) == 1, [b.name for b in bundles]
        # sustained burn: further breached evaluations add NO bundle
        for i in range(25, 35):
            query(port0, i)
        engine.evaluate()
        time.sleep(1.0)
        assert len(list(tmp_path.glob("inc-*.json"))) == 1
        cap.stop()

        bundle = json.loads(bundles[0].read_text())
        assert bundle["trigger"] == "serve_p99"
        assert bundle["scope"] == "fleet"
        insts = bundle["recorder"]["instances"]
        # the fleet-merged pre-breach window: BOTH instances, each with
        # the breaching histogram's recorded history + scheduler state
        assert sorted(insts) == ["w0", "w1"]
        for name in ("w0", "w1"):
            dump = insts[name]
            assert "error" not in dump, dump.get("error")
            assert "pio_query_latency_seconds" in dump["series"]
            assert "scheduler" in dump["state"]
            assert "engines" in dump["state"]["scheduler"]
        # >=1 exemplar trace ID for the breaching histogram, and it is
        # one of the trace IDs the load generator actually sent
        ex_ids = bundle["exemplars"]["traceIds"]
        assert ex_ids and set(ex_ids) <= set(sent_traces)
        # the in-window controller decisions rode along
        assert bundle["decisions"] == decisions
        # incident_report --check accepts the artifact
        proc = subprocess.run(
            [sys.executable, REPORT, str(bundles[0]), "--check"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
    finally:
        for p in (p0, p1):
            try:
                p.stdin.close()
            except Exception:
                pass
        for p in (p0, p1):
            try:
                p.wait(timeout=30)
            except Exception:
                p.kill()
    # cross-process reconstruction: the workers' span logs (their
    # stderr, drained live) hold the exemplar traces; the stitcher
    # rebuilds each as a tree whose worker span links under the
    # client-side parent span the generator stamped
    trace_stitch = _load_trace_stitch()
    spans = trace_stitch.parse_span_lines(spans0 + spans1)
    traces = trace_stitch.group_by_trace(spans)
    ex_ids = json.loads(
        sorted(tmp_path.glob("inc-*.json"))[0].read_text())[
        "exemplars"]["traceIds"]
    stitched = 0
    for tid in ex_ids:
        if tid not in traces:
            continue
        rendered = trace_stitch.render_trace(tid, traces[tid])
        assert "prediction POST /queries.json" in rendered
        # the worker's span named the client's parent span id
        assert any(s.get("parentSpanId") == "cafe0001"
                   for s in traces[tid])
        stitched += 1
    assert stitched >= 1, (ex_ids, list(traces)[:5])
