"""Event model + validation matrix (parity: data/.../storage/Event.scala:112-167)."""

import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    validate_event,
)
from incubator_predictionio_tpu.utils.times import parse_iso8601


def ev(**kw):
    base = dict(event="rate", entity_type="user", entity_id="u1")
    base.update(kw)
    return Event(**base)


def test_valid_plain_event():
    validate_event(
        ev(
            target_entity_type="item",
            target_entity_id="i1",
            properties=DataMap({"rating": 4.0}),
        )
    )


@pytest.mark.parametrize(
    "bad",
    [
        dict(event=""),
        dict(entity_type=""),
        dict(entity_id=""),
        dict(target_entity_type="item"),  # target type without id
        dict(target_entity_id="i1"),  # target id without type
        dict(target_entity_type="", target_entity_id="i1"),
        dict(event="$unset"),  # empty props
        dict(event="$custom"),  # reserved prefix, not special
        dict(event="pio_thing"),
        dict(event="$set", target_entity_type="item", target_entity_id="i1"),
        dict(entity_type="pio_user"),
        dict(target_entity_type="pio_item", target_entity_id="i1"),
        dict(properties=DataMap({"pio_weight": 1})),
    ],
)
def test_invalid_events(bad):
    with pytest.raises(EventValidationError):
        validate_event(ev(**bad))


def test_special_events_allowed():
    validate_event(ev(event="$set", properties=DataMap({"a": 1})))
    validate_event(ev(event="$unset", properties=DataMap({"a": 1})))
    validate_event(ev(event="$delete"))
    # built-in entity type may use the reserved prefix
    validate_event(ev(entity_type="pio_pr"))


def test_json_round_trip():
    e = ev(
        target_entity_type="item",
        target_entity_id="i1",
        properties=DataMap({"rating": 4.5}),
        event_time=parse_iso8601("2014-09-09T16:17:42.937-08:00"),
        tags=("a", "b"),
        pr_id="pr-1",
        event_id="abc123",
    )
    j = e.to_jsonable()
    assert j["event"] == "rate"
    assert j["entityType"] == "user"
    assert j["targetEntityId"] == "i1"
    back = Event.from_jsonable(j)
    assert back.event == e.event
    assert back.entity_id == e.entity_id
    assert back.properties == e.properties
    assert back.event_time == e.event_time
    assert back.tags == e.tags
    assert back.pr_id == "pr-1"
    assert back.event_id == "abc123"


def test_from_jsonable_rejects_malformed():
    with pytest.raises(ValueError):
        Event.from_jsonable({"entityType": "user", "entityId": "u1"})  # no event
    with pytest.raises(ValueError):
        Event.from_jsonable({"event": "rate", "entityType": 3, "entityId": "u1"})
    with pytest.raises(ValueError):
        Event.from_jsonable(
            {"event": "rate", "entityType": "user", "entityId": "u1", "properties": []}
        )
