"""Classification, similarproduct and ecommerce templates end-to-end."""

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.workflow import CoreWorkflow


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def seed_app(name):
    Storage.get_meta_data_apps().insert(App(0, name))
    return Storage.get_meta_data_apps().get_by_name(name).id


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def seed_classification(app_id):
    dao = Storage.get_events()
    rng = np.random.default_rng(0)
    for n in range(80):
        # plan 1.0 users: high attr0; plan 0.0 users: high attr2
        plan = float(n % 2)
        attrs = (
            {"attr0": int(rng.integers(5, 10)), "attr1": int(rng.integers(0, 3)),
             "attr2": int(rng.integers(0, 2))}
            if plan == 1.0 else
            {"attr0": int(rng.integers(0, 2)), "attr1": int(rng.integers(0, 3)),
             "attr2": int(rng.integers(5, 10))}
        )
        dao.insert(Event(
            event="$set", entity_type="user", entity_id=f"u{n}",
            properties=DataMap({"plan": plan, **attrs}),
        ), app_id)


def test_classification_template():
    from incubator_predictionio_tpu.models.classification import (
        ClassificationEngine,
        DataSourceParams,
        LogRegAlgorithmParams,
        NaiveBayesAlgorithmParams,
        Query,
    )

    app_id = seed_app("clf")
    seed_classification(app_id)
    engine = ClassificationEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="clf")),
        algorithm_params_list=[
            ("naive", NaiveBayesAlgorithmParams(lambda_=1.0)),
            ("logreg", LogRegAlgorithmParams(steps=200)),
        ],
    )
    iid = CoreWorkflow.run_train(engine, ep, engine_variant="clf")
    models = CoreWorkflow.load_models(iid, engine, ep)
    nb_algo, lr_algo = engine.algorithms(ep)
    q_plan1 = Query(features=(8.0, 1.0, 0.0))
    q_plan0 = Query(features=(0.0, 1.0, 8.0))
    assert nb_algo.predict(models[0], q_plan1).label == 1.0
    assert nb_algo.predict(models[0], q_plan0).label == 0.0
    assert lr_algo.predict(models[1], q_plan1).label == 1.0
    assert lr_algo.predict(models[1], q_plan0).label == 0.0


def test_classification_wire_format():
    from incubator_predictionio_tpu.models.classification import Query
    from incubator_predictionio_tpu.utils import json_codec

    q = json_codec.extract(Query, {"features": [1.0, 2.0, 3.0]})
    assert q.features == (1.0, 2.0, 3.0)


# ---------------------------------------------------------------------------
# similarproduct
# ---------------------------------------------------------------------------

def seed_views(app_id, extra_like=False):
    dao = Storage.get_events()
    rng = np.random.default_rng(1)
    # block structure: uA* view iA*, uB* view iB*
    for block, (users, items) in enumerate((
        ([f"uA{i}" for i in range(6)], [f"iA{i}" for i in range(8)]),
        ([f"uB{i}" for i in range(6)], [f"iB{i}" for i in range(8)]),
    )):
        for u in users:
            for it in items:
                if rng.random() < 0.6:
                    dao.insert(Event(event="view", entity_type="user",
                                     entity_id=u, target_entity_type="item",
                                     target_entity_id=it), app_id)
    if extra_like:
        dao.insert(Event(event="like", entity_type="user", entity_id="uA0",
                         target_entity_type="item", target_entity_id="iA1"),
                   app_id)
    for i in range(8):
        dao.insert(Event(
            event="$set", entity_type="item", entity_id=f"iA{i}",
            properties=DataMap({"categories": ["catA"]}),
        ), app_id)


def test_similarproduct_template():
    from incubator_predictionio_tpu.models.similarproduct import (
        ALSAlgorithmParams,
        DataSourceParams,
        Query,
        SimilarProductEngine,
    )

    app_id = seed_app("simapp")
    seed_views(app_id, extra_like=True)
    engine = SimilarProductEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="simapp")),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=8, num_iterations=10,
                                       lambda_=0.05, alpha=2.0, seed=7)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    r = algo.predict(models[0], Query(items=("iA0",), num=3))
    assert r.item_scores
    assert all(s.item.startswith("iA") for s in r.item_scores)
    assert "iA0" not in {s.item for s in r.item_scores}  # query item excluded
    # unknown item → empty
    assert algo.predict(models[0], Query(items=("nope",), num=3)).item_scores == ()
    # blacklist
    r2 = algo.predict(models[0], Query(items=("iA0",), num=4,
                                       black_list=("iA1",)))
    assert "iA1" not in {s.item for s in r2.item_scores}
    # category filter restricts to cat-A even for a B-block query item
    r3 = algo.predict(models[0], Query(items=("iB0",), num=3,
                                       categories=("catA",)))
    assert all(s.item.startswith("iA") for s in r3.item_scores)


def test_similarproduct_dimsum_variant():
    """The similarproduct-dimsum variant: exact item-item cosine
    similarities replacing Spark's sampled columnSimilarities
    (ops/dimsum.py)."""
    from incubator_predictionio_tpu.models.similarproduct import (
        DataSourceParams,
        Query,
        SimilarProductEngine,
    )
    from incubator_predictionio_tpu.models.similarproduct.engine import (
        DIMSUMAlgorithmParams,
    )

    app_id = seed_app("dimapp")
    seed_views(app_id)
    engine = SimilarProductEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="dimapp")),
        algorithm_params_list=[
            ("dimsum", DIMSUMAlgorithmParams(threshold=0.05, top_n=10)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    r = algo.predict(models[0], Query(items=("iA0",), num=3))
    assert r.item_scores
    # co-viewed block items are the cosine neighbors
    assert all(s.item.startswith("iA") for s in r.item_scores)
    assert "iA0" not in {s.item for s in r.item_scores}
    # multi-item query sums similarities (indexScores groupBy-sum)
    r2 = algo.predict(models[0], Query(items=("iA0", "iA1"), num=3))
    assert r2.item_scores
    assert {"iA0", "iA1"}.isdisjoint({s.item for s in r2.item_scores})
    # scores descending + filters shared with the ALS variant
    scores = [s.score for s in r2.item_scores]
    assert scores == sorted(scores, reverse=True)
    r3 = algo.predict(models[0], Query(items=("iA0",), num=4,
                                       black_list=("iA1",)))
    assert "iA1" not in {s.item for s in r3.item_scores}
    assert algo.predict(
        models[0], Query(items=("nope",), num=3)).item_scores == ()


def test_dimsum_matches_numpy_cosine():
    """ops/dimsum.py produces the exact cosine matrix (what DIMSUM merely
    approximates) — checked against a dense numpy reference."""
    from incubator_predictionio_tpu.ops.dimsum import column_cosine_topk

    rng = np.random.default_rng(4)
    n_users, n_items, nnz = 40, 12, 200
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    weights = rng.random(nnz).astype(np.float32)
    dense = np.zeros((n_users, n_items), np.float64)
    np.add.at(dense, (users, items), weights)
    gram = dense.T @ dense
    norms = np.sqrt(np.maximum(np.diag(gram), 1e-12))
    ref = gram / np.outer(norms, norms)
    np.fill_diagonal(ref, 0.0)
    ref[ref < 0.2] = 0.0

    scores, indices = column_cosine_topk(
        users, items, weights, n_items=n_items, threshold=0.2,
        top_n=n_items)
    got = np.zeros((n_items, n_items), np.float32)
    for i in range(n_items):
        got[i, indices[i]] = scores[i]
    np.testing.assert_allclose(got, ref, atol=2e-3)


# ---------------------------------------------------------------------------
# ecommerce
# ---------------------------------------------------------------------------

def test_ecommerce_template():
    from incubator_predictionio_tpu.models.ecommerce import (
        DataSourceParams,
        ECommAlgorithmParams,
        ECommerceEngine,
        Query,
    )

    app_id = seed_app("shop2")
    seed_views(app_id)
    dao = Storage.get_events()
    # buys strengthen block A for uA0
    dao.insert(Event(event="buy", entity_type="user", entity_id="uA0",
                     target_entity_type="item", target_entity_id="iA2"), app_id)
    engine = ECommerceEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="shop2")),
        algorithm_params_list=[
            ("ecomm", ECommAlgorithmParams(app_name="shop2", rank=8,
                                           num_iterations=10, lambda_=0.05,
                                           alpha=2.0, seed=5)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]

    r = algo.predict(models[0], Query(user="uA1", num=3))
    assert r.item_scores
    # top unseen recommendation comes from the user's own block (implicit
    # ALS scores *all* unobserved cells near 0, so only the best in-block
    # unseen item clearly outranks the other block on a tiny catalog)
    assert r.item_scores[0].item.startswith("iA")
    # unseen_only: none of uA1's seen items
    seen = {
        e.target_entity_id for e in Storage.get_events().find(
            app_id=app_id, entity_id="uA1")
    }
    assert not seen.intersection({s.item for s in r.item_scores})

    # unavailable items constraint ($set without retraining)
    first = r.item_scores[0].item
    dao.insert(Event(
        event="$set", entity_type="constraint",
        entity_id="unavailableItems",
        properties=DataMap({"items": [first]}),
    ), app_id)
    r2 = algo.predict(models[0], Query(user="uA1", num=3))
    assert first not in {s.item for s in r2.item_scores}

    # unknown user with recent views → item-based vector
    dao.insert(Event(event="view", entity_type="user", entity_id="fresh",
                     target_entity_type="item", target_entity_id="iB0"), app_id)
    dao.insert(Event(event="view", entity_type="user", entity_id="fresh",
                     target_entity_type="item", target_entity_id="iB1"), app_id)
    r3 = algo.predict(models[0], Query(user="fresh", num=2))
    assert r3.item_scores
    assert all(s.item.startswith("iB") for s in r3.item_scores)

    # totally cold user → popularity fallback still answers
    r4 = algo.predict(models[0], Query(user="nobody", num=2))
    assert len(r4.item_scores) == 2


def test_ecommerce_weighted_items():
    """weightedItems constraint multiplies matching items' scores at serve
    time (weighted-items/ECommAlgorithm.scala:234-261)."""
    from incubator_predictionio_tpu.models.ecommerce import (
        DataSourceParams,
        ECommAlgorithmParams,
        ECommerceEngine,
        Query,
    )

    app_id = seed_app("wshop")
    seed_views(app_id)
    dao = Storage.get_events()
    engine = ECommerceEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="wshop")),
        algorithm_params_list=[
            ("ecomm", ECommAlgorithmParams(app_name="wshop", rank=8,
                                           num_iterations=10, lambda_=0.05,
                                           alpha=2.0, seed=5)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]

    base = algo.predict(models[0], Query(user="uA1", num=4))
    assert len(base.item_scores) >= 2
    first, second = base.item_scores[0], base.item_scores[1]

    # boost the runner-up enough to overtake; demote the old leader
    dao.insert(Event(
        event="$set", entity_type="constraint", entity_id="weightedItems",
        properties=DataMap({"weights": [
            {"items": [second.item], "weight": 100.0},
            {"items": [first.item], "weight": 0.001},
        ]}),
    ), app_id)
    boosted = algo.predict(models[0], Query(user="uA1", num=4))
    assert boosted.item_scores[0].item == second.item
    by_item = {s.item: s.score for s in boosted.item_scores}
    assert by_item[second.item] == pytest.approx(second.score * 100.0,
                                                 rel=1e-4)

    # a later $set replaces the groups: back to the natural order
    dao.insert(Event(
        event="$set", entity_type="constraint", entity_id="weightedItems",
        properties=DataMap({"weights": []}),
    ), app_id)
    reset = algo.predict(models[0], Query(user="uA1", num=4))
    assert reset.item_scores[0].item == first.item


# ---------------------------------------------------------------------------
# similarproduct: recommended-user variant
# ---------------------------------------------------------------------------

def seed_follows(app_id):
    dao = Storage.get_events()
    rng = np.random.default_rng(3)
    # two communities: cA* follow each other, cB* follow each other;
    # one bridge edge from cA0 to cB0
    groups = (
        [f"cA{i}" for i in range(7)],
        [f"cB{i}" for i in range(7)],
    )
    for members in groups:
        for u in members:
            for v in members:
                if u != v and rng.random() < 0.7:
                    dao.insert(Event(
                        event="follow", entity_type="user", entity_id=u,
                        target_entity_type="user", target_entity_id=v,
                    ), app_id)
    dao.insert(Event(event="follow", entity_type="user", entity_id="cA0",
                     target_entity_type="user", target_entity_id="cB0"),
               app_id)


def test_recommended_user_template():
    from incubator_predictionio_tpu.models.similarproduct.recommended_user import (
        ALSAlgorithmParams,
        DataSourceParams,
        Query,
        RecommendedUserEngine,
    )

    app_id = seed_app("social")
    seed_follows(app_id)
    engine = RecommendedUserEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="social")),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=8, num_iterations=10,
                                       lambda_=0.05, seed=11)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]

    r = algo.predict(models[0], Query(users=("cA1", "cA2"), num=3))
    assert r.similar_user_scores
    # same community dominates; the query users themselves are excluded
    names = [s.user for s in r.similar_user_scores]
    assert all(u.startswith("cA") for u in names)
    assert not {"cA1", "cA2"}.intersection(names)
    # scores are positive, descending
    scores = [s.score for s in r.similar_user_scores]
    assert all(s > 0 for s in scores)
    assert scores == sorted(scores, reverse=True)

    # blacklist removes a recommendation
    r2 = algo.predict(models[0], Query(users=("cA1",), num=5,
                                       black_list=(names[0],)))
    assert names[0] not in {s.user for s in r2.similar_user_scores}

    # whitelist restricts candidates
    r3 = algo.predict(models[0], Query(users=("cA1",), num=5,
                                       white_list=("cB0", "cB1")))
    assert {s.user for s in r3.similar_user_scores} <= {"cB0", "cB1"}

    # unknown query users → empty result (ALSAlgorithm.scala:149-151)
    r4 = algo.predict(models[0], Query(users=("ghost",), num=3))
    assert r4.similar_user_scores == ()


def test_recommended_user_wire_format():
    from incubator_predictionio_tpu.models.similarproduct.recommended_user import (
        PredictedResult,
        Query,
        SimilarUserScore,
    )
    from incubator_predictionio_tpu.utils import json_codec

    q = json_codec.extract(Query, {
        "users": ["u1", "u2"], "num": 5, "whiteList": ["u3"],
    })
    assert q.users == ("u1", "u2") and q.white_list == ("u3",)
    out = json_codec.to_jsonable(PredictedResult(
        similar_user_scores=(SimilarUserScore(user="u9", score=1.5),)))
    assert out == {"similarUserScores": [{"user": "u9", "score": 1.5}]}


def test_ecommerce_seen_events_config():
    """seen_events controls which event types mark items as 'seen'."""
    from incubator_predictionio_tpu.models.ecommerce import (
        DataSourceParams,
        ECommAlgorithmParams,
        ECommerceEngine,
        Query,
    )

    app_id = seed_app("shop3")
    seed_views(app_id)
    engine = ECommerceEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name="shop3")),
        algorithm_params_list=[
            ("ecomm", ECommAlgorithmParams(app_name="shop3", rank=8,
                                           num_iterations=5, lambda_=0.05,
                                           alpha=2.0, seed=5,
                                           seen_events=("buy",))),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    # uA1 only VIEWED items (no buys) -> nothing is "seen" -> viewed items
    # may be recommended again
    r = algo.predict(models[0], Query(user="uA1", num=5))
    viewed = {
        e.target_entity_id for e in Storage.get_events().find(
            app_id=app_id, entity_id="uA1", event_names=["view"])
    }
    assert viewed.intersection({s.item for s in r.item_scores})


def test_warmup_hooks_run_on_template_models():
    """Each template algorithm's warmup must execute cleanly against a
    freshly trained model (the prediction server calls these on deploy)."""
    from incubator_predictionio_tpu.models.similarproduct import (
        ALSAlgorithmParams as SPParams,
        DataSourceParams as SPDS,
        SimilarProductEngine,
    )

    app_id = seed_app("warmapp")
    seed_views(app_id, extra_like=True)
    engine = SimilarProductEngine().apply()
    ep = EngineParams(
        data_source_params=("", SPDS(app_name="warmapp")),
        algorithm_params_list=[
            ("als", SPParams(rank=8, num_iterations=4, seed=3)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    algo.warmup(models[0], max_batch=4)      # must not raise

    from incubator_predictionio_tpu.models.ecommerce import (
        DataSourceParams as EcDS,
        ECommAlgorithmParams,
        ECommerceEngine,
    )

    ec_engine = ECommerceEngine().apply()
    ec_ep = EngineParams(
        data_source_params=("", EcDS(app_name="warmapp")),
        algorithm_params_list=[
            ("ecomm", ECommAlgorithmParams(app_name="warmapp", rank=8,
                                           num_iterations=4,
                                           seed=3)),
        ],
    )
    ec_models = ec_engine.train(RuntimeContext(), ec_ep)
    ec_engine.algorithms(ec_ep)[0].warmup(ec_models[0], max_batch=4)


def test_warmup_recommendation_batched_and_sequence():
    """The two complex warmups: the ALS batched loop must exercise the
    exact power-of-two shapes live traffic compiles, and the SASRec
    warmup must run the transformer forward without touching the store."""
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        Query as RecQuery,
    )
    import incubator_predictionio_tpu.models.recommendation.engine as rec_mod

    app_id = seed_app("warmrec")
    ev = Storage.get_events()
    rng = np.random.default_rng(2)
    for u in range(12):
        for i in rng.choice(20, 5, replace=False):
            ev.insert(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{int(i)}",
                properties=DataMap({"rating": float(1 + int(i) % 5)})),
                app_id)
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
        DataSourceParams as RecDS,
    )

    engine = RecommendationEngine().apply()
    ep = EngineParams(
        data_source_params=("", RecDS(app_name="warmrec")),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=8, num_iterations=3, seed=1)),
        ],
    )
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    calls = []
    orig = algo.batch_predict

    def spy(model, queries):
        calls.append(len(queries))
        return orig(model, queries)

    algo.batch_predict = spy
    algo.warmup(models[0], max_batch=5)
    # size=2 start, cap = next_pow2(5) = 8 → exactly [2, 4, 8]
    assert calls == [2, 4, 8]
    algo.batch_predict = orig
    algo.warmup(models[0], max_batch=0)   # disabled batcher: singleton only

    # sequence: explicit-history warmup, no event-store read
    from incubator_predictionio_tpu.models.sequence.engine import (
        SeqRecAlgorithm,
        SeqRecAlgorithmParams,
        PreparedData as SeqPD,
    )
    import numpy as _np

    seqs = _np.array([[1, 2, 3, 4], [2, 3, 4, 5]], _np.int32)
    from incubator_predictionio_tpu.data.bimap import BiMap

    algo2 = SeqRecAlgorithm(SeqRecAlgorithmParams(
        app_name="warmrec", d_model=8, n_heads=2, n_layers=1, epochs=1))
    pd = SeqPD(sequences=seqs,
               item_bimap=BiMap({f"i{k}": k for k in range(6)}))
    model2 = algo2.train(RuntimeContext(), pd)
    algo2.warmup(model2)                  # must not raise or hit storage
