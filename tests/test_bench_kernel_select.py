"""Measured ALS-kernel selection in the TPU bench child.

The Mosaic availability probe only proves the fused bucket solve
COMPILES; `select_als_kernel` proves it HELPS before the bench commits
its run window to it, and records both single-sweep timings in the
fragment so every driver round carries the on-chip on/off comparison.
"""

import importlib.util
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_select", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tiny_buckets(bench):
    rng = np.random.default_rng(5)
    n = 600

    class _Inter:
        user_idx = rng.integers(0, 40, n).astype(np.int32)
        item_idx = rng.integers(0, 30, n).astype(np.int32)
        values = rng.uniform(1, 5, n).astype(np.float32)
        user_ids = [str(u) for u in range(40)]
        item_ids = [str(i) for i in range(30)]

    u_b, i_b, n_users, n_items, _ = bench.prep_buckets(_Inter)
    return u_b, i_b, n_users, n_items


def test_unavailable_backend_skips_the_probe(bench, monkeypatch):
    from incubator_predictionio_tpu.ops import als
    monkeypatch.setattr(als, "_ALS_KERNEL", "auto")
    use, rows, frag = bench.select_als_kernel(_tiny_buckets(bench))
    assert use is False and rows == 1
    assert frag == {"als_kernel": "unavailable"}


def test_operator_override_recorded_as_disabled(bench, monkeypatch):
    from incubator_predictionio_tpu.ops import als
    monkeypatch.setattr(als, "_ALS_KERNEL", "off")
    use, rows, frag = bench.select_als_kernel(_tiny_buckets(bench))
    assert use is False and rows == 1
    assert frag == {"als_kernel": "disabled"}


def test_kernel_leg_crash_falls_back_to_xla(bench, monkeypatch):
    """A full-shape-only kernel failure must not forfeit the accelerator
    leg: crashed kernel legs are skipped, the XLA timing is kept, and
    the fragment says probe_failed."""
    from incubator_predictionio_tpu.ops import als
    monkeypatch.setattr(als, "_ALS_KERNEL", "on")
    real = als._mixed_run

    def boom(*a, **kw):
        if kw.get("use_kernel"):
            raise RuntimeError("mosaic rejected the full-shape block")
        return real(*a, **kw)

    monkeypatch.setattr(als, "_mixed_run", boom)
    use, rows, frag = bench.select_als_kernel(_tiny_buckets(bench))
    assert use is False and rows == 1
    assert frag["als_kernel"] == "probe_failed"
    assert frag["als_kernel_sweep_xla_s"] > 0


def test_forced_on_measures_both_legs(bench, monkeypatch):
    from incubator_predictionio_tpu.ops import als
    monkeypatch.setattr(als, "_ALS_KERNEL", "on")
    use, rows, frag = bench.select_als_kernel(_tiny_buckets(bench))
    # interpret mode on CPU is never faster than XLA, so the measured
    # choice must fall back — the exact protection this selector exists
    # to provide on hardware
    assert isinstance(use, bool)
    assert rows in (1, 8)
    assert frag["als_kernel"] == ("on" if use else "off")
    assert frag["als_kernel_sweep_xla_s"] > 0
    assert frag["als_kernel_sweep_pallas_r1_s"] > 0
    assert frag["als_kernel_sweep_pallas_r8_s"] > 0
    assert frag["als_kernel_rows"] == rows
