"""Self-tuning serving — the knob controller (obs/knobs.py).

The pins, in the order the ISSUE promises them:

- convergence mechanics on a FakeClock: recall-low climbs the MIPS
  effort, hysteresis gates single noisy windows, cooldown holds a
  stepped knob still, bounds and the capacity guard veto with named
  reasons, one knob steps per evaluation;
- observe-vs-act: observe records the would-step decision and touches
  nothing;
- incident rollback: a breach inside the newest step's cooldown rolls
  the whole vector back to last-known-good as an audited decision,
  then re-arms (streaks cleared, every knob cooled) so a second climb
  + second breach produces a second rollback;
- the audit trail: knob.decision/knob.apply spans under the decision's
  own ``knb-`` trace ID, ``trace_stitch --decisions`` learns knob
  roots and flags family-scoped orphans;
- the fleet seam: ``POST /knobs`` on a REAL worker applies the vector
  without restart (env + scheduler refresh), the front door fans the
  vector to both real workers under the decision's trace;
- GET/POST /knobs on the admin server with recorder/incident
  armed-state; bounded ring; exported pio_knob_* metrics; the lint
  rule's literal env set cannot drift from the registry's.
"""

import json
import logging
import os
import sys
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.obs import knobs as knb_mod
from incubator_predictionio_tpu.obs.knobs import (
    KNOB_ENV_VARS,
    KnobConfig,
    KnobController,
    default_knobs,
    http_knobs_fn,
    local_knobs_fn,
)
from incubator_predictionio_tpu.obs.metrics import Registry
from incubator_predictionio_tpu.obs.recorder import FlightRecorder
from incubator_predictionio_tpu.utils.times import FakeClock

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import trace_stitch  # noqa: E402


# ---------------------------------------------------------------------------
# harness: a planted flight recorder (real Registry + FlightRecorder on
# a fake clock — the controller reads exactly the window API production
# reads) and a spied local actuator
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _clean_knob_env():
    """The local actuator writes the REAL process env (that is its
    job); restore every registered knob env afterwards."""
    watched = tuple(KNOB_ENV_VARS) + ("PIO_KNOBS",)
    saved = {e: os.environ.get(e) for e in watched}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def planted_recorder(clock):
    reg = Registry()
    met = {
        "lat": reg.histogram("pio_query_latency_seconds", "x",
                             buckets=(0.05, 0.1, 0.25, 0.5, 1.0)),
        "queue": reg.histogram("pio_serve_queue_wait_seconds", "x",
                               buckets=(0.01, 0.05, 0.1, 0.25)),
        "shed": reg.counter("pio_serve_shed_total", "x"),
        "recall": reg.gauge("pio_serve_mips_recall", "x"),
        "fold": reg.histogram("pio_freshness_fold_seconds", "x",
                              buckets=(0.5, 1.0, 2.0, 5.0)),
        "tail": reg.gauge("pio_mips_tail_size", "x",
                          labels=("engine",)),
        "age": reg.gauge("pio_mips_index_age_seconds", "x"),
    }
    rec = FlightRecorder(registry=reg, hz=1.0, window_s=60.0,
                        clock=clock, wall=clock)
    return rec, met


def plant(rec, clock, met, lat=0.2, recall=0.97, samples=3):
    """Write a steady window: per-interval latency observations + the
    recall gauge, one recorder sample per simulated second.

    ``lat=0.2`` is the NEUTRAL point on the planted bucket grid: its
    per-interval p99 (~0.248s) sits under the 0.25s objective but well
    above the 0.25*objective headroom deadband, so no latency rule
    (tighten OR relax) fires and only the planted recall signal moves
    knobs."""
    met["recall"].set(recall)
    for _ in range(samples):
        met["lat"].observe(lat, 50)
        rec.sample_now()
        clock.advance(1.0)


def make_knobs(clock, rec, hysteresis=2, cooldown=0.0, mode="act",
               ring=64, **kw):
    applies = []
    local = local_knobs_fn()

    def spy_apply(vector):
        applies.append(dict(vector))
        return local(vector)

    ctl = KnobController(
        specs=kw.pop("specs", None),
        apply_fn=kw.pop("apply_fn", spy_apply),
        capacity_fn=kw.pop("capacity_fn", None),
        recorder_fn=lambda: rec,
        config=KnobConfig(interval_s=0.05, hysteresis_evals=hysteresis,
                          cooldown_s=cooldown, window_s=30.0,
                          ring=ring),
        clock=clock, mode=mode, **kw)
    return ctl, applies


# ---------------------------------------------------------------------------
# the registry itself
# ---------------------------------------------------------------------------

def test_knob_env_set_matches_the_lint_rules_literal_copy():
    """analysis/rules.py carries a literal copy of KNOB_ENV_VARS (a
    rule must not import the runtime it audits) — pin the two sets so a
    knob added to the registry cannot silently escape the audit."""
    from incubator_predictionio_tpu.analysis import rules

    assert set(rules._KNOB_ENV_VARS) == set(KNOB_ENV_VARS)
    # and the registry's specs cover exactly the declared env surface
    assert {s.env for s in default_knobs()} == set(KNOB_ENV_VARS)


def test_spec_step_is_bounded_pow2_and_binary_toggle():
    nprobe = default_knobs()[0]
    assert nprobe.step(64, 1) == 128
    assert nprobe.step(64, -1) == 32
    assert nprobe.step(nprobe.hi, 1) == nprobe.hi        # clamped
    assert nprobe.step(nprobe.lo, -1) == nprobe.lo
    shed = [s for s in default_knobs() if s.scale == "binary"][0]
    assert shed.step(0, 1) == 1
    assert shed.step(1, -1) == 0


def test_tail_high_tightens_the_rebuild_trigger():
    """A tail sustained above the rebuild trigger means fold-in outruns
    the rebuild cadence — the controller tightens the trigger one rung
    through the audited seam (the daemon only ever READS this env)."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1)
    met["tail"].labels(engine="recommendation").set(9000.0)
    plant(rec, clock, met, recall=0.97)
    d = ctl.evaluate_once()
    assert (d["knob"], d["action"], d["reason"]) == \
        ("mips_rebuild_tail", "step_down", "tail_high")
    assert (d["from"], d["to"]) == (4096, 2048)
    assert applies[0]["PIO_MIPS_REBUILD_TAIL"] == 2048
    assert os.environ["PIO_MIPS_REBUILD_TAIL"] == "2048"
    # ...and the daemon's trigger reader sees the step immediately
    from incubator_predictionio_tpu.ops import mips_daemon

    assert mips_daemon.tail_trigger_rows() == 2048


def test_stale_index_tightens_the_age_trigger():
    """An index aging past its own trigger while a tail keeps arriving:
    the cadence is too loose (or the daemon is drowning) — tighten.
    The worst reading across the fleet (max) is what counts."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1)
    met["age"].set(3000.0)
    met["tail"].labels(engine="recommendation").set(10.0)  # < trigger
    plant(rec, clock, met, recall=0.97)
    d = ctl.evaluate_once()
    assert (d["knob"], d["action"], d["reason"]) == \
        ("mips_rebuild_age_s", "step_down", "index_stale")
    assert (d["from"], d["to"]) == (900, 450)
    assert os.environ["PIO_MIPS_REBUILD_AGE_S"] == "450"


def test_recall_sag_climbs_pq_m_one_rung():
    """PQ subquantizer count defends the recall floor only (a BUILD
    time knob: the step lands at the next daemon rebuild); it never
    trades recall away for latency on its own."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    pq_m = [s for s in default_knobs() if s.name == "mips_pq_m"]
    ctl, applies = make_knobs(clock, rec, hysteresis=1, specs=pq_m)
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert (d["knob"], d["action"], d["reason"]) == \
        ("mips_pq_m", "step_up", "recall_low")
    assert (d["from"], d["to"]) == (16, 32)
    assert os.environ["PIO_SERVE_MIPS_PQ_M"] == "32"
    # a latency breach with healthy recall never shrinks M
    plant(rec, clock, met, lat=0.6, recall=0.97)
    assert ctl.evaluate_once()["action"] == "none"


# ---------------------------------------------------------------------------
# convergence / hysteresis / cooldown / bounds / capacity
# ---------------------------------------------------------------------------

def test_healthy_window_never_steps():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec)
    plant(rec, clock, met, recall=0.97)
    for _ in range(5):
        d = ctl.evaluate_once()
        assert d["action"] == "none"
        assert d["reason"] == "healthy"
    assert applies == []
    assert ctl.stats()["adjustments"] == 0


def test_no_data_is_a_skip_not_a_step():
    clock = FakeClock(100.0)
    rec, _met = planted_recorder(clock)           # zero samples
    ctl, applies = make_knobs(clock, rec)
    d = ctl.evaluate_once()
    assert d["reason"] == "no_data"
    assert applies == []


def test_recall_low_climbs_nprobe_behind_hysteresis():
    """The convergence opening move: a recall sag desires +1 on the
    MIPS knobs; hysteresis eats the first window, the second steps
    mips_nprobe one pow2 rung through the audited seam."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec)
    plant(rec, clock, met, recall=0.80)
    d1 = ctl.evaluate_once()
    assert d1["action"] == "none"
    assert d1["reason"] == "hysteresis"
    assert d1["knobs"]["mips_nprobe"] == {
        "value": 64, "desire": 1, "why": "recall_low", "streak": 1,
        "gate": "hysteresis"}
    assert applies == []
    d2 = ctl.evaluate_once()
    assert d2["knob"] == "mips_nprobe"
    assert d2["action"] == "step_up"
    assert d2["reason"] == "recall_low"
    assert (d2["from"], d2["to"]) == (64, 128)
    assert d2["outcome"]["actuated"] is True
    assert d2["outcome"]["apply"]["ok"] is True
    # the actuator pushed the FULL vector (rollback consistency), and
    # the call-time env seam took it live
    assert applies == [{**ctl.values()}]
    assert applies[0]["PIO_SERVE_MIPS_NPROBE"] == 128
    assert os.environ["PIO_SERVE_MIPS_NPROBE"] == "128"
    assert knb_mod._VALUE.labels(knob="mips_nprobe").value == 128.0
    # recovery converges: recall back over target = healthy, no flap
    plant(rec, clock, met, recall=0.97)
    assert ctl.evaluate_once()["reason"] == "healthy"
    assert ctl.values()["PIO_SERVE_MIPS_NPROBE"] == 128


def test_cooldown_holds_a_stepped_knob_still():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    # single-spec registry: otherwise mips_candidates (same desire,
    # not cooling) would rightly take the next step — coordinate
    # descent — and mask the cooldown gate this test pins
    ctl, applies = make_knobs(clock, rec, cooldown=120.0,
                              specs=default_knobs()[:1])
    plant(rec, clock, met, recall=0.80)
    ctl.evaluate_once()                            # hysteresis
    assert ctl.evaluate_once()["action"] == "step_up"   # 64 -> 128
    # the sag persists: streak rebuilds, then cooldown gates the step
    ctl.evaluate_once()                            # streak 1 again
    d = ctl.evaluate_once()
    assert d["reason"] == "cooldown"
    assert d["knobs"]["mips_nprobe"]["gate"] == "cooldown"
    assert d["knobs"]["mips_nprobe"]["cooldownRemainingS"] > 0
    assert len(applies) == 1
    clock.advance(121.0)
    d = ctl.evaluate_once()                        # cooldown expired
    assert (d["knob"], d["from"], d["to"]) == ("mips_nprobe", 128, 256)
    assert len(applies) == 2


def test_bound_gate_never_saturates_silently(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_MIPS_NPROBE", "4096")   # at hi
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1,
                              specs=default_knobs()[:1])
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert d["action"] == "none"
    assert d["reason"] == "bound"
    assert d["knobs"]["mips_nprobe"]["gate"] == "bound"
    assert applies == []


def test_capacity_guard_vetoes_per_knob():
    """A fitted ceiling below the proposed step vetoes THAT knob with
    gate="capacity"; an unguarded sibling still steps — the guard is
    per-knob, not a global freeze."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1,
                              capacity_fn=lambda: {"mips_nprobe": 100})
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert d["knobs"]["mips_nprobe"]["gate"] == "capacity"
    assert d["knobs"]["mips_nprobe"]["capacityMax"] == 100
    # the candidate pool (same desire, no ceiling) took the step
    assert d["knob"] == "mips_candidates"
    assert d["action"] == "step_up"
    assert ctl.stats()["actuators"]["capacityGuard"] is True
    # everything capacity-gated -> reason="capacity" (runbook: add
    # chips, the knob cannot climb its way out)
    ctl2, applies2 = make_knobs(
        clock, rec, hysteresis=1, specs=default_knobs()[:1],
        capacity_fn=lambda: {"mips_nprobe": 100})
    d = ctl2.evaluate_once()
    assert d["reason"] == "capacity"
    assert applies2 == []


def test_one_knob_steps_per_evaluation():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1)
    plant(rec, clock, met, recall=0.80)            # both MIPS knobs +1
    d = ctl.evaluate_once()
    assert d["knob"] == "mips_nprobe"              # registry priority
    assert d["knobs"]["mips_nprobe"]["gate"] == "selected"
    assert d["knobs"]["mips_candidates"]["gate"] == "queued"
    assert len(applies) == 1


def test_observe_mode_is_a_dry_run_and_act_resumes():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, mode="observe")
    plant(rec, clock, met, recall=0.80)
    ctl.evaluate_once()
    d = ctl.evaluate_once()
    assert d["action"] == "step_up"                # WOULD have stepped
    assert d["outcome"] == {"actuated": False, "dryRun": True}
    assert applies == []
    assert os.environ.get("PIO_SERVE_MIPS_NPROBE") is None
    # the live flip (admin POST /knobs): the sustained desire acts on
    # the very next evaluation — observe never reset the streak
    ctl.set_mode("act")
    d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is True
    assert len(applies) == 1
    # both the flip and the step are in the ring
    kinds = [r.get("kind") for r in ctl.decisions(limit=8)]
    assert "mode_change" in kinds


def test_apply_failure_keeps_the_old_vector_authoritative():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)

    def bad_apply(vector):
        raise RuntimeError("fan-out died")

    ctl, _ = make_knobs(clock, rec, hysteresis=1, apply_fn=bad_apply)
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is False
    assert d["outcome"]["apply"]["ok"] is False
    assert ctl.values()["PIO_SERVE_MIPS_NPROBE"] == 64   # belief held
    # a step that never landed must not arm the rollback window
    assert ctl.stats()["rollbackArmed"] is False


def test_fleet_200_with_failures_is_an_apply_failure():
    """The front door's fleet /knobs answers HTTP 200 even when
    workers fail or reject the vector — the real outcome lives in the
    body's 'failed' list and 'applied' count. The controller must
    read it: a partial fan-out is a split fleet, so belief, the
    rollback baseline, cooldown and the applied-steps counters all
    hold, and the very next evaluation re-proposes the same step."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    responses = [
        # one worker explicitly failed
        {"workers": 2, "applied": 1, "failed": ["w1"], "results": {}},
        # coverage short of the fleet even with an empty failed list
        {"workers": 2, "applied": 1, "failed": [], "results": {}},
        # full coverage: the real success shape
        {"workers": 2, "applied": 2, "failed": [], "results": {}},
    ]
    applies = []

    def fleet_apply(vector):
        applies.append(dict(vector))
        return responses[len(applies) - 1]

    ctl, _ = make_knobs(clock, rec, hysteresis=1, cooldown=120.0,
                        apply_fn=fleet_apply)
    metric_before = knb_mod._ADJUSTMENTS.labels(knob="mips_nprobe").value
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is False
    assert d["outcome"]["apply"]["ok"] is False
    assert d["outcome"]["apply"]["failed"] == ["w1"]
    assert ctl.values()["PIO_SERVE_MIPS_NPROBE"] == 64   # belief held
    st = ctl.stats()
    assert st["adjustments"] == 0                        # not counted
    assert st["rollbackArmed"] is False                  # no baseline
    assert knb_mod._ADJUSTMENTS.labels(knob="mips_nprobe").value \
        == metric_before
    assert knb_mod._VALUE.labels(knob="mips_nprobe").value == 64.0
    # NO cooldown from the failed attempt: the next pass re-proposes
    # immediately (applied < workers is also a failure) …
    d2 = ctl.evaluate_once()
    assert (d2["knob"], d2["to"]) == ("mips_nprobe", 128)
    assert d2["outcome"]["actuated"] is False
    # … and the first full-coverage fan-out commits the belief
    d3 = ctl.evaluate_once()
    assert d3["outcome"]["actuated"] is True
    assert d3["outcome"]["apply"]["ok"] is True
    assert ctl.values()["PIO_SERVE_MIPS_NPROBE"] == 128
    st = ctl.stats()
    assert st["adjustments"] == 1
    assert st["rollbackArmed"] is True
    assert knb_mod._ADJUSTMENTS.labels(knob="mips_nprobe").value \
        == metric_before + 1


# ---------------------------------------------------------------------------
# incident rollback
# ---------------------------------------------------------------------------

def _climb_once(ctl, rec, clock, met):
    plant(rec, clock, met, recall=0.80)
    d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is True
    return d


def test_breach_inside_cooldown_rolls_back_and_rearms():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1, cooldown=120.0)
    step1 = _climb_once(ctl, rec, clock, met)      # nprobe 64 -> 128
    assert ctl.stats()["rollbackArmed"] is True
    before = knb_mod._ROLLBACKS.value
    # the SLO engine's breach listener fires INSIDE the cooldown
    ctl.on_breach({"name": "serve_p99", "objective": {}})
    assert ctl.stats()["rollbackPending"] is True
    d = ctl.evaluate_once()                        # the audited rollback
    assert d["action"] == "rollback"
    assert d["reason"] == "incident"
    assert d["incident"] == {"slo": "serve_p99",
                             "steppedBy": step1["id"]}
    assert d["toVector"]["PIO_SERVE_MIPS_NPROBE"] == 64
    assert d["outcome"]["actuated"] is True
    assert applies[-1]["PIO_SERVE_MIPS_NPROBE"] == 64
    assert os.environ["PIO_SERVE_MIPS_NPROBE"] == "64"
    assert knb_mod._ROLLBACKS.value == before + 1
    st = ctl.stats()
    assert st["rollbacks"] == 1
    assert st["rollbackPending"] is False
    assert st["rollbackArmed"] is False
    # re-arm: every knob cooled down; past the cooldown the climb
    # restarts from scratch, and a second breach inside the SECOND
    # step's cooldown rolls back again
    assert ctl.evaluate_once()["reason"] == "cooldown"
    clock.advance(121.0)
    step2 = _climb_once(ctl, rec, clock, met)
    assert (step2["from"], step2["to"]) == (64, 128)
    ctl.on_breach({"name": "serve_p99"})
    d = ctl.evaluate_once()
    assert d["action"] == "rollback"
    assert ctl.stats()["rollbacks"] == 2
    assert os.environ["PIO_SERVE_MIPS_NPROBE"] == "64"


def test_breach_outside_cooldown_is_ignored():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, hysteresis=1, cooldown=30.0)
    _climb_once(ctl, rec, clock, met)
    clock.advance(31.0)                            # cooldown expired
    ctl.on_breach({"name": "serve_p99"})
    assert ctl.stats()["rollbackPending"] is False
    # and a breach with no step at all is a no-op too
    ctl2, _ = make_knobs(clock, rec, hysteresis=1)
    ctl2.on_breach({"name": "serve_p99"})
    assert ctl2.stats()["rollbackPending"] is False


def test_failed_rollback_stays_pending_and_counts_once():
    """A fan-out failure during the rollback itself leaves the
    rollback PENDING (the fleet is on a known-bad vector — the next
    tick must retry, not abandon), and the rollback counters advance
    only when the restore actually lands — one rollback, however many
    attempts it took."""
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    local = local_knobs_fn()
    failing = {"on": False}

    def flaky_apply(vector):
        if failing["on"]:
            raise RuntimeError("fan-out died")
        return local(vector)

    ctl, _ = make_knobs(clock, rec, hysteresis=1, cooldown=120.0,
                        apply_fn=flaky_apply)
    _climb_once(ctl, rec, clock, met)              # nprobe 64 -> 128
    before = knb_mod._ROLLBACKS.value
    ctl.on_breach({"name": "serve_p99"})
    failing["on"] = True
    d = ctl.evaluate_once()
    assert d["action"] == "rollback"
    assert d["outcome"]["actuated"] is False
    st = ctl.stats()
    assert st["rollbackPending"] is True           # retried next tick
    assert st["rollbacks"] == 0                    # attempt ≠ rollback
    assert knb_mod._ROLLBACKS.value == before
    failing["on"] = False
    d = ctl.evaluate_once()                        # the retry lands
    assert d["action"] == "rollback"
    assert d["outcome"]["actuated"] is True
    assert os.environ["PIO_SERVE_MIPS_NPROBE"] == "64"
    st = ctl.stats()
    assert st["rollbackPending"] is False
    assert st["rollbacks"] == 1
    assert knb_mod._ROLLBACKS.value == before + 1


def test_rollback_in_observe_mode_is_a_dry_run():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, applies = make_knobs(clock, rec, hysteresis=1, cooldown=120.0)
    _climb_once(ctl, rec, clock, met)
    ctl.on_breach({"name": "serve_p99"})
    ctl.set_mode("observe")
    d = ctl.evaluate_once()
    assert d["action"] == "rollback"
    assert d["outcome"] == {"actuated": False, "dryRun": True}
    assert len(applies) == 1                       # only the step
    assert ctl.stats()["rollbackPending"] is False


def test_incident_bundle_carries_the_knob_ring(tmp_path):
    """IncidentCapture's knobs_fn seam: a frozen bundle records the
    knob decisions that preceded the breach."""
    from incubator_predictionio_tpu.obs.controller import export_ring_fn
    from incubator_predictionio_tpu.obs.recorder import IncidentCapture

    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, hysteresis=1)
    _climb_once(ctl, rec, clock, met)
    cap = IncidentCapture(directory=str(tmp_path), recorder=rec,
                          window_s=60.0, clock=clock, wall=clock,
                          knobs_fn=export_ring_fn(ctl))
    path = cap.capture_now("serve_p99")["path"]
    bundle = json.loads((tmp_path / os.path.basename(path)).read_text())
    assert bundle["knobsTotal"] >= 1               # the step decision
    actions = [d["action"] for d in bundle["knobs"]]
    assert "step_up" in actions


# ---------------------------------------------------------------------------
# the audit trail: spans + the stitcher
# ---------------------------------------------------------------------------

def _captured_spans(caplog):
    return [json.loads(r.getMessage()) for r in caplog.records
            if r.name == "pio.trace"]


def test_apply_spans_land_under_the_decision_trace(caplog):
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, hysteresis=1)
    plant(rec, clock, met, recall=0.80)
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        d = ctl.evaluate_once()
    assert d["outcome"]["actuated"] is True
    assert d["traceId"].startswith("knb-")
    spans = [s for s in _captured_spans(caplog)
             if str(s.get("span", "")).startswith("knob.")]
    by_name = {s["span"]: s for s in spans}
    assert set(by_name) == {"knob.decision", "knob.apply"}
    root = by_name["knob.decision"]
    assert root["traceId"] == d["traceId"]
    assert root["spanId"] == d["spanId"]
    assert root["decisionId"] == d["id"]
    assert root["knob"] == "mips_nprobe"
    assert by_name["knob.apply"]["traceId"] == d["traceId"]
    assert by_name["knob.apply"]["parentSpanId"] == root["spanId"]


def test_trace_stitch_learns_knob_decision_roots(tmp_path, caplog,
                                                 capsys):
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, hysteresis=1)
    plant(rec, clock, met, recall=0.80)
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        d = ctl.evaluate_once()
    log = tmp_path / "spans.log"
    log.write_text("\n".join(
        r.getMessage() for r in caplog.records if r.name == "pio.trace")
        + "\n")
    assert trace_stitch.main([str(log), "--decisions"]) == 0
    out = capsys.readouterr().out
    assert f"decision #{d['id']}" in out
    assert "knob=mips_nprobe" in out
    assert "knob.apply" in out
    assert d["traceId"] in out


def test_trace_stitch_orphan_knob_span_exits_1(tmp_path, capsys):
    log = tmp_path / "orphan.log"
    log.write_text(json.dumps({
        "span": "knob.apply", "traceId": "knb-orphan",
        "spanId": "ab12cd34", "ts": 1000.0, "durationMs": 5.0,
    }) + "\n")
    assert trace_stitch.main([str(log), "--decisions"]) == 1
    err = capsys.readouterr().err
    assert "ORPHAN ACTUATION" in err
    assert "knb-orphan" in err


def test_trace_stitch_orphans_are_family_scoped(tmp_path, capsys):
    """A controller.decision root does NOT sanction a knob.* span in
    the same trace — each family needs its own decision root."""
    log = tmp_path / "mixed.log"
    log.write_text("\n".join(json.dumps(s) for s in (
        {"span": "controller.decision", "traceId": "ctl-x",
         "spanId": "aa00", "ts": 1000.0, "durationMs": 1.0,
         "decisionId": 1, "action": "retrain+reload", "reason": "r"},
        {"span": "knob.apply", "traceId": "ctl-x", "spanId": "bb11",
         "ts": 1000.5, "durationMs": 1.0},
    )) + "\n")
    assert trace_stitch.main([str(log), "--decisions"]) == 1
    assert "knob.apply" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the ring + metrics
# ---------------------------------------------------------------------------

def test_decision_ring_is_bounded_and_newest_first():
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, ring=16)
    plant(rec, clock, met)                         # healthy
    for _ in range(40):
        ctl.evaluate_once()
    ds = ctl.decisions(limit=1000)
    assert len(ds) == 16
    assert ds[0]["id"] > ds[-1]["id"]


def test_knob_metrics_exported():
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    ctl, _ = make_knobs(clock, rec, hysteresis=1)
    before = knb_mod._EVALUATIONS.value
    adj_before = knb_mod._ADJUSTMENTS.labels(knob="mips_nprobe").value
    plant(rec, clock, met, recall=0.80)
    ctl.evaluate_once()
    assert knb_mod._EVALUATIONS.value == before + 1
    assert knb_mod._ADJUSTMENTS.labels(knob="mips_nprobe").value == \
        adj_before + 1
    text = obs_metrics.REGISTRY.expose()
    for name in ("pio_knob_evaluations_total",
                 "pio_knob_adjustments_total",
                 "pio_knob_rollbacks_total",
                 "pio_knob_value"):
        assert name in text


# ---------------------------------------------------------------------------
# the fleet seam: a REAL worker's POST /knobs, fanned by the front door
# ---------------------------------------------------------------------------

@pytest.fixture
def served_workers():
    """Memory storage + trained engine + TWO real prediction servers —
    the fleet the knob fan-out must reach."""
    from fake_engine import AP, make_engine, params
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.workflow import CoreWorkflow

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    engine = make_engine()
    CoreWorkflow.run_train(engine, params(ds=9, algos=[("algo0", AP(1))]),
                           engine_variant="knobs")
    servers = []
    ports = []
    for _ in range(2):
        ps = PredictionServer(engine, ServerConfig(
            ip="127.0.0.1", port=0, engine_variant="knobs"))
        servers.append(ps)
        ports.append(ps.start_background())
    yield servers, ports
    for ps in servers:
        ps.stop()
    Storage.reset()


def _post_json(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_worker_knobs_route_applies_without_restart(served_workers):
    _servers, ports = served_workers
    port = ports[0]
    # the worker announces knob support in its /status scheduler block
    status, info = _get_json(port, "/")
    assert info["scheduler"]["knobs"]["supported"] is True
    status, body = _post_json(port, "/knobs", {"values": {
        "PIO_SERVE_MAX_BATCH": 64, "PIO_SERVE_MAX_WAIT_MS": 50}})
    assert status == 200
    assert body["applied"] == {"PIO_SERVE_MAX_BATCH": 64,
                               "PIO_SERVE_MAX_WAIT_MS": 50}
    # the scheduler refreshed live (call-time env + apply_knobs)
    assert body["scheduler"]["cap"] == 64
    assert body["scheduler"]["waitBoundS"] == pytest.approx(0.05)
    assert os.environ["PIO_SERVE_MAX_BATCH"] == "64"
    # an unregistered env rejects the WHOLE vector
    status, body = _post_json(port, "/knobs", {"values": {
        "PIO_SERVE_MAX_BATCH": 32, "PIO_EVIL": 1}})
    assert status == 400
    assert body["unknown"] == ["PIO_EVIL"]
    assert os.environ["PIO_SERVE_MAX_BATCH"] == "64"   # untouched
    # malformed body -> 400, not a crash
    assert _post_json(port, "/knobs",
                      {"values": {"PIO_SERVE_MAX_BATCH": "lots"}})[0] \
        == 400


def test_mips_lifecycle_knobs_roundtrip_the_worker_seam(served_workers):
    """Act-mode round trip for the PQ/daemon knobs: POST /knobs on a
    REAL worker applies the vector, and the call-time readers the
    rebuild daemon and the PQ build path use see the applied values
    with no restart."""
    from incubator_predictionio_tpu.ops import mips_daemon

    _servers, ports = served_workers
    vector = {"PIO_SERVE_MIPS_PQ_M": 8,
              "PIO_SERVE_MIPS_PQ_CANDIDATES": 4096,
              "PIO_MIPS_REBUILD_TAIL": 1024,
              "PIO_MIPS_REBUILD_AGE_S": 300}
    status, body = _post_json(ports[0], "/knobs", {"values": vector})
    assert status == 200
    assert body["applied"] == vector
    for env, want in vector.items():
        assert os.environ[env] == str(want)
    assert mips_daemon.tail_trigger_rows() == 1024
    assert mips_daemon.age_trigger_s() == 300.0
    from incubator_predictionio_tpu.ops import mips as mips_mod

    assert mips_mod._pq_m(32) == 8


def test_frontdoor_fans_the_vector_to_both_real_workers(
        served_workers, caplog):
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )

    _servers, ports = served_workers
    fd = FrontDoor([("127.0.0.1", p) for p in ports],
                   FrontDoorConfig(probe_interval_s=0.2))
    fport = fd.start_background()
    try:
        clock = FakeClock(100.0)
        rec, met = planted_recorder(clock)
        ctl, _ = make_knobs(
            clock, rec, hysteresis=1,
            apply_fn=http_knobs_fn(f"http://127.0.0.1:{fport}/knobs"))
        plant(rec, clock, met, recall=0.80)
        with caplog.at_level(logging.INFO, logger="pio.trace"):
            d = ctl.evaluate_once()
        assert d["outcome"]["actuated"] is True
        result = d["outcome"]["apply"]["result"]
        assert result["workers"] == 2
        assert result["applied"] == 2
        assert result["failed"] == []
        # every worker applied the full vector and refreshed its
        # scheduler — the per-worker result carries the proof
        for res in result["results"].values():
            assert res["applied"]["PIO_SERVE_MIPS_NPROBE"] == 128
            assert res["scheduler"]["cap"] == 512
        # the decision's trace crossed the door onto both workers:
        # the door's /knobs hop + each worker's /knobs hop all carry
        # the knb- trace ID
        hops = [s for s in _captured_spans(caplog)
                if s.get("traceId") == d["traceId"]
                and s.get("route") == "/knobs"]
        servers = {s.get("server") for s in hops}
        assert "frontdoor" in servers
        assert len(hops) >= 3                      # door + 2 workers
    finally:
        fd.stop()


def test_local_fallback_in_act_mode_warns_and_names_its_scope(caplog):
    """PIO_KNOBS=act with PIO_KNOBS_URL unset tunes only the admin
    process's own env — the factory warns loudly at wire-up and
    stats() names the actuator scope, so one status call shows
    whether adjustments ever leave the process."""
    saved_url = os.environ.pop("PIO_KNOBS_URL", None)
    knb_mod.reset_knob_controller()
    os.environ["PIO_KNOBS"] = "act"
    try:
        with caplog.at_level(logging.WARNING,
                             logger="incubator_predictionio_tpu"
                                    ".obs.knobs"):
            ctl = knb_mod.get_knob_controller()
        assert ctl.stats()["actuators"]["scope"] == "local"
        assert any("PIO_KNOBS_URL" in r.getMessage()
                   for r in caplog.records)
        # with the URL set, the scope is the fleet and nothing warns
        knb_mod.reset_knob_controller()
        caplog.clear()
        os.environ["PIO_KNOBS_URL"] = "http://127.0.0.1:1/knobs"
        ctl = knb_mod.get_knob_controller()
        assert ctl.stats()["actuators"]["scope"] == "fleet"
        assert not any("PIO_KNOBS_URL" in r.getMessage()
                       for r in caplog.records)
    finally:
        knb_mod.reset_knob_controller()
        if saved_url is None:
            os.environ.pop("PIO_KNOBS_URL", None)
        else:
            os.environ["PIO_KNOBS_URL"] = saved_url


# ---------------------------------------------------------------------------
# admin hosting: GET/POST /knobs + armed-state
# ---------------------------------------------------------------------------

@pytest.fixture
def admin_with_knobs():
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.servers.admin import AdminServer

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    clock = FakeClock(100.0)
    rec, met = planted_recorder(clock)
    # a long interval: the admin-hosted loop evaluates once at start,
    # then the tests drive evaluate_once explicitly
    applies = []

    def spy(vector):
        applies.append(dict(vector))
        return {"ok": True}

    ctl = KnobController(
        apply_fn=spy, recorder_fn=lambda: rec,
        config=KnobConfig(interval_s=60.0, hysteresis_evals=2,
                          cooldown_s=0.0, ring=64),
        clock=clock, mode="observe")
    ad = AdminServer(ip="127.0.0.1", port=0, knobs=ctl)
    port = ad.start_background()
    try:
        yield {"port": port, "ctl": ctl, "rec": rec, "met": met,
               "clock": clock, "applies": applies}
    finally:
        ad.stop()
        knb_mod.reset_knob_controller()
        from incubator_predictionio_tpu.obs.controller import (
            reset_controller,
        )

        reset_controller()
        Storage.reset()


def test_knobs_routes_on_admin(admin_with_knobs):
    port = admin_with_knobs["port"]
    plant(admin_with_knobs["rec"], admin_with_knobs["clock"],
          admin_with_knobs["met"], recall=0.80)
    admin_with_knobs["ctl"].evaluate_once()
    status, body = _get_json(port, "/knobs?limit=10")
    assert status == 200
    assert body["mode"] == "observe"
    assert body["running"] is True         # the admin started the loop
    assert body["values"]["PIO_SERVE_MIPS_NPROBE"] == 64
    assert body["knobs"]["mips_nprobe"]["env"] == \
        "PIO_SERVE_MIPS_NPROBE"
    # the armed-state rides both controllers' GET responses
    assert set(body["recorder"]) == {"armed", "samples"}
    assert set(body["incident"]) == {"armed", "directory"}
    decisions = body["decisions"]
    assert decisions and decisions[0]["kind"] == "evaluation"
    assert decisions[0]["traceId"].startswith("knb-")
    status, cbody = _get_json(port, "/controller")
    assert "recorder" in cbody and "incident" in cbody
    # the LIVE mode flip
    status, body = _post_json(port, "/knobs", {"mode": "act"})
    assert status == 200 and body["mode"] == "act"
    assert _post_json(port, "/knobs", {"mode": "sideways"})[0] == 400
    status, body = _get_json(port, "/knobs")
    assert body["mode"] == "act"
    assert any(d.get("kind") == "mode_change" and d["to"] == "act"
               for d in body["decisions"])
