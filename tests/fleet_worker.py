"""Worker program for the fleet tests and the fleet serving bench.

Run as a REAL separate process by tests/test_federation.py and by
``bench.py bench_fleet`` / ``bench_frontdoor``:

- ``--mode metrics``: an HttpServer exposing ``GET /metrics`` from its
  own process registry, with a planted query-latency histogram and
  queue-depth gauge — one "serving worker" for the admin's
  ``GET /federate`` to scrape.
- ``--mode storage``: a memory-backed StorageServer with span logging
  enabled — the downstream hop of the cross-process trace test: the
  parent's event server forwards ``X-PIO-Trace-Id``/``X-PIO-Parent-
  Span`` on its storage RPCs, and THIS process's ``pio.trace`` span
  lines (on stderr) must link under the parent's spans.
- ``--mode serve``: a full PredictionServer over a planted ALS model
  (random factors, synthetic catalog), serving ``/queries.json``
  through the continuous-batching scheduler (serving/scheduler.py) with
  the pow2 ladder pre-warmed before the port is announced — one worker
  of the ``bench_fleet`` / ``bench_frontdoor`` legs. ``/metrics`` on
  the same port exposes ``pio_serve_batch_size`` /
  ``pio_serve_shed_total`` / ``pio_serve_compile_cache_size`` for the
  bench's scrapes, and ``POST /reload`` hot-swaps to a freshly planted
  model through the real warm-before-swap route (what the front door's
  rolling reload drives).

``--compile-cache DIR`` points the persistent XLA compile cache at a
FLEET-SHARED directory (utils/compile_cache.py) before any jax work, so
a joining worker pre-warms its pow2 ladder from disk instead of paying
the cold compile wall — the elasticity story bench_frontdoor measures.

``--chaos SPEC`` arms fault injection (comma-separated; serve mode):

- ``kill-after=S``   — hard-exit the process S seconds after serving
  starts (the in-flight-connection-reset class a crashed worker causes)
- ``stall-after=S``  — after S seconds every dispatch wedges (the
  accepted-but-never-answers class: queue grows, callers time out)
- ``latency-spike=MS:P`` — each dispatch pays +MS ms with probability P
  (tail-latency injection)
- ``refuse-after=S`` — close the listener after S seconds (new
  connections refused; already-open keep-alives keep serving)

Prints ``PORT <n> WARM_S <seconds>`` on stdout once bound (serve mode:
once WARM; WARM_S is the ladder warmup wall — the cold/warm
compile-cache delta the bench records), then serves until stdin closes
(the parent owns the lifetime; no signals needed).
"""

import argparse
import sys


def _parse_chaos(spec: str) -> dict:
    """``--chaos`` grammar → {kill_after_s, stall_after_s, refuse_after_s,
    latency_ms, latency_prob} (absent hooks None)."""
    out = {"kill_after_s": None, "stall_after_s": None,
           "refuse_after_s": None, "latency_ms": None,
           "latency_prob": None}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, value = part.partition("=")
        if name == "kill-after":
            out["kill_after_s"] = float(value)
        elif name == "stall-after":
            out["stall_after_s"] = float(value)
        elif name == "refuse-after":
            out["refuse_after_s"] = float(value)
        elif name == "latency-spike":
            ms, _, prob = value.partition(":")
            out["latency_ms"] = float(ms)
            out["latency_prob"] = float(prob) if prob else 1.0
        else:
            raise ValueError(f"unknown chaos hook {name!r}")
    return out


def _chaos_wrap(handle, chaos: dict, rng, clock):
    """Wrap the scheduler's handle_batch with the dispatch-level chaos
    hooks (stall / latency-spike). ``clock()`` is seconds since serving
    started; process-level hooks (kill/refuse) arm in _serve_worker."""
    import time as _time

    stall_after = chaos.get("stall_after_s")
    latency_ms = chaos.get("latency_ms")
    latency_prob = chaos.get("latency_prob") or 0.0

    def wrapped(bodies, engine, tenant):
        if stall_after is not None and clock() >= stall_after:
            # wedged worker: accepted the work, never answers — the
            # front door's attempt timeout is what rescues the query
            _time.sleep(3600.0)
        if latency_ms is not None and rng.random() < latency_prob:
            _time.sleep(latency_ms / 1000.0)
        return handle(bodies, engine, tenant)

    return wrapped


def _serve_worker(args) -> tuple:
    """Planted-model serving worker → (bound port, ladder warmup wall
    seconds). The port is announced only after warmup: a worker is not
    IN the fleet until it can serve without compiling."""
    import threading
    import time

    import numpy as np

    import jax.numpy as jnp

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.data.storage import EngineInstance
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        Query,
        RecommendationServing,
    )
    from incubator_predictionio_tpu.servers.plugins import PluginContext
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
        _AsyncPoster,
    )
    from incubator_predictionio_tpu.serving.scheduler import BatchScheduler
    from incubator_predictionio_tpu.utils.http import HttpServer
    from incubator_predictionio_tpu.utils.times import now_utc
    from incubator_predictionio_tpu.workflow.workflow import (
        make_runtime_context,
    )

    rng = np.random.default_rng(args.seed)
    n_users, n_items, rank = args.users, args.items, args.rank

    def plant_model(seed: int) -> ALSModel:
        r = np.random.default_rng(seed)
        return ALSModel(
            user_factors=jnp.asarray(
                r.normal(0, 0.3, (n_users, rank)).astype(np.float32)),
            item_factors=jnp.asarray(
                r.normal(0, 0.3, (n_items, rank)).astype(np.float32)),
            user_bimap=BiMap({f"u{i}": i for i in range(n_users)}),
            item_bimap=BiMap({f"i{i}": i for i in range(n_items)}),
            item_years={}, item_categories={},
        )

    model = plant_model(args.seed)
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=rank))
    now = now_utc()
    server = PredictionServer.__new__(PredictionServer)
    # direct state injection (the bench_serving pattern): this worker
    # measures the serving plane, not checkpoint restore
    server.engine = None
    server.config = ServerConfig(ip="127.0.0.1", port=0,
                                 micro_batch=args.max_batch)
    server.plugin_context = PluginContext()
    server.ctx = make_runtime_context(None)
    server._lock = threading.Lock()
    server._reload_lock = threading.Lock()
    server.engine_instance = EngineInstance(
        id="fleet", status="COMPLETED", start_time=now, end_time=now,
        engine_id="fleet", engine_version="1", engine_variant="fleet",
        engine_factory="fleet")
    server.engine_params = None
    server.algorithms = [algo]
    server.serving = RecommendationServing()
    server.models = [model]
    server.start_time = now
    server.request_count = 0
    server.avg_serving_sec = 0.0
    server.last_serving_sec = 0.0
    server.max_batch_served = 0
    server._conf_server_key = None
    server.http = HttpServer(server._build_router(), "127.0.0.1", 0,
                             name="prediction")
    server._speed_overlays = []
    server._deploys = {}
    handle = server._handle_batch
    if args.dispatch_floor_ms > 0:
        # CPU-sim stand-in for an accelerator's fixed per-dispatch wall
        # (compile-cache lookup + launch + result fetch — on a real TPU
        # this floor exists regardless of batch width, and it is WHY
        # fusing a deeper queue into one dispatch wins): pad every
        # dispatch to the floor. time.sleep releases the GIL, so the
        # HTTP plane keeps admitting — queue depth builds exactly as it
        # would behind a busy device.
        import time as _time

        floor_s = args.dispatch_floor_ms / 1000.0
        inner = server._handle_batch

        def handle(bodies, engine, tenant):
            t0 = _time.perf_counter()
            out = inner(bodies, engine, tenant)
            left = floor_s - (_time.perf_counter() - t0)
            if left > 0:
                _time.sleep(left)
            return out

    chaos = _parse_chaos(args.chaos)
    serve_t0 = [None]

    def chaos_clock() -> float:
        return 0.0 if serve_t0[0] is None else \
            time.monotonic() - serve_t0[0]

    if chaos["stall_after_s"] is not None or chaos["latency_ms"] is not None:
        handle = _chaos_wrap(handle, chaos,
                             np.random.default_rng(args.seed + 7),
                             chaos_clock)

    from incubator_predictionio_tpu.servers import (
        prediction_server as ps_mod,
    )
    from incubator_predictionio_tpu.serving import tenancy

    server._batcher = BatchScheduler(
        handle, server.config.micro_batch,
        workers=server.config.serve_workers,
        # same live per-tenant p99 feed the real PredictionServer
        # wires in (one positional param → the scheduler slices the
        # SLO signal by tenant)
        p99_fn=lambda tenant: ps_mod._QUERY_LATENCY.labels(
            tenant=tenancy.get_registry().label(tenant)).quantile(0.99))
    # PIO_TENANTS (bench_tenants sets it in the worker env) → weighted-
    # fair weights + admission quotas pushed into the scheduler, same
    # seam the real server syncs after construction and reloads
    server._sync_tenant_policy()
    # __new__-built server skipped __init__: wire the per-tenant
    # pio_serve_queue_depth scrape collector onto OUR batcher
    server.register_queue_collector()
    server._feedback_poster = _AsyncPoster("feedback")
    server._log_poster = _AsyncPoster("log", workers=1)

    # POST /reload support: the real route runs self.load_models(
    # warm_before_swap=True) under _reload_lock — the planted stand-in
    # re-plants fresh factors, warms the NEW model's ladder while the
    # old one keeps serving (compile-cache hits: same shapes), then
    # swaps under the serving lock. Bumped end_time resets staleness,
    # exactly like a real instance swap.
    reload_seq = [0]

    def load_models(warm_before_swap: bool = False,
                    tenant: str = None) -> None:
        reload_seq[0] += 1
        new_model = plant_model(args.seed + 1000 + reload_seq[0])
        if warm_before_swap:
            algo.warmup(new_model, max_batch=server.config.micro_batch)
        instance = EngineInstance(
            id=f"fleet-r{reload_seq[0]}", status="COMPLETED",
            start_time=now_utc(), end_time=now_utc(),
            engine_id="fleet", engine_version="1",
            engine_variant="fleet", engine_factory="fleet")
        if tenant is not None and tenant != tenancy.DEFAULT_TENANT:
            # tenant-scoped reload: swap ONLY this tenant's co-resident
            # deploy — the shared/default deploy (and every other
            # tenant riding it) keeps serving the old model untouched,
            # which is exactly what bench_tenants' reload stage proves
            if tenancy.get_registry().get(tenant) is None:
                from incubator_predictionio_tpu.utils.http import (
                    HttpError,
                )

                raise HttpError(404, f"Unknown tenant {tenant!r}.")
            with server._lock:
                server._deploys[tenant] = {
                    "engine_instance": instance,
                    "engine_params": None,
                    "algorithms": [algo],
                    "serving": server.serving,
                    "models": [new_model],
                }
            return
        with server._lock:
            server.models = [new_model]
            server.engine_instance = instance

    server.load_models = load_models

    # the staleness gauge the fleet /slo (and the freshness controller
    # behind it) evaluates: the real PredictionServer registers this
    # collector in __init__, which the __new__ state-injection path
    # above bypasses — re-plant it here so a fleet-worker /metrics
    # scrape reports the served instance's age, and the planted
    # /reload's end_time bump resets it exactly like a real hot swap
    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.utils.times import ensure_aware

    staleness_gauge = obs_metrics.REGISTRY.gauge(
        "pio_model_staleness_seconds",
        "seconds since the served engine instance finished training "
        "(scrape-time snapshot)")

    def _collect_staleness() -> None:
        with server._lock:
            instance = server.engine_instance
        if instance is not None:
            staleness_gauge.set(max(
                (now_utc() - ensure_aware(instance.end_time))
                .total_seconds(), 0.0))

    obs_metrics.REGISTRY.register_collector(
        "fleet_worker_staleness", _collect_staleness)

    # pre-warm EVERY pow2 ladder rung (plus the singleton path) so the
    # load ramp measures serving, not XLA compiles — the zero-steady-
    # state-recompile contract starts from here. With a shared
    # persistent compile cache (--compile-cache) the rungs load from
    # disk and this wall collapses — the measured WARM_S delta.
    t_warm = time.perf_counter()
    algo.warmup(model, max_batch=server.config.micro_batch)
    warm_s = time.perf_counter() - t_warm
    port = server.http.start_background()
    serve_t0[0] = time.monotonic()
    # daemon timers: a worker torn down (stdin closed) before its
    # chaos fires must still exit promptly — a pending non-daemon
    # Timer would pin the process until the timer ran
    if chaos["kill_after_s"] is not None:
        import os as _os

        t = threading.Timer(chaos["kill_after_s"],
                            lambda: _os._exit(137))
        t.daemon = True
        t.start()
    if chaos["refuse_after_s"] is not None:
        t = threading.Timer(chaos["refuse_after_s"], server.http.stop)
        t.daemon = True
        t.start()
    return port, warm_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("metrics", "storage", "serve"),
                    required=True)
    ap.add_argument("--observe", default="",
                    help="comma-separated seconds planted into "
                         "pio_query_latency_seconds (metrics mode)")
    ap.add_argument("--depth", type=float, default=0.0,
                    help="pio_serve_queue_depth value (metrics mode)")
    ap.add_argument("--staleness", type=float, default=None,
                    help="pio_model_staleness_seconds value "
                         "(metrics mode)")
    ap.add_argument("--users", type=int, default=2000)
    ap.add_argument("--items", type=int, default=1000)
    ap.add_argument("--rank", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=512,
                    help="scheduler ladder cap (serve mode)")
    ap.add_argument("--dispatch-floor-ms", type=float, default=0.0,
                    help="pad every scheduler dispatch to this wall — "
                         "the CPU sim's stand-in for an accelerator's "
                         "fixed per-dispatch cost (serve mode)")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="fleet-shared persistent XLA compile cache "
                         "directory (serve mode join pre-warm)")
    ap.add_argument("--chaos", default="",
                    help="fault injection: kill-after=S, stall-after=S, "
                         "latency-spike=MS:P, refuse-after=S "
                         "(comma-separated; serve mode)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.compile_cache:
        # before any jax work: the join pre-warm reads compiled rungs
        # from the fleet-shared directory instead of re-compiling
        from incubator_predictionio_tpu.utils import compile_cache

        compile_cache.enable(args.compile_cache)

    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.obs import trace as obs_trace

    obs_trace.enable_span_logging()

    srv = None
    warm_s = 0.0
    if args.mode == "metrics":
        from incubator_predictionio_tpu.obs.http import (
            add_metrics_route,
            add_recorder_route,
        )
        from incubator_predictionio_tpu.utils.http import (
            HttpServer,
            Router,
        )

        h = obs_metrics.REGISTRY.histogram(
            "pio_query_latency_seconds",
            "per-query serving wall")
        for raw in args.observe.split(","):
            raw = raw.strip()
            if raw:
                h.observe(float(raw))
        obs_metrics.REGISTRY.gauge(
            "pio_serve_queue_depth", "micro-batcher backlog").set(
            args.depth)
        if args.staleness is not None:
            obs_metrics.REGISTRY.gauge(
                "pio_model_staleness_seconds",
                "age of the served engine instance").set(args.staleness)
        r = Router()
        add_metrics_route(r)
        add_recorder_route(r)
        srv = HttpServer(r, "127.0.0.1", 0, name="worker")
        port = srv.start_background()
    elif args.mode == "serve":
        port, warm_s = _serve_worker(args)
    else:
        from incubator_predictionio_tpu.data.storage import (
            StorageClientConfig,
        )
        from incubator_predictionio_tpu.data.storage import (
            memory as memory_backend,
        )
        from incubator_predictionio_tpu.data.storage.server import (
            StorageServer,
        )

        config = StorageClientConfig(test=True, properties={})
        client = memory_backend.StorageClient(config)
        srv = StorageServer(memory_backend, client, config,
                            host="127.0.0.1", port=0)
        port = srv.start_background()

    # extra tokens ride behind the port: existing parsers split()[1]
    print(f"PORT {port} WARM_S {warm_s:.3f}", flush=True)
    # serve until the parent closes our stdin (its process exit does)
    sys.stdin.read()
    if srv is not None:
        srv.stop()


if __name__ == "__main__":
    main()
