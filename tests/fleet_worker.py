"""Worker program for the fleet-observability two-process tests.

Run by tests/test_federation.py as a REAL second (and third) process:

- ``--mode metrics``: an HttpServer exposing ``GET /metrics`` from its
  own process registry, with a planted query-latency histogram and
  queue-depth gauge — one "serving worker" for the admin's
  ``GET /federate`` to scrape.
- ``--mode storage``: a memory-backed StorageServer with span logging
  enabled — the downstream hop of the cross-process trace test: the
  parent's event server forwards ``X-PIO-Trace-Id``/``X-PIO-Parent-
  Span`` on its storage RPCs, and THIS process's ``pio.trace`` span
  lines (on stderr) must link under the parent's spans.

Prints ``PORT <n>`` on stdout once bound, then serves until stdin
closes (the parent owns the lifetime; no signals needed).
"""

import argparse
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("metrics", "storage"),
                    required=True)
    ap.add_argument("--observe", default="",
                    help="comma-separated seconds planted into "
                         "pio_query_latency_seconds (metrics mode)")
    ap.add_argument("--depth", type=float, default=0.0,
                    help="pio_serve_queue_depth value (metrics mode)")
    ap.add_argument("--staleness", type=float, default=None,
                    help="pio_model_staleness_seconds value "
                         "(metrics mode)")
    args = ap.parse_args()

    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.obs import trace as obs_trace

    obs_trace.enable_span_logging()

    if args.mode == "metrics":
        from incubator_predictionio_tpu.obs.http import add_metrics_route
        from incubator_predictionio_tpu.utils.http import (
            HttpServer,
            Router,
        )

        h = obs_metrics.REGISTRY.histogram(
            "pio_query_latency_seconds",
            "per-query serving wall")
        for raw in args.observe.split(","):
            raw = raw.strip()
            if raw:
                h.observe(float(raw))
        obs_metrics.REGISTRY.gauge(
            "pio_serve_queue_depth", "micro-batcher backlog").set(
            args.depth)
        if args.staleness is not None:
            obs_metrics.REGISTRY.gauge(
                "pio_model_staleness_seconds",
                "age of the served engine instance").set(args.staleness)
        r = Router()
        add_metrics_route(r)
        srv = HttpServer(r, "127.0.0.1", 0, name="worker")
        port = srv.start_background()
    else:
        from incubator_predictionio_tpu.data.storage import (
            StorageClientConfig,
        )
        from incubator_predictionio_tpu.data.storage import (
            memory as memory_backend,
        )
        from incubator_predictionio_tpu.data.storage.server import (
            StorageServer,
        )

        config = StorageClientConfig(test=True, properties={})
        client = memory_backend.StorageClient(config)
        srv = StorageServer(memory_backend, client, config,
                            host="127.0.0.1", port=0)
        port = srv.start_background()

    print(f"PORT {port}", flush=True)
    # serve until the parent closes our stdin (its process exit does)
    sys.stdin.read()
    srv.stop()


if __name__ == "__main__":
    main()
