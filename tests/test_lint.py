"""pio-lint: per-rule positive/negative fixtures + the repo-wide gate.

Each rule gets a seeded violation (must be detected) and a hazard-free
twin (must stay silent), so a rule that goes blind or trigger-happy
fails here before it rots. The repo-wide test shells out exactly the
way CI and scripts/lint.sh do and is the tier-1 guarantee that the
tree stays clean modulo the checked-in baseline.
"""

import subprocess
import sys
import time
from pathlib import Path

import pytest

from incubator_predictionio_tpu.analysis import (
    ALL_RULES,
    RULES_BY_NAME,
    apply_baseline,
    lint_paths,
    repo_root,
    write_baseline,
)
from incubator_predictionio_tpu.analysis.engine import load_baseline

# (bad source that MUST trigger the rule, good twin that MUST NOT)
FIXTURES = {
    "host-sync": (
        """
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def step(x):
    host = np.asarray(x)
    jax.device_get(x)
    x.block_until_ready()
    return host

@jax.jit
def train_sweeps(state):
    # the per-sweep convergence-check anti-pattern: float() on a traced
    # value forces a device round trip (or TracerError) EVERY sweep
    for _ in range(10):
        state = state * 0.5
        if float(jnp.linalg.norm(state)) < 1e-3:
            break
    return state
""",
        """
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def step(x):
    return x + 1

def fetch(x):
    return np.asarray(jax.device_get(x))

@jax.jit
def sweep_chunk(state):
    # the early-stop probe pattern (ops/retrain.py): the delta is
    # computed IN-trace and returned; the host fetches it outside
    state = state * 0.5
    return state, jnp.linalg.norm(state)

def train(state, tol, budget=10):
    done = 0
    while done < budget:
        state, delta = sweep_chunk(state)
        done += 2
        if float(delta) < tol:  # host sync at the probe boundary only
            break
    return state
""",
    ),
    "neg-gather": (
        """
import jax.numpy as jnp

def warm(prev, row_ids):
    return prev[row_ids]
""",
        """
import jax.numpy as jnp

def warm(prev, row_ids):
    safe_ids = jnp.maximum(row_ids, 0)
    x0 = prev[safe_ids]
    return jnp.where(row_ids[:, None] >= 0, x0, 0.0)
""",
    ),
    "probe-arity": (
        """
import jax

def solve(a: jax.Array, x0: "Optional[jax.Array]" = None,
          yty: "Optional[jax.Array]" = None) -> jax.Array:
    out = a if x0 is None else a + x0
    return out if yty is None else out + yty

def solve_kernel_available():
    return bool(solve(jax.numpy.zeros((2,)), x0=jax.numpy.zeros((2,))))
""",
        """
import jax

def solve(a: jax.Array, x0: "Optional[jax.Array]" = None,
          yty: "Optional[jax.Array]" = None) -> jax.Array:
    out = a if x0 is None else a + x0
    return out if yty is None else out + yty

def solve_kernel_available():
    return bool(solve(jax.numpy.zeros((2,)), x0=jax.numpy.zeros((2,)),
                      yty=jax.numpy.zeros((2,))))
""",
    ),
    "tracer-branch": (
        """
import jax
import jax.numpy as jnp

@jax.jit
def clip(x):
    if jnp.any(x < 0):
        return jnp.zeros_like(x)
    return x
""",
        """
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("training",))
def clip(x, training):
    if training:
        return jnp.where(x < 0, 0.0, x)
    if x is None:
        return x
    return x
""",
    ),
    "env-import": (
        """
import os

CHUNK = int(os.environ.get("PIO_CHUNK", "64"))
""",
        """
import os

def chunk_default():
    return int(os.environ.get("PIO_CHUNK", "64"))
""",
    ),
    "f64": (
        """
import jax.numpy as jnp

def histogram(x):
    return jnp.zeros((4,), jnp.float64)
""",
        """
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

def histogram(x):
    return jnp.zeros((4,), jnp.float64)
""",
    ),
    "wallclock": (
        """
import time
import jax

@jax.jit
def step(x):
    return x * time.time()
""",
        """
import time
import jax

@jax.jit
def step(x):
    return x + 1

def timed_step(x):
    t0 = time.time()
    return step(x), time.time() - t0
""",
    ),
    "lock-native-scan": (
        """
class Events:
    def scan(self, h):
        with self.client.lock:
            raw = self.count(h)
            inter, times = self._scan_native(h, raw)
        return inter
""",
        """
class Events:
    def scan(self, h):
        with self.client.lock:
            raw = self.count(h)
            pin = self.client.pin(h)
        try:
            inter, times = self._scan_native(h, raw)
        finally:
            self.client.unpin(pin)
        return inter

    def helper(self, h):
        with self.client.lock:
            def deferred():
                return self._scan_native(h, 0)
            return deferred
""",
    ),
    "metric-in-trace": (
        """
import jax
from incubator_predictionio_tpu.obs import metrics

QUERIES = metrics.REGISTRY.counter("q_total", "queries")
LAT = metrics.REGISTRY.histogram("q_seconds", "latency")

@jax.jit
def step(x):
    QUERIES.inc()
    LAT.observe(0.1)
    return x + 1
""",
        """
import jax
from incubator_predictionio_tpu.obs import metrics

QUERIES = metrics.REGISTRY.counter("q_total", "queries")

@jax.jit
def step(x, ids):
    return x.at[ids].set(0.0)

def serve(x, ids):
    out = step(x, ids)
    QUERIES.inc()
    return out
""",
    ),
    "host-gather-in-mesh": (
        """
import numpy as np
import jax

def train_loop(mesh, step, xs):
    with mesh:
        out = step(xs)
        host = np.asarray(out)
        ids = out.tolist()
        return jax.device_get(host), ids
""",
        """
import numpy as np
import jax

def train_loop(mesh, step, xs):
    with mesh:
        out = step(xs)

    def fetch(v):
        # a function DEFINED under a mesh elsewhere is not a gather;
        # shard_map-traced bodies are host-sync's jurisdiction
        return np.asarray(v)

    # the sanctioned pattern: one fetch after the mesh context closes
    return fetch(out)
""",
    ),
    "blocking-profiler": (
        """
import jax

class Algo:
    def _score(self, model, query):
        out = model.score(query)
        jax.block_until_ready(out)
        return out

    def predict(self, model, query):
        return self._score(model, query)
""",
        """
import jax
from incubator_predictionio_tpu.obs import profile

class Algo:
    def train(self, ctx, pd):
        # training may block: it is not the serving hot path
        out = pd.run()
        jax.block_until_ready(out)
        return out

    def predict(self, model, query):
        # the sanctioned pattern: env-gated attribution via obs/profile
        t0 = profile.t0()
        out = model.score(query)
        profile.record(t0, "serve", "score", 0.0, out)
        return out
""",
    ),
    "serve-blocking-io": (
        """
from incubator_predictionio_tpu.data.store import EventStore

class Algo:
    def _recent(self, user):
        return list(EventStore.find_by_entity(
            app_name="app", entity_type="user", entity_id=user))

    def predict(self, model, query):
        return self._recent(query.user)
""",
        """
from incubator_predictionio_tpu.data.store import EventStore

class Algo:
    def train(self, ctx, pd):
        # train-time reads are not the serving hot path
        return list(EventStore.find(app_name="app"))

    def predict(self, model, query):
        # serving reads go through the TTL micro-cache's public API;
        # the cache-miss loader lives outside predict's reach
        return self._cache.get_or_load(query.user, _load_recent)
""",
    ),
    "server-state": (
        """
class Handler:
    async def handle(self, request):
        self.count += 1
        self.seen.append(request)
        return self.count
""",
        """
class Handler:
    async def handle(self, request):
        with self._lock:
            self.count += 1
            self.seen.append(request)
        local = 1
        local += 1
        return self.count
""",
    ),
    "unbatched-dispatch": (
        """
from incubator_predictionio_tpu.ops.topk import score_and_top_k

class Server:
    async def handle_query(self, request):
        # direct device dispatch from a request handler: no queue
        # coalescing, no shed policy
        packed = score_and_top_k(self.user_vec, self.item_factors, 10)
        preds = self.algo.predict(self.model, request)
        return packed, preds
""",
        """
import asyncio

class Server:
    async def handle_query(self, request):
        # the sanctioned seam: enqueue, let the scheduler coalesce the
        # in-flight queries into one fused dispatch
        return await asyncio.wrap_future(
            self.batcher.submit(request.body))
""",
    ),
    "exhaustive-scan": (
        """
import jax
from incubator_predictionio_tpu.ops.topk import (
    sharded_top_k,
    top_k_with_exclusions,
)

class Server:
    async def handle_query(self, request):
        # full-table scoring below the MIPS auto-router: even with a
        # registered two-stage index the query pays the linear scan
        scores = self.item_factors @ self.user_vec
        top = jax.lax.top_k(scores, 10)
        packed = sharded_top_k(self.user_vec, self.item_factors, 10)
        return top_k_with_exclusions(scores, 10), packed, top
""",
        """
from incubator_predictionio_tpu.ops.topk import score_and_top_k

class Server:
    async def handle_query(self, request):
        # the sanctioned entry: the auto-router serves two-stage when
        # an index is registered and falls back to exhaustive itself
        return score_and_top_k(self.user_vec, self.item_factors, 10)
""",
    ),
    "unbounded-retry": (
        """
import time

def post_event(conn, body):
    while True:
        try:
            return conn.post(body)
        except ConnectionError:
            # fixed delay, no deadline: every client re-offers the
            # same load in lockstep, forever
            time.sleep(1.0)
""",
        """
import time
from incubator_predictionio_tpu.utils.http import (
    RetryableError,
    RetryPolicy,
)

_POLICY = RetryPolicy(attempts=3, deadline_s=10.0)

def post_event(conn, body):
    def attempt():
        try:
            return conn.post(body)
        except ConnectionError as e:
            raise RetryableError(e) from e
    return _POLICY.call(attempt)

def poll_until_ready(probe, budget_s=10.0):
    # a sleep with a COMPUTED delay in a loop that swallows nothing is
    # a poll, not a retry loop; and backoff expressions stay silent
    delay = 0.05
    for _ in range(int(budget_s / delay)):
        if probe():
            return True
        time.sleep(delay)
    return False
""",
    ),
    "unaudited-actuation": (
        """
class FreshnessController:
    def evaluate_once(self):
        # actuation OUTSIDE the decision-record emitter: the fleet
        # mutates with no audit-ring entry and no trace context
        if self.breached():
            self._retrain_fn()
            self._reload_fn()

    def panic_reload(self, fd):
        fd.rolling_reload(timeout=30)
""",
        """
class FreshnessController:
    def evaluate_once(self):
        if self.breached():
            self._actuate(self.new_decision())

    def _actuate(self, decision):
        # THE emitter: trace context + outcome into the audit ring
        self._retrain_fn()
        self._reload_fn()
        decision["outcome"] = {"actuated": True}


def workflow_retrain_fn(engine, engine_params):
    # actuator FACTORY (*_fn): builds the callable _actuate invokes
    def retrain():
        from incubator_predictionio_tpu.workflow.workflow import (
            CoreWorkflow,
        )

        return CoreWorkflow.run_train(engine, engine_params)

    return retrain
""",
    ),
    "unaudited-knob-write": (
        """
import os


def emergency_widen(scheduler):
    # knob writes OUTSIDE the audited seam: serving behavior mutates
    # with no knob.decision record and nothing to roll back to
    os.environ["PIO_SERVE_MIPS_NPROBE"] = "4096"
    os.environ.setdefault("PIO_SERVE_MAX_WAIT_MS", "1000")
    os.putenv("PIO_SERVE_SHED", "0")
    scheduler.cap = 4096
    scheduler.max_batch = 4096
""",
        """
import os


class KnobController:
    def _apply(self, decision, vector):
        # THE audited seam: trace context + ring entry wrap the write
        for env, v in sorted(vector.items()):
            os.environ[env] = str(v)


def post_knobs(request, batcher):
    # the /knobs route handlers share the sanction by name
    os.environ["PIO_SERVE_MIPS_NPROBE"] = "128"
    batcher.apply_knobs()


def local_knobs_fn():
    # actuator FACTORY (*_fn): builds the callable _apply invokes
    def apply(vector):
        os.environ["PIO_SERVE_MAX_BATCH"] = "512"
        return {"local": True}

    return apply


class Batcher:
    def apply_knobs(self):
        # the scheduler re-reading its OWN fields on self is the
        # refresh seam, not a bypass
        self.cap = 512
        self.max_batch = self.cap
""",
    ),
    "recorder-in-serve-path": (
        """
from incubator_predictionio_tpu.obs import recorder as obs_recorder

class Server:
    def _freeze(self):
        # registry walk + bundle write inline with the dispatch: the
        # incident stalls the very queries it is diagnosing
        obs_recorder.get_recorder().sample_now()
        cap = obs_recorder.get_capture()
        cap.capture_now("serve_p99")

    def _handle_batch(self, bodies):
        out = [self.score(b) for b in bodies]
        self._freeze()
        return out
""",
        """
from incubator_predictionio_tpu.obs import recorder as obs_recorder

class Server:
    def __init__(self):
        # registering a state provider is not a snapshot — the
        # recorder's OWN thread calls it later
        obs_recorder.register_state_provider(
            "server", lambda: {"ok": True})

    def _handle_batch(self, bodies):
        out = [self.score(b) for b in bodies]
        if self.overloaded():
            # the sanctioned serve-path hook: non-blocking enqueue
            self._capture.trigger("serve_p99")
        return out

    def admin_dump(self, request):
        # admin/debug handlers are not the serving hot path
        return obs_recorder.get_recorder().dump()
""",
    ),
    "metric-label-cardinality": (
        """
from incubator_predictionio_tpu.obs import metrics

REQS = metrics.REGISTRY.counter("t_total", "x", labels=("who", "why"))

def handle(request, user_id):
    # every distinct user/path/exception mints a new time series
    REQS.labels(who=user_id, why=request.path).inc()
    REQS.labels(who=f"user-{user_id}", why="x").inc()
    try:
        run(request)
    except Exception as e:
        REQS.labels(who="x", why=str(e)).inc()
""",
        """
from incubator_predictionio_tpu.obs import metrics

REQS = metrics.REGISTRY.counter("t_total", "x", labels=("route", "status"))

def handle(request, route_label, response):
    # bounded sets: the route PATTERN, the status code, enum names
    REQS.labels(route=route_label, status=str(response.status)).inc()
    REQS.labels(route="/events.json", status="201").inc()
    for phase, secs in timings.items():
        PHASES.labels(phase=phase).set(secs)
""",
    ),
    "unscoped-tenant-metric": (
        """
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.serving import tenancy

LAT = obs_metrics.REGISTRY.histogram(
    "pio_query_latency_seconds", "per-query wall", labels=("tenant",))
SHED = obs_metrics.REGISTRY.counter(
    "pio_serve_shed_total", "sheds", labels=("tenant", "reason"))


def book(dt, tenant):
    LAT.labels().observe(dt)                       # no tenant label
    SHED.labels(tenant=tenant, reason="quota").inc()   # raw wire value
""",
        """
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.serving import tenancy

LAT = obs_metrics.REGISTRY.histogram(
    "pio_query_latency_seconds", "per-query wall", labels=("tenant",))
SHED = obs_metrics.REGISTRY.counter(
    "pio_serve_shed_total", "sheds", labels=("tenant", "reason"))


def book(dt, tenant):
    reg = tenancy.get_registry()
    LAT.labels(tenant=reg.label(tenant)).observe(dt)
    SHED.labels(tenant=reg.label(tenant), reason="quota").inc()
""",
    ),
    "unguarded-shared-state": (
        """
import threading


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            self.count += 1      # poller write, no lock

    def stats(self):
        with self._lock:         # scrape read, under the lock
            return {"count": self.count}
""",
        """
import threading


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            with self._lock:
                self.count += 1

    def stats(self):
        with self._lock:
            return {"count": self.count}
""",
    ),
    "thread-lifecycle": (
        """
import threading


def kick(fn):
    threading.Thread(target=fn).start()
""",
        """
import threading


def kick(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
""",
    ),
}


def _lint_source(tmp_path: Path, source: str, rule: str, name="fixture.py"):
    # server-state / unbatched-dispatch / exhaustive-scan only apply
    # under servers/ (exhaustive-scan also covers serving/);
    # unaudited-actuation only applies to obs/controller.py itself
    if rule == "unaudited-actuation":
        target_dir = tmp_path / "obs"
        name = "controller.py"
    elif rule in ("server-state", "unbatched-dispatch",
                  "exhaustive-scan"):
        target_dir = tmp_path / "servers"
    elif rule == "unscoped-tenant-metric":
        target_dir = tmp_path / "serving"
    else:
        target_dir = tmp_path
    target_dir.mkdir(exist_ok=True)
    target = target_dir / name
    target.write_text(source, encoding="utf-8")
    return lint_paths([target], [RULES_BY_NAME[rule]])


def test_registry_has_at_least_eight_rules():
    assert len(ALL_RULES) >= 10
    assert set(FIXTURES) == set(RULES_BY_NAME), (
        "every rule needs a positive/negative fixture pair")


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_seeded_violation_is_detected(tmp_path, rule):
    findings = _lint_source(tmp_path, FIXTURES[rule][0], rule)
    assert findings, f"rule {rule} missed its seeded violation"
    assert all(f.rule == rule for f in findings)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_hazard_free_twin_is_silent(tmp_path, rule):
    findings = _lint_source(tmp_path, FIXTURES[rule][1], rule)
    assert not findings, (
        f"rule {rule} false-positived: {[f.format() for f in findings]}")


def test_inline_suppression(tmp_path):
    src = FIXTURES["env-import"][0].replace(
        'CHUNK = int(os.environ.get("PIO_CHUNK", "64"))',
        'CHUNK = int(os.environ.get("PIO_CHUNK", "64"))'
        '  # pio-lint: disable=env-import')
    assert not _lint_source(tmp_path, src, "env-import")


def test_comment_line_above_suppression(tmp_path):
    src = FIXTURES["env-import"][0].replace(
        'CHUNK = int(os.environ.get("PIO_CHUNK", "64"))',
        '# pio-lint: disable=env-import\n'
        'CHUNK = int(os.environ.get("PIO_CHUNK", "64"))')
    assert not _lint_source(tmp_path, src, "env-import")


def test_file_level_suppression(tmp_path):
    src = "# pio-lint: disable-file=env-import\n" + FIXTURES["env-import"][0]
    assert not _lint_source(tmp_path, src, "env-import")


def test_docstring_directive_does_not_suppress(tmp_path):
    """Documenting the suppression syntax in a docstring must not
    disable anything — only real COMMENT tokens count."""
    src = '''
"""Module doc: use `# pio-lint: disable-file=env-import` to suppress.

# pio-lint: disable=env-import
"""
import os

CHUNK = int(os.environ.get("PIO_CHUNK", "64"))
'''
    assert _lint_source(tmp_path, src, "env-import")


def test_clamp_in_other_function_does_not_exempt(tmp_path):
    """A clamp assignment in one function must not blind neg-gather to
    a same-named raw gather in another function."""
    src = """
import jax.numpy as jnp

def safe(prev, ids):
    safe_ids = jnp.maximum(ids, 0)
    return prev[safe_ids]

def unsafe(prev, safe_ids):
    return prev[safe_ids]
"""
    findings = _lint_source(tmp_path, src, "neg-gather")
    assert len(findings) == 1 and "'safe_ids'" in findings[0].message


def test_partial_bound_kernel_body_is_traced(tmp_path):
    """A kernel bound through an intermediate (`body = partial(k, ...)`
    then `pallas_call(body)`) is still traced, with partial keywords
    treated as statics — the repo's main ALS kernels use this shape."""
    src = """
import functools
import time
from jax.experimental import pallas as pl

def _kernel(x_ref, o_ref, *, precise):
    if precise:
        o_ref[...] = x_ref[...] * time.time()

def launch(x, precise):
    body = functools.partial(_kernel, precise=precise)
    kfn = body
    return pl.pallas_call(kfn, out_shape=None)(x)
"""
    findings = _lint_source(tmp_path, src, "wallclock")
    assert len(findings) == 1 and "time.time" in findings[0].message
    # and `precise` (partial-bound) must be static for tracer-branch
    assert not _lint_source(tmp_path, src, "tracer-branch")


def test_metric_set_flagged_but_chained_at_set_exempt(tmp_path):
    """`g.set(...)`-style metric writes in a trace are flagged while the
    JAX functional-update idiom — including chained `.at[].set()` — is
    not."""
    src = """
import jax

@jax.jit
def step(x, ids, g):
    y = x.at[ids].set(0.0).at[0].set(1.0)
    g.set(1.0)
    return y
"""
    findings = _lint_source(tmp_path, src, "metric-in-trace")
    assert len(findings) == 1
    assert ".set() metric mutation" in findings[0].message


def test_write_baseline_preserves_justifications(tmp_path):
    findings = _lint_source(tmp_path, FIXTURES["env-import"][0],
                            "env-import")
    baseline = tmp_path / "baseline.json"
    write_baseline(baseline, findings)
    entries = load_baseline(baseline)
    entries[0]["justification"] = "hand-written reason"
    baseline.write_text(
        __import__("json").dumps({"entries": entries}), encoding="utf-8")
    write_baseline(baseline, findings)  # regenerate over the curated file
    assert load_baseline(baseline)[0]["justification"] == \
        "hand-written reason"


def test_nested_async_def_reported_once(tmp_path):
    src = """
class Handler:
    async def handle(self, request):
        async def inner():
            self.count += 1
        await inner()
"""
    findings = _lint_source(tmp_path, src, "server-state")
    assert len(findings) == 1, [f.format() for f in findings]
    assert "'inner'" in findings[0].message


def test_write_baseline_select_keeps_out_of_scope_entries(tmp_path):
    """--write-baseline under --select must not wipe entries whose rule
    the filtered run could not even see."""
    import json
    target = tmp_path / "code.py"
    target.write_text(FIXTURES["env-import"][0] + FIXTURES["wallclock"][0],
                      encoding="utf-8")
    bl = tmp_path / "bl.json"
    run = [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
           str(target), "--write-baseline", str(bl)]
    subprocess.run(run, cwd=repo_root(), check=True, capture_output=True,
                   timeout=120)
    rules_before = sorted(e["rule"]
                          for e in json.loads(bl.read_text())["entries"])
    assert rules_before == ["env-import", "wallclock"]
    subprocess.run(run + ["--select", "env-import"], cwd=repo_root(),
                   check=True, capture_output=True, timeout=120)
    rules_after = sorted(e["rule"]
                         for e in json.loads(bl.read_text())["entries"])
    assert rules_after == rules_before


def test_baseline_roundtrip(tmp_path):
    findings = _lint_source(tmp_path, FIXTURES["env-import"][0],
                            "env-import")
    baseline = tmp_path / "baseline.json"
    write_baseline(baseline, findings)
    entries = load_baseline(baseline)
    unmatched, stale = apply_baseline(findings, entries)
    assert not unmatched and not stale
    # fixing the violation leaves the entry stale, never hidden
    unmatched, stale = apply_baseline([], entries)
    assert not unmatched and len(stale) == len(entries)


def test_baseline_entries_all_have_real_justifications():
    entries = load_baseline(
        repo_root() / "incubator_predictionio_tpu/analysis/baseline.json")
    assert entries, "checked-in baseline should record the deliberate "\
        "exceptions (read-once env knobs)"
    for e in entries:
        assert e.get("justification", "").strip(), e
        assert "TODO" not in e["justification"], e


def test_repo_is_clean_modulo_baseline():
    """THE CI gate: the tree must lint clean the way scripts/lint.sh and
    the acceptance criteria run it."""
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         "--baseline"],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"pio-lint found new violations:\n{proc.stdout}\n{proc.stderr}")
    assert "stale baseline entry" not in proc.stderr, proc.stderr


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         "--list-rules"],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for rule in RULES_BY_NAME:
        assert rule in proc.stdout


def test_cli_exit_one_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["env-import"][0], encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         str(bad)],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "[env-import]" in proc.stdout

# ---------------------------------------------------------------------------
# whole-program concurrency pass (unguarded-shared-state / thread-lifecycle)
# ---------------------------------------------------------------------------

def test_timer_spawn_without_daemon_flagged(tmp_path):
    src = """
import threading


class Refresher:
    def kick(self):
        t = threading.Timer(5.0, self._tick)
        t.start()

    def _tick(self):
        pass
"""
    findings = _lint_source(tmp_path, src, "thread-lifecycle")
    assert len(findings) == 1 and "timer" in findings[0].message.lower()


def test_timer_daemonized_on_local_is_silent(tmp_path):
    src = """
import threading


class Refresher:
    def kick(self):
        t = threading.Timer(5.0, self._tick)
        t.daemon = True
        t.start()

    def _tick(self):
        pass
"""
    assert not _lint_source(tmp_path, src, "thread-lifecycle")


def test_executor_without_shutdown_flagged_with_block_silent(tmp_path):
    bad = """
from concurrent.futures import ThreadPoolExecutor


def fan_out(items, fn):
    ex = ThreadPoolExecutor(max_workers=4)
    return [ex.submit(fn, it) for it in items]
"""
    good = """
from concurrent.futures import ThreadPoolExecutor


def fan_out(items, fn):
    with ThreadPoolExecutor(max_workers=4) as ex:
        return [f.result() for f in [ex.submit(fn, it) for it in items]]
"""
    findings = _lint_source(tmp_path, bad, "thread-lifecycle")
    assert len(findings) == 1 and "executor" in findings[0].message.lower()
    assert not _lint_source(tmp_path, good, "thread-lifecycle")


def test_nested_and_aliased_lock_regions_count_as_guarded(tmp_path):
    src = """
import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self.total = 0
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        lk = self._lock
        with lk:
            with self._io_lock:
                self.total += 1

    def read(self):
        with self._lock:
            return self.total
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_publish_only_annotation_honored_for_single_writer(tmp_path):
    src = """
import threading


class Sampler:
    def __init__(self):
        self.snapshot = ()  # pio-lint: publish-only
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        while True:
            self.snapshot = (1, 2, 3)

    def read(self):
        return self.snapshot
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_publish_only_annotation_verified_multi_writer_flagged(tmp_path):
    src = """
import threading


class Sampler:
    def __init__(self):
        self.snapshot = ()  # pio-lint: publish-only
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        while True:
            self.snapshot = (1, 2, 3)

    def reset(self):
        self.snapshot = ()

    def read(self):
        return self.snapshot
"""
    findings = _lint_source(tmp_path, src, "unguarded-shared-state")
    assert len(findings) == 1
    assert "publish-only" in findings[0].message


def test_guarded_by_annotation_honored(tmp_path):
    src = """
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        # pio-lint: guarded-by(_lock)
        self.count = 0
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_guarded_by_annotation_verified_bare_write_flagged(tmp_path):
    src = """
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        # pio-lint: guarded-by(_lock)
        self.count = 0
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        self.count += 1

    def read(self):
        with self._lock:
            return self.count
"""
    findings = _lint_source(tmp_path, src, "unguarded-shared-state")
    assert len(findings) == 1
    assert "guarded-by" in findings[0].message


def test_queue_handoff_is_sanctioned(tmp_path):
    src = """
import queue
import threading


class Pipeline:
    def __init__(self):
        self._q = queue.Queue()
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        while True:
            self._q.put(1)

    def drain(self):
        return self._q.get()
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_cross_method_reachability_through_call_graph(tmp_path):
    """A write two hops away from the thread entry (entry -> helper) is
    still on a thread-side path and must be flagged."""
    src = """
import threading


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        while True:
            self._step()

    def _step(self):
        self.total += 1

    def report(self):
        with self._lock:
            return self.total
"""
    findings = _lint_source(tmp_path, src, "unguarded-shared-state")
    assert len(findings) == 1
    assert findings[0].line and "total" in findings[0].message


def test_caller_held_lock_propagates_into_private_helper(tmp_path):
    """A `_locked`-style helper whose every call site holds the lock is
    effectively guarded — no finding."""
    src = """
import threading


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        while True:
            with self._lock:
                self._bump()

    def _bump(self):
        self.n += 1

    def get(self):
        with self._lock:
            return self.n
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_fully_unguarded_conflict_reported_once_per_attr(tmp_path):
    """Tier B: no lock anywhere, but a genuine cross-domain conflict —
    one finding anchored at the thread-side write, not one per access."""
    src = """
import threading


class Tally:
    def __init__(self):
        self.hits = 0
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        while True:
            self.hits += 1

    def read(self):
        return self.hits

    def read_again(self):
        return self.hits
"""
    findings = _lint_source(tmp_path, src, "unguarded-shared-state")
    assert len(findings) == 1 and "hits" in findings[0].message


def test_single_domain_state_never_flagged(tmp_path):
    """No spawn, or all accesses on one side: no conflict, no finding."""
    src = """
class Plain:
    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1

    def read(self):
        return self.count
"""
    assert not _lint_source(tmp_path, src, "unguarded-shared-state")


def test_cli_format_json(tmp_path):
    import json
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["env-import"][0], encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         str(bad), "--format", "json"],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert (doc["summary"]["errors"] + doc["summary"]["warnings"] >= 1
            and not doc["summary"]["clean"])
    assert any(f["rule"] == "env-import" and not f["suppressed"]
               for f in doc["findings"])
    assert "ruleTimingsMs" in doc


def test_cli_json_out_artifact(tmp_path):
    import json
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["env-import"][0], encoding="utf-8")
    out = tmp_path / "artifacts" / "lint-report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         str(bad), "--json-out", str(out)],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "[env-import]" in proc.stdout  # stdout stays text format
    doc = json.loads(out.read_text(encoding="utf-8"))
    assert doc["version"] == 1
    assert doc["summary"]["errors"] + doc["summary"]["warnings"] >= 1


def test_cli_prune_baseline_drops_stale_keeps_live(tmp_path):
    import json
    target = tmp_path / "code.py"
    target.write_text(FIXTURES["env-import"][0] + FIXTURES["wallclock"][0],
                      encoding="utf-8")
    bl = tmp_path / "bl.json"
    base = [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
            str(target)]
    subprocess.run(base + ["--write-baseline", str(bl)], cwd=repo_root(),
                   check=True, capture_output=True, timeout=120)
    for e in json.loads(bl.read_text())["entries"]:
        assert e["rule"] in ("env-import", "wallclock")
    # fix only the env-import half; its entry goes stale
    target.write_text(FIXTURES["env-import"][1] + FIXTURES["wallclock"][0],
                      encoding="utf-8")
    proc = subprocess.run(
        base + ["--baseline-path", str(bl), "--prune-baseline"],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pruned 1 stale entry" in proc.stderr
    left = json.loads(bl.read_text())["entries"]
    assert [e["rule"] for e in left] == ["wallclock"]


def test_timings_within_tier1_budget():
    """--timings reports every rule, and the whole-program pass keeps the
    full-package lint inside a tier-1-friendly wall-clock budget."""
    start = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.analysis",
         "--baseline", "--timings"],
        cwd=repo_root(), capture_output=True, text=True, timeout=180)
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rule timings" in proc.stderr
    for rule in ("unguarded-shared-state", "thread-lifecycle"):
        assert rule in proc.stderr
    assert elapsed < 90.0, f"full-package lint took {elapsed:.1f}s"
