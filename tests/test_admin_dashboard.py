"""AdminAPI + Dashboard servers (parity: tools AdminAPISpec + Dashboard)."""

import json
import urllib.error
import urllib.request

import pytest

from fake_engine import AP, QxMetric, make_engine, params
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.servers.admin import AdminServer
from incubator_predictionio_tpu.servers.dashboard import DashboardServer
from incubator_predictionio_tpu.workflow import CoreWorkflow


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            ct = resp.headers.get("Content-Type", "")
            raw = resp.read()
            return resp.status, (json.loads(raw) if "json" in ct else raw)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def test_admin_api_app_crud():
    srv = AdminServer(ip="127.0.0.1", port=0)
    port = srv.start_background()
    try:
        assert call(port, "GET", "/")[1]["status"] == "alive"
        status, body = call(port, "POST", "/cmd/app", {"name": "AdminApp"})
        assert status == 200 and body["accessKey"]
        status, body = call(port, "POST", "/cmd/app", {"name": "AdminApp"})
        assert status == 400 and "already exists" in body["message"]
        assert call(port, "POST", "/cmd/app", {})[0] == 400
        status, apps = call(port, "GET", "/cmd/app")
        assert [a["name"] for a in apps] == ["AdminApp"]
        assert call(port, "DELETE", "/cmd/app/AdminApp/data")[0] == 200
        assert call(port, "DELETE", "/cmd/app/AdminApp")[0] == 200
        assert call(port, "DELETE", "/cmd/app/AdminApp")[0] == 404
    finally:
        srv.stop()


def test_dashboard_lists_evaluations():
    evaluation = Evaluation()
    evaluation.engine_metric = (make_engine(), QxMetric())
    iid, _ = CoreWorkflow.run_evaluation(
        evaluation, [params(algos=[("algo0", AP(2))])],
        evaluation_class="tests.Eval",
    )
    srv = DashboardServer(ip="127.0.0.1", port=0)
    port = srv.start_background()
    try:
        status, body = call(port, "GET", "/")
        assert status == 200
        html = body.decode()
        assert iid in html and "tests.Eval" in html
        status, detail = call(port, "GET", f"/engine_instances/{iid}")
        assert status == 200 and b"<table" in detail
        status, js = call(
            port, "GET", f"/engine_instances/{iid}/evaluator_results.json"
        )
        assert status == 200
        assert call(port, "GET", "/engine_instances/nope")[0] == 404
    finally:
        srv.stop()


def test_dashboard_cors():
    """CORS parity (CorsSupport.scala:30-66): allow-origin on responses,
    OPTIONS preflight announces allowed methods."""
    srv = DashboardServer(ip="127.0.0.1", port=0)
    port = srv.start_background()
    try:
        _dashboard_cors_checks(port)
    finally:
        srv.stop()


def _dashboard_cors_checks(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as resp:
        assert resp.headers["Access-Control-Allow-Origin"] == "*"
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", method="OPTIONS")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200
        assert "GET" in resp.headers["Access-Control-Allow-Methods"]
        assert "OPTIONS" in resp.headers["Access-Control-Allow-Methods"]
        assert resp.headers["Access-Control-Max-Age"] == "1728000"
        assert "Content-Type" in resp.headers["Access-Control-Allow-Headers"]
