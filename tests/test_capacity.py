"""Capacity/regression model over the checked-in bench trajectory.

The acceptance contract: ``scripts/capacity_report.py`` run over the
repo's real ``BENCH_*.json``/``MULTICHIP_*.json`` emits a
``capacity.json`` with a rows-per-chip estimate and a NON-NULL verdict
for every record — including structured reasons for the r04/r05-style
failed runs (``accelerator init still blocked`` rc=3, driver-kill
rc=124), which used to be unexplainable ``parsed: null`` rows. The
unit tests pin the failure classifier, the tolerance compare and the
record normalizer on synthetic records so the contract outlives the
particular files checked in today.
"""

import json
import os
import subprocess
import sys

import pytest

from incubator_predictionio_tpu.obs import capacity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "capacity_report.py")


# -- the tier-1 gate: the real script over the real trajectory --------------

def test_capacity_report_check_over_checked_in_records(tmp_path):
    out = tmp_path / "capacity.json"
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--repo-dir", REPO,
         "--out", str(out), "--check"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CHECK OK" in proc.stderr
    report = json.loads(out.read_text())

    # a rows-per-chip estimate exists and is a real rate
    cap = report["capacity"]
    assert cap["rows_per_chip_per_s"] and cap["rows_per_chip_per_s"] > 0
    assert cap["rows_per_chip_at_staleness"] > 0
    assert cap["train_source_record"]
    assert cap["qps_per_worker"] and cap["qps_per_worker"] > 0
    assert cap["projections"]["workers_for_qps"]

    # EVERY record carries a non-null verdict
    by_name = {}
    for rec in report["records"]:
        assert rec["verdict"] is not None, rec["name"]
        assert rec["verdict"].get("status"), rec["name"]
        by_name[rec["name"]] = rec

    # the r04/r05 failure modes are STRUCTURED, never bare nulls
    r04 = by_name["BENCH_r04"]
    assert r04["verdict"]["status"] == "skipped"
    assert r04["skipped_reason"]["class"] == "accelerator_unavailable"
    assert r04["rc"] == 3
    assert "accelerator init still blocked" in " ".join(
        r04["skipped_reason"]["matched"])
    r05 = by_name["BENCH_r05"]
    assert r05["verdict"]["status"] == "skipped"
    assert r05["skipped_reason"]["class"] == "driver_deadline"
    assert r05["rc"] == 124

    # regression section names the pinned baseline and a real status
    reg = report["regression"]
    assert reg["baseline"] is not None
    assert reg["status"] in ("ok", "regressed", "baseline",
                             "incomparable_shape")


def test_pinned_baseline_file_is_valid():
    base = capacity.load_baseline(REPO)
    assert base is not None, "CAPACITY_BASELINE.json missing/invalid"
    assert base["record"]
    assert isinstance(base["keys"], dict) and base["keys"]
    # the pinned record actually exists in the trajectory
    names = {r.name for r in capacity.load_trajectory(REPO)}
    assert base["record"] in names


# -- failure classifier ------------------------------------------------------

def test_classify_accelerator_wedge_rc3():
    tail = ("accelerator init still blocked (attempt 9) - likely a "
            "stale chip lease; retrying\n"
            "accelerator unavailable after 1200s; aborting\n")
    reason = capacity.classify_failure(tail, 3)
    assert reason["class"] == "accelerator_unavailable"
    assert reason["rc"] == 3
    assert reason["matched"]


def test_classify_driver_kill_rc124_wins_over_tail():
    tail = "tpu child attempt 3 did not claim within 720s\n"
    reason = capacity.classify_failure(tail, 124)
    assert reason["class"] == "driver_deadline"
    assert "accelerator" in reason["detail"]  # the secondary cause rides


def test_classify_unknown_nonzero_and_clean_exit():
    r = capacity.classify_failure("boom\nlast words", 7)
    assert r["class"] == "error_exit" and "last words" in r["detail"]
    r = capacity.classify_failure("", 0)
    assert r["class"] == "no_record"


# -- tolerance compare -------------------------------------------------------

BASE = {"value": 2.0, "serve_qps": 1000.0, "nnz": 100, "rank": 8,
        "sweeps": 4, "heldout_rmse": 0.6}


def test_compare_flags_regressions_both_directions():
    worse = dict(BASE, value=3.0, serve_qps=500.0)
    v = capacity.compare_record(worse, BASE, tolerance=0.25)
    assert v["status"] == "regressed"
    keys = {r["key"] for r in v["regressed"]}
    assert keys == {"value", "serve_qps"}   # wall UP, qps DOWN


def test_compare_skips_null_keys_and_tolerates_noise():
    rec = dict(BASE, value=2.2, serve_qps=None, heldout_rmse=0.65)
    v = capacity.compare_record(rec, BASE, tolerance=0.25)
    assert v["status"] == "ok"
    assert "serve_qps" in v["skipped"]       # null = skipped, not failed


def test_compare_shape_mismatch_is_incomparable_not_green():
    rec = dict(BASE, nnz=999)
    v = capacity.compare_record(rec, BASE, tolerance=0.25)
    assert v["status"] == "incomparable_shape"


def test_improvements_are_reported_not_flagged():
    rec = dict(BASE, value=1.0, serve_qps=2000.0)
    v = capacity.compare_record(rec, BASE, tolerance=0.25)
    assert v["status"] == "ok"
    assert set(v["improved"]) == {"value", "serve_qps"}


def test_key_direction_classes():
    assert capacity.key_direction("value") == "lower"
    assert capacity.key_direction("serve_p99_ms") == "lower"
    assert capacity.key_direction("heldout_rmse") == "lower"
    assert capacity.key_direction("serve_qps_concurrent") == "higher"
    assert capacity.key_direction("mfu") == "higher"
    assert capacity.key_direction("ingest_http_eps") == "higher"
    assert capacity.key_direction("nnz") is None        # shape key
    assert capacity.key_direction("als_kernel") is None  # informational


# -- record normalization ----------------------------------------------------

def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_normalize_wrapped_flat_and_multichip(tmp_path):
    wrapped = _write(tmp_path, "BENCH_r07.json", {
        "n": 7, "cmd": "python bench.py", "rc": 0, "tail": "",
        "parsed": {"value": 1.5, "nnz": 100, "degraded": False,
                   "bench_env": {"backend": "tpu"}}})
    flat = _write(tmp_path, "BENCH_r08.json", {
        "metric": "als_ml20m_train_wall_s", "value": 1.4, "nnz": 100})
    multi = _write(tmp_path, "MULTICHIP_r07.json", {
        "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
        "tail": ""})
    w = capacity.normalize_record(wrapped)
    assert w.kind == "bench" and w.round == 7
    assert w.parsed["value"] == 1.5
    assert w.bench_env == {"backend": "tpu"}
    assert w.skipped_reason is None
    f = capacity.normalize_record(flat)
    assert f.parsed["value"] == 1.4 and f.round == 8
    m = capacity.normalize_record(multi)
    assert m.kind == "multichip" and m.ok is True


def test_normalize_surfaces_bench_emitted_skip_reason(tmp_path):
    # post-PR-9 degraded rounds: the bench ITSELF ships the structured
    # reason inside parsed — the normalizer surfaces it as-is
    p = _write(tmp_path, "BENCH_r09.json", {
        "n": 9, "rc": 0, "tail": "", "parsed": {
            "value": 300.0, "nnz": 100, "degraded": True,
            "skipped_reason": {"class": "accelerator_unavailable",
                               "stage": "tpu_child", "rc": 3}}})
    r = capacity.normalize_record(p)
    assert r.degraded is True
    assert r.skipped_reason["class"] == "accelerator_unavailable"


def test_trajectory_verdicts_every_record_non_null(tmp_path):
    _write(tmp_path, "BENCH_r01.json", {
        "n": 1, "rc": 0, "tail": "", "parsed": {
            "value": 2.0, "nnz": 100, "rank": 8, "sweeps": 4,
            "serve_qps_concurrent": 900.0}})
    _write(tmp_path, "BENCH_r02.json", {
        "n": 2, "rc": 3,
        "tail": "accelerator init still blocked (attempt 1)",
        "parsed": None})
    report = capacity.capacity_report(str(tmp_path))
    assert len(report["records"]) == 2
    for rec in report["records"]:
        assert rec["verdict"]["status"]
    # no pinned baseline file in tmp: the oldest parsed record becomes
    # the honest fallback baseline
    assert report["regression"]["baseline"] == "BENCH_r01"
    assert report["regression"]["baseline_provenance"] \
        == "fallback:oldest_parsed"
    cap = report["capacity"]
    assert cap["rows_per_chip_per_s"] == pytest.approx(50.0)
    assert cap["qps_per_worker"] == 900.0


def test_degraded_records_never_feed_the_chip_rate(tmp_path):
    _write(tmp_path, "BENCH_r01.json", {
        "n": 1, "rc": 0, "tail": "", "parsed": {
            "value": 10.0, "nnz": 1000, "degraded": False}})
    _write(tmp_path, "BENCH_r02.json", {
        "n": 2, "rc": 0, "tail": "", "parsed": {
            "value": 300.0, "nnz": 1000, "degraded": True}})
    cap = capacity.fit_capacity(capacity.load_trajectory(str(tmp_path)))
    # the newer record is degraded (CPU fallback): the chip rate comes
    # from r01, the newest NON-degraded training wall
    assert cap["train_source_record"] == "BENCH_r01"
    assert cap["rows_per_chip_per_s"] == pytest.approx(100.0)
