"""The external-DataSource worked example (examples/csv-datasource):
build → train → deploy → query through the real CLI with NO event server
and NO app — the data comes straight from the CSV directory.

Parity: examples/experimental/scala-parallel-recommendation-custom-
datasource (a DataSource reading a third-party source instead of
PEventStore; the mongo-datasource variant is the same pattern)."""

import json
import shutil
import urllib.request
from pathlib import Path

import pytest

from incubator_predictionio_tpu.cli.main import main
from incubator_predictionio_tpu.data.storage import Storage

EXAMPLE = Path(__file__).parent.parent / "examples" / "csv-datasource"


@pytest.fixture
def storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_csv_datasource_end_to_end(storage, tmp_path, monkeypatch, capsys):
    # work on a copy so the example directory stays pristine
    workdir = tmp_path / "csv-datasource"
    shutil.copytree(EXAMPLE, workdir)
    monkeypatch.chdir(workdir)

    # no `app new`, no event server: build + train read data/*.csv
    assert main(["build"]) == 0
    assert main(["train"]) == 0
    out = capsys.readouterr().out
    assert "Engine instance ID:" in out

    # deploy the trained instance and query over HTTP
    from incubator_predictionio_tpu.cli.commands import (
        engine_from_variant,
        engine_id_for_variant_path,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )

    variant = json.loads((workdir / "engine.json").read_text())
    engine, _ = engine_from_variant(variant)
    ps = PredictionServer(engine, ServerConfig(
        ip="127.0.0.1", port=0,
        engine_id=engine_id_for_variant_path(
            str(workdir / "engine.json"), variant),
        engine_variant=variant["id"],
    ))
    port = ps.start_background()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": "u3", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            result = json.load(resp)
        scores = result["itemScores"]
        assert len(scores) == 3
        assert all(s["item"].startswith("i") for s in scores)
        # ranked descending
        vals = [s["score"] for s in scores]
        assert vals == sorted(vals, reverse=True)

        # unknown user → empty result, not an error
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": "nobody", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req2, timeout=60) as resp:
            assert json.load(resp)["itemScores"] == []
    finally:
        ps.stop()


def test_csv_datasource_rejects_malformed_rows(storage, tmp_path,
                                               monkeypatch):
    workdir = tmp_path / "csv-datasource"
    shutil.copytree(EXAMPLE, workdir)
    (workdir / "data" / "bad.csv").write_text("u1,i1\n")  # missing rating
    monkeypatch.chdir(workdir)
    assert main(["build"]) == 0
    # fails loudly with file:line context (a subprocess `pio train` exits
    # nonzero with this traceback)
    with pytest.raises(ValueError, match=r"bad\.csv:1: expected"):
        main(["train"])