"""`pio train --hosts local,local` end to end: the launcher spawns two
real CLI worker processes that join one jax.distributed runtime over
shared sqlite storage; exactly ONE engine instance is persisted
(process 0), and its model serves (Runner.scala:101-213's role, driven
through the actual CLI)."""

import json
import os
import subprocess
import sys
from pathlib import Path

def test_pio_train_hosts_two_process(tmp_path):
    engine_dir = tmp_path / "engine"
    engine_dir.mkdir()
    # a local one-file engine, resolved from the engine dir like
    # examples/helloworld (commands.resolve_engine_factory adds cwd)
    (engine_dir / "podengine.py").write_text(
        "import dataclasses\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from incubator_predictionio_tpu.core import (\n"
        "    Algorithm, DataSource, Engine, EngineFactory, FirstServing,\n"
        "    IdentityPreparator, Params)\n"
        "\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class DSParams(Params):\n"
        "    n: int = 64\n"
        "\n"
        "class DS(DataSource):\n"
        "    def __init__(self, params: DSParams = DSParams()):\n"
        "        super().__init__(params)\n"
        "    def read_training(self, ctx):\n"
        "        return np.arange(self.params.n, dtype=np.float32)\n"
        "\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class AParams(Params):\n"
        "    scale: float = 2.0\n"
        "\n"
        "@dataclasses.dataclass\n"
        "class Model:\n"
        "    mean: np.ndarray\n"
        "\n"
        "class Algo(Algorithm):\n"
        "    params_class = AParams\n"
        "    def __init__(self, params: AParams = AParams()):\n"
        "        super().__init__(params)\n"
        "    def train(self, ctx, td):\n"
        "        # a real device reduction so the SPMD path is exercised\n"
        "        m = jnp.mean(jnp.asarray(td)) * self.params.scale\n"
        "        return Model(mean=np.asarray(m))\n"
        "    def predict(self, model, query):\n"
        "        return float(model.mean)\n"
        "\n"
        "class PodEngine(EngineFactory):\n"
        "    def apply(self):\n"
        "        return Engine(DS, IdentityPreparator, {'a': Algo},\n"
        "                      FirstServing)\n"
    )
    (engine_dir / "engine.json").write_text(json.dumps({
        "id": "pod-test",
        "engineFactory": "podengine:PodEngine",
        "datasource": {"params": {"n": 64}},
        "algorithms": [{"name": "a", "params": {"scale": 2.0}}],
    }))

    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    repo_root = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PIO_HOME": str(tmp_path / "home"),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.cli.main",
         "train", "--hosts", "local,localhost"],
        cwd=engine_dir, env=env, capture_output=True, text=True,
        timeout=420,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "Training completed. Engine instance ID:" in out
    assert "pod worker" in out  # the non-zero process said so

    # exactly one COMPLETED instance, with a working model blob
    import sqlite3

    conn = sqlite3.connect(str(tmp_path / "pio.db"))
    rows = conn.execute(
        "SELECT status FROM engine_instances").fetchall()
    assert [r[0] for r in rows] == ["COMPLETED"], rows
    (n_models,) = conn.execute("SELECT COUNT(*) FROM models").fetchone()
    assert n_models == 1
