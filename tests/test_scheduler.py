"""Continuous-batching scheduler behavior under load.

The serving plane's contracts, pinned without sleeps wherever a
decision is involved (the FakeClock seam drives every age/wall/shed
decision):

- ladder-rung growth/collapse (``plan_dispatch`` — the pure rule)
- the PIO_SERVE_MAX_WAIT_MS age bound: a query is never held past it
  (the _MicroBatcher starvation regression)
- per-engine queue isolation: batches never mix engines, rungs adapt
  independently
- SLO-projected load shedding: overload sheds 503 + Retry-After,
  priority evicts, recovery re-admits (the shed-then-recover flip)
- zero steady-state recompiles: a warm pow2 ladder serves every batch
  width the scheduler can choose from the jit cache
  (``ops.topk.serve_compile_cache_size`` — the foldin-cache pin's
  serving twin)
"""

import threading

import pytest

from incubator_predictionio_tpu.serving.scheduler import (
    BatchScheduler,
    ShedError,
    ladder_cap,
    plan_dispatch,
)
from incubator_predictionio_tpu.utils.times import FakeClock


# ---------------------------------------------------------------------------
# plan_dispatch: the pure ladder rule
# ---------------------------------------------------------------------------

def test_rung_grows_one_ladder_step_under_load():
    # queue deeper than the rung: take the rung now, grow for next time
    assert plan_dispatch(10, 4, 0.0, 512, 0.25) == (4, 8)
    assert plan_dispatch(100, 8, 0.0, 512, 0.25) == (8, 16)
    # growth saturates at the cap
    assert plan_dispatch(1000, 512, 0.0, 512, 0.25) == (512, 512)


def test_rung_collapses_when_idle():
    assert plan_dispatch(1, 8, 0.0, 512, 0.25) == (1, 4)
    assert plan_dispatch(0, 8, 0.0, 512, 0.25) == (0, 8)  # no dispatch
    # floor is rung 1
    assert plan_dispatch(1, 1, 0.0, 512, 0.25) == (1, 1)


def test_rung_hysteresis_band_holds_steady():
    # depth in (rung/2, rung]: no thrash
    assert plan_dispatch(3, 4, 0.0, 512, 0.25) == (3, 4)
    assert plan_dispatch(4, 4, 0.0, 512, 0.25) == (4, 4)


def test_age_breach_drains_whole_backlog():
    # the oldest waiter crossed the bound: take EVERYTHING (up to cap),
    # rung still only steps one ladder rung
    assert plan_dispatch(100, 4, 0.3, 512, 0.25) == (100, 8)
    assert plan_dispatch(1000, 4, 0.3, 512, 0.25) == (512, 8)
    # bound disabled (<=0): never triggers
    assert plan_dispatch(100, 4, 99.0, 512, 0.0) == (4, 8)


def test_ladder_cap_is_pow2(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_MAX_BATCH", "100")
    assert ladder_cap() == 128
    monkeypatch.setenv("PIO_SERVE_MAX_BATCH", "512")
    assert ladder_cap() == 512


# ---------------------------------------------------------------------------
# threaded scheduler behavior
# ---------------------------------------------------------------------------

def _drain(futs, timeout=10.0):
    return [f.result(timeout) for f in futs]


def test_ladder_walkup_batch_sizes():
    """A prefilled queue drains in pow2 ladder steps: 1 (the in-flight
    singleton), then 2, 4, 8, ... — the fused width follows queue
    depth, not a fixed cap."""
    gate = threading.Event()
    first_in = threading.Event()
    batches = []

    def handler(bodies):
        first_in.set()
        gate.wait(10)
        batches.append(len(bodies))
        return bodies

    s = BatchScheduler(handler, 64, shed=False, wait_bound_s=0.0)
    try:
        futs = [s.submit(b"0")]
        assert first_in.wait(5)           # singleton dispatch in flight
        futs += [s.submit(b"%d" % i) for i in range(1, 64)]
        gate.set()
        _drain(futs)
        # the in-flight singleton, then one rung-1 dispatch (the rung
        # only grows AFTER a dispatch observed the deep queue), then
        # the pow2 walk-up
        assert batches == [1, 1, 2, 4, 8, 16, 32], batches
    finally:
        s.stop()


def test_age_bound_never_holds_a_query_past_it():
    """The starvation regression: requests arriving while a full batch
    dispatches must NOT wait multiple rung-limited dispatch cycles —
    once their age crosses the bound, the next dispatch takes the whole
    backlog."""
    clock = FakeClock()
    gate = threading.Event()
    first_in = threading.Event()
    batches = []

    def handler(bodies):
        first_in.set()
        gate.wait(10)
        batches.append(len(bodies))
        return bodies

    s = BatchScheduler(handler, 64, clock=clock, shed=False,
                       wait_bound_s=0.25)
    try:
        futs = [s.submit(b"a")]
        assert first_in.wait(5)
        # ten requests land while the dispatch is in flight (rung is
        # still 1 — without the age bound they would drain one per
        # cycle, the last waiting TEN cycles)
        futs += [s.submit(b"%d" % i) for i in range(10)]
        clock.advance(1.0)                # all ten now exceed the bound
        gate.set()
        _drain(futs)
        assert batches == [1, 10], batches
    finally:
        s.stop()


def test_per_engine_queues_fuse_independently():
    """Batches never mix engines, and each engine's rung adapts to ITS
    queue depth only."""
    gate = threading.Event()
    first_in = threading.Event()
    batches = []

    def handler(bodies, engine):
        first_in.set()
        gate.wait(10)
        batches.append((engine, len(bodies)))
        return bodies

    s = BatchScheduler(handler, 64, shed=False, wait_bound_s=0.0)
    try:
        futs = [s.submit(b"x", engine="reco")]
        assert first_in.wait(5)
        futs += [s.submit(b"%d" % i, engine="reco") for i in range(32)]
        futs += [s.submit(b"e%d" % i, engine="ecom") for i in range(2)]
        gate.set()
        _drain(futs)
        for engine, n in batches:
            assert engine in ("reco", "ecom")
        # totals per engine add up — no cross-engine leakage
        assert sum(n for e, n in batches if e == "reco") == 33
        assert sum(n for e, n in batches if e == "ecom") == 2
        # the busy engine's rung grew; the idle one's never left 1
        assert s.rung("reco") > s.rung("ecom")
        assert s.rung("ecom") == 1
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# load shedding
# ---------------------------------------------------------------------------

class _GatedHandler:
    """Handler whose first call advances the fake clock (planting the
    EWMA dispatch wall) and whose later calls block on a gate."""

    def __init__(self, clock, wall_s):
        self.clock = clock
        self.wall_s = wall_s
        self.gate = threading.Event()
        self.in_handler = threading.Event()
        self.calls = 0

    def __call__(self, bodies):
        self.calls += 1
        if self.calls == 1:
            self.clock.advance(self.wall_s)  # plants ewma_wall
        else:
            self.in_handler.set()
            self.gate.wait(10)
        return bodies


def test_shed_then_recover_flip():
    clock = FakeClock()
    handler = _GatedHandler(clock, wall_s=0.2)
    s = BatchScheduler(handler, 4, clock=clock, shed=True, slo_s=0.5,
                       p99_fn=lambda: 0.1, wait_bound_s=0.0)
    try:
        # dispatch one to plant ewma_wall=0.2
        s.submit(b"w").result(10)
        # block the dispatcher with an in-flight singleton
        inflight = s.submit(b"0")
        assert handler.in_handler.wait(5)
        # queue depth grows; projection = (cycles + in-flight)·0.2 +
        # p99 0.1 against slo 0.5: with cap 4, depth 4 → 1 cycle →
        # (1+1)·0.2 + 0.1 = 0.5, NOT > slo; depth 5 → 2 cycles → 0.7 →
        # SHED. So 4 queued admit, the 5th sheds.
        admitted = [s.submit(b"%d" % i) for i in range(4)]
        shed = s.submit(b"last")
        assert shed.done()
        with pytest.raises(ShedError) as ei:
            shed.result()
        assert ei.value.status == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert ei.value.reason == "overload"
        # recovery: the queue drains, projections fall, admission resumes
        handler.gate.set()
        _drain([inflight] + admitted)
        ok = s.submit(b"again")
        assert ok.result(10) == b"again"
        assert s.shed_count == 1
    finally:
        s.stop()


def test_priority_evicts_lowest_not_highest():
    clock = FakeClock()
    handler = _GatedHandler(clock, wall_s=0.2)
    s = BatchScheduler(handler, 4, clock=clock, shed=True, slo_s=0.5,
                       p99_fn=lambda: 0.1, wait_bound_s=0.0)
    try:
        s.submit(b"w").result(10)
        inflight = s.submit(b"0")
        assert handler.in_handler.wait(5)
        low = [s.submit(b"%d" % i, priority=0) for i in range(4)]
        # overload point reached — a HIGHER-priority arrival evicts the
        # lowest-priority waiter instead of shedding itself
        vip = s.submit(b"vip", priority=5)
        assert not vip.done()
        evicted = [f for f in low if f.done()]
        assert len(evicted) == 1
        with pytest.raises(ShedError) as ei:
            evicted[0].result()
        assert ei.value.reason == "evicted"
        # an equal-priority arrival at the same depth sheds itself
        shed = s.submit(b"eq", priority=0)
        with pytest.raises(ShedError):
            shed.result()
        handler.gate.set()
        _drain([inflight, vip] + [f for f in low if f is not evicted[0]])
        assert s.shed_count == 2
    finally:
        s.stop()


def test_cold_queue_never_sheds():
    """No EWMA evidence (no dispatch yet) → no shedding, whatever the
    depth: admission control must never fire on a cold start."""
    gate = threading.Event()

    def handler(bodies):
        gate.wait(10)
        return bodies

    s = BatchScheduler(handler, 4, shed=True, slo_s=0.01,
                       p99_fn=lambda: 10.0, wait_bound_s=0.0)
    try:
        futs = [s.submit(b"%d" % i) for i in range(20)]
        assert not any(f.done() and f.exception() for f in futs)
        gate.set()
        _drain(futs)
        assert s.shed_count == 0
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# zero steady-state recompiles (real jit ladder)
# ---------------------------------------------------------------------------

def test_warm_ladder_serves_with_zero_recompiles():
    """Once every pow2 rung the scheduler can pick has compiled, any
    mixture of live batch widths serves entirely from the jit cache —
    the serving twin of foldin_compile_cache_size's pin."""
    import numpy as np

    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import topk

    uf = jnp.asarray(np.random.default_rng(0).normal(
        size=(64, 8)).astype(np.float32))
    itf = jnp.asarray(np.random.default_rng(1).normal(
        size=(48, 8)).astype(np.float32))

    def handler(bodies):
        rows = [int(b) % 64 for b in bodies]
        out = topk.batch_score_top_k(uf, itf, rows, k=8)
        assert out.shape[1] >= len(bodies)
        return bodies

    cap = 16
    # warm every pow2 ladder rung directly — exactly what the deploy-
    # time warmup hook (Algorithm.warmup) compiles before traffic lands
    for rung in topk.ladder_rungs(cap):
        handler([b"%d" % i for i in range(rung)])
    warm = topk.serve_compile_cache_size()
    assert warm > 0
    s = BatchScheduler(handler, cap, shed=False, wait_bound_s=0.0)
    try:
        # steady state through the scheduler: arbitrary live widths,
        # every one padding onto an already-compiled rung
        for width in (3, 7, 11, 16, 5, 13):
            futs = [s.submit(b"%d" % i) for i in range(width)]
            _drain(futs)
        assert topk.serve_compile_cache_size() == warm, \
            "steady-state serving recompiled"
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_batch_size_and_queue_wait_booked():
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    size_h = obs_metrics.REGISTRY.get("pio_serve_batch_size")
    wait_h = obs_metrics.REGISTRY.get("pio_serve_queue_wait_seconds")
    assert size_h is not None and wait_h is not None
    _n0, t0 = size_h.cumulative_below(float("inf"))
    _w0, w0 = wait_h.cumulative_below(float("inf"))

    s = BatchScheduler(lambda bodies: bodies, 8, shed=False)
    try:
        _drain([s.submit(b"x") for _ in range(5)])
    finally:
        s.stop()
    _n1, t1 = size_h.cumulative_below(float("inf"))
    _w1, w1 = wait_h.cumulative_below(float("inf"))
    assert t1 > t0          # ≥1 dispatch booked its fused width
    assert w1 - w0 == 5     # every query booked its queue wait
