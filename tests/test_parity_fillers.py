"""Tests for EngineManifests DAO, batch views, example webhook connectors,
template version gate, build/unregister, and FakeWorkflow.

Reference analogues: EngineManifests.scala, view/LBatchView.scala specs,
webhooks/{examplejson,exampleform}/*Spec.scala, commands/Template.scala,
RegisterEngine.scala, workflow/FakeWorkflow.scala.
"""

import json
from datetime import timedelta

import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    EngineManifest,
    Storage,
    StorageClientConfig,
)
from incubator_predictionio_tpu.data.storage import memory as memory_backend
from incubator_predictionio_tpu.data.storage import sqlite as sqlite_backend
from incubator_predictionio_tpu.data.view import BatchView, data_view
from incubator_predictionio_tpu.data.webhooks import ConnectorError
from incubator_predictionio_tpu.data.webhooks.examples import (
    ExampleFormConnector,
    ExampleJsonConnector,
)
from incubator_predictionio_tpu.utils.times import parse_iso8601

T0 = parse_iso8601("2021-06-01T00:00:00Z")


# ---------------------------------------------------------------------------
# EngineManifests conformance (both backends)
# ---------------------------------------------------------------------------

@pytest.fixture(params=["memory", "sqlite"])
def manifests(request):
    config = StorageClientConfig(test=True, properties={"PATH": ":memory:"})
    mod = {"memory": memory_backend, "sqlite": sqlite_backend}[request.param]
    client = mod.StorageClient(config)
    yield mod.DATA_OBJECTS["EngineManifests"](client, config, prefix="test_")
    client.close()


def test_engine_manifests_crud(manifests):
    m = EngineManifest(
        id="e1", version="v1", name="reco",
        engine_factory="pkg.mod:factory",
        description="d", files=("engine.json",),
    )
    manifests.insert(m)
    assert manifests.get("e1", "v1") == m
    assert manifests.get("e1", "v2") is None
    m2 = EngineManifest(id="e1", version="v2", name="reco",
                        engine_factory="pkg.mod:factory")
    assert manifests.update(m2) is False          # absent, no upsert
    assert manifests.update(m2, upsert=True) is True
    assert {x.version for x in manifests.get_all()} == {"v1", "v2"}
    assert manifests.delete("e1", "v1") is True
    assert manifests.delete("e1", "v1") is False
    assert manifests.get("e1", "v1") is None


# ---------------------------------------------------------------------------
# Batch views
# ---------------------------------------------------------------------------

def _ev(name, eid, props=None, minutes=0, **kw):
    return Event(
        event=name, entity_type="user", entity_id=eid,
        properties=DataMap(props or {}), event_time=T0 + timedelta(minutes=minutes),
        **kw,
    )


def test_batch_view_aggregate_properties():
    with pytest.warns(DeprecationWarning):
        view = BatchView([
            _ev("$set", "u1", {"a": 1, "b": 2}, minutes=0),
            _ev("$set", "u1", {"b": 3}, minutes=1),
            _ev("$unset", "u1", {"a": 0}, minutes=2),
            _ev("$set", "u2", {"x": 9}, minutes=0),
            _ev("$delete", "u2", minutes=5),
            _ev("rate", "u1", {"rating": 5}, minutes=3),  # non-special: no-op
        ])
    props = view.aggregate_properties("user")
    assert props["u1"].fields == {"b": 3}
    assert "u2" not in props  # $delete clears the entity


def test_batch_view_filter_start_time_exclusive():
    with pytest.warns(DeprecationWarning):
        view = BatchView([_ev("rate", "u1", minutes=m) for m in (0, 1, 2)])
    # ViewPredicates start-time predicate is exclusive (LBatchView.scala:39-41)
    out = view.filter(start_time=T0, until_time=T0 + timedelta(minutes=2))
    assert [e.event_time for e in out] == [T0 + timedelta(minutes=1)]


def test_data_view_rows():
    with pytest.warns(DeprecationWarning):
        rows = data_view([_ev("rate", "u1", {"rating": 4},
                              target_entity_type="item",
                              target_entity_id="i9")])
    assert rows[0]["event"] == "rate"
    assert rows[0]["targetEntityId"] == "i9"
    assert rows[0]["properties.rating"] == 4


# ---------------------------------------------------------------------------
# Example webhook connectors
# ---------------------------------------------------------------------------

def test_example_json_connector_user_action():
    out = ExampleJsonConnector().to_event_json({
        "type": "userAction", "userId": "as34smg4", "event": "do_something",
        "context": {"ip": "24.5.68.47"}, "anotherProperty1": 100,
        "anotherProperty2": "optional1",
        "timestamp": "2015-01-02T00:30:12.984Z",
    })
    assert out["event"] == "do_something"
    assert out["entityType"] == "user"
    assert out["entityId"] == "as34smg4"
    assert out["properties"]["anotherProperty1"] == 100
    assert "targetEntityType" not in out


def test_example_json_connector_user_action_item():
    out = ExampleJsonConnector().to_event_json({
        "type": "userActionItem", "userId": "u", "event": "view",
        "itemId": "i1", "context": {"ip": "1.2.3.4"},
        "anotherPropertyA": 4.567, "timestamp": "2015-01-15T04:20:23.567Z",
    })
    assert out["targetEntityType"] == "item"
    assert out["targetEntityId"] == "i1"
    assert out["properties"]["anotherPropertyA"] == pytest.approx(4.567)


def test_example_json_connector_rejects_unknown_type():
    with pytest.raises(ConnectorError):
        ExampleJsonConnector().to_event_json({"type": "nope"})
    with pytest.raises(ConnectorError):
        ExampleJsonConnector().to_event_json({})


def test_example_form_connector():
    out = ExampleFormConnector().to_event_json({
        "type": "userActionItem", "userId": "u", "event": "view",
        "itemId": "i1", "context[ip]": "1.2.3.4", "context[prop1]": "2.345",
        "context[prop2]": "value1", "anotherPropertyA": "4.567",
        "anotherPropertyB": "false", "timestamp": "2015-01-15T04:20:23.567Z",
    })
    assert out["properties"]["context"]["prop1"] == pytest.approx(2.345)
    assert out["properties"]["anotherPropertyB"] is False
    with pytest.raises(ConnectorError):
        ExampleFormConnector().to_event_json({"type": "bad"})
    with pytest.raises(ConnectorError):
        # userActionItem requires all context[...] fields
        ExampleFormConnector().to_event_json({
            "type": "userActionItem", "userId": "u", "event": "view",
            "itemId": "i1", "timestamp": "2015-01-15T04:20:23.567Z",
        })


# ---------------------------------------------------------------------------
# Template gate + build/unregister + FakeRun
# ---------------------------------------------------------------------------

def test_template_min_version_gate(tmp_path):
    from incubator_predictionio_tpu.cli.commands import (
        verify_template_min_version,
    )

    assert verify_template_min_version(str(tmp_path)) is None
    (tmp_path / "template.json").write_text(
        json.dumps({"pio": {"version": {"min": "0.0.1"}}})
    )
    assert verify_template_min_version(str(tmp_path)) is None
    (tmp_path / "template.json").write_text(
        json.dumps({"pio": {"version": {"min": "999.0.0"}}})
    )
    assert "999.0.0" in verify_template_min_version(str(tmp_path))


def test_build_and_unregister(tmp_path, monkeypatch):
    from incubator_predictionio_tpu.cli import commands

    monkeypatch.setenv("PIO_HOME", str(tmp_path / "home"))
    Storage.configure({
        "PIO_STORAGE_SOURCES_T_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "event",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "model",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
    })
    try:
        engine_dir = tmp_path / "engine"
        engine_dir.mkdir()
        (engine_dir / "engine.json").write_text(json.dumps({
            "id": "default", "version": "1",
            "engineFactory":
                "incubator_predictionio_tpu.models.recommendation:RecommendationEngine",
            "algorithms": [{"name": "als", "params": {"rank": 4}}],
        }))
        monkeypatch.chdir(engine_dir)
        manifest_id = commands.build(str(engine_dir))
        assert (engine_dir / "manifest.json").exists()
        manifests = Storage.get_meta_data_engine_manifests()
        assert len(manifests.get_all()) == 1
        assert manifests.get_all()[0].id == manifest_id
        commands.unregister(str(engine_dir))
        assert manifests.get_all() == []
        with pytest.raises(commands.CommandError):
            commands.unregister(str(engine_dir))  # already gone
    finally:
        Storage.reset()


def test_fake_run(tmp_path, monkeypatch):
    from incubator_predictionio_tpu.workflow import CoreWorkflow, FakeRun

    monkeypatch.setenv("PIO_HOME", str(tmp_path))
    Storage.configure({"PIO_STORAGE_SOURCES_T_TYPE": "memory",
                       "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
                       "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
                       "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
                       "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
                       "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
                       "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T"})
    try:
        calls = []

        run = FakeRun()
        run.func = lambda ctx: calls.append(ctx)
        instance_id, result = CoreWorkflow.run_evaluation(
            run, run.engine_params_list, evaluation_class="test:fake",
        )
        assert len(calls) == 1
        assert calls[0] is not None  # func received the RuntimeContext
        assert result.no_save is True
        instance = Storage.get_meta_data_evaluation_instances().get(instance_id)
        assert instance.status == "EVALCOMPLETED"
        assert instance.evaluator_results == ""  # noSave: nothing persisted
    finally:
        Storage.reset()


def test_sigterm_exits_through_interpreter():
    """utils/lease.py: SIGTERM must unwind through finally blocks and
    exit 143 via SystemExit (abrupt death while holding the chip wedges
    the single-tenant lease; see the lease-safety contract)."""
    import subprocess
    import sys
    import time

    p = subprocess.Popen([sys.executable, "-c", (
        "from incubator_predictionio_tpu.utils.lease import "
        "install_sigterm_exit\n"
        "import time\n"
        "assert install_sigterm_exit()\n"
        "try:\n"
        "    print('ready', flush=True)\n"
        "    time.sleep(30)\n"
        "finally:\n"
        "    print('clean shutdown ran', flush=True)\n")],
        stdout=subprocess.PIPE, text=True)
    assert p.stdout.readline().strip() == "ready"
    p.terminate()
    out, _ = p.communicate(timeout=15)
    assert p.returncode == 143
    assert "clean shutdown ran" in out
