"""Fleet observability: metrics federation + cross-process traces.

Three layers of proof:

1. **Planted merge math** — two in-process registries with known
   observations, merged through the real parse→federate path, with the
   federated histogram quantiles verified against HAND-computed merged
   bucket sums (the acceptance oracle for the federation math).
2. **Two-process /federate e2e** — two real worker processes
   (tests/fleet_worker.py) scraped through the admin server's
   ``GET /federate``: both workers' ``pio_query_latency_seconds`` come
   back under distinct ``instance`` labels, the fleet quantile matches
   the hand-merged bucket math, and ``GET /slo?fleet=1`` evaluates the
   shipped objectives over the federation.
3. **Two-process trace e2e** — an event server in THIS process backed
   by a remote StorageServer in a child process: one trace ID produces
   linked span lines in both processes (the storage span's
   ``parentSpanId`` is the event span's ``spanId``), and
   scripts/trace_stitch.py reassembles them into one tree.
"""

import json
import logging
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Storage,
)
from incubator_predictionio_tpu.obs import expofmt, federate
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs.metrics import Registry

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)
WORKER = os.path.join(TESTS_DIR, "fleet_worker.py")
sys.path.insert(0, os.path.join(REPO, "scripts"))

import trace_stitch  # noqa: E402


# -- target grammar ---------------------------------------------------------

def test_parse_targets_grammar():
    ts = federate.parse_targets(
        "10.0.0.1:8000, b=10.0.0.2:8000 ,http://h:9/custom,")
    assert [(t.instance, t.url) for t in ts] == [
        ("10.0.0.1:8000", "http://10.0.0.1:8000/metrics"),
        ("b", "http://10.0.0.2:8000/metrics"),
        ("h:9", "http://h:9/custom"),
    ]
    assert federate.parse_targets("") == []


# -- planted two-registry merge vs hand bucket math -------------------------

def _snapshot_from_registries(named_registries):
    results = []
    for instance, reg in named_registries:
        fams = expofmt.parse_families(reg.expose())
        results.append(federate.ScrapeResult(
            target=federate.Target(instance, f"http://{instance}"),
            ok=True, wall_s=0.0, families=fams))
    return federate.FederatedSnapshot(results)


def test_planted_merge_quantiles_match_hand_bucket_math():
    r1, r2 = Registry(), Registry()
    h1 = r1.histogram("pio_query_latency_seconds", "lat",
                      buckets=(0.1, 1.0))
    for _ in range(4):
        h1.observe(0.05)
    h2 = r2.histogram("pio_query_latency_seconds", "lat",
                      buckets=(0.1, 1.0))
    h2.observe(0.5)
    h2.observe(0.5)
    h2.observe(5.0)
    h2.observe(5.0)
    r1.gauge("pio_serve_queue_depth", "d").set(3)
    r2.gauge("pio_serve_queue_depth", "d").set(7)
    r1.counter("pio_http_requests_total", "r").inc(5)
    r2.counter("pio_http_requests_total", "r").inc(5)

    snap = _snapshot_from_registries((("w1", r1), ("w2", r2)))
    m = snap.get("pio_query_latency_seconds")
    # hand-merged buckets: le0.1=4, le1.0=2, overflow=2, total=8
    assert m.count == 8
    assert m.cumulative_below(0.1) == (4, 8)
    assert m.cumulative_below(1.0) == (6, 8)
    # p50: rank 4 lands exactly on the first bucket's cumulative 4 →
    # linear interpolation to the bucket's upper bound: 0 + 0.1·(4/4)
    assert m.quantile(0.50) == pytest.approx(0.1)
    # p75: rank 6 → second bucket [0.1, 1.0] holding 2, needs both:
    # 0.1 + 0.9·(2/2) = 1.0
    assert m.quantile(0.75) == pytest.approx(1.0)
    # p99: rank 7.92 is in the overflow — clamps to the last finite
    # bound (the honest fixed-bucket answer, same as the registry)
    assert m.quantile(0.99) == pytest.approx(1.0)
    # gauges: fleet SUM for load-style gauges, MAX for worst-of
    depth = snap.get("pio_serve_queue_depth")
    assert depth.total() == 10 and depth.max_value() == 7
    assert snap.get("pio_http_requests_total").total() == 10


def test_federated_exposition_round_trips_and_labels_instances():
    r1, r2 = Registry(), Registry()
    for reg, val in ((r1, 1), (r2, 2)):
        reg.counter("t_reqs_total", "x", labels=("route",)).labels(
            route="/a").inc(val)
        reg.histogram("t_lat_seconds", "x", buckets=(1.0,)).observe(0.5)
    snap = _snapshot_from_registries((("w1", r1), ("w2", r2)))
    text = snap.expose()
    # the output round-trips through the SAME grammar parser that read
    # the inputs
    types, samples = expofmt.parse_exposition(text)
    assert types["t_reqs_total"] == "counter"
    assert samples[("t_reqs_total", frozenset(
        {("instance", "w1"), ("route", "/a")}))] == 1
    assert samples[("t_reqs_total", frozenset(
        {("instance", "w2"), ("route", "/a")}))] == 2
    assert samples[("pio_federate_up", frozenset(
        {("instance", "w1")}))] == 1
    # histogram children keep per-instance identity
    b, s, tot = expofmt.histogram_series(
        samples, "t_lat_seconds", frozenset({("instance", "w2")}))
    assert tot == 1 and s == pytest.approx(0.5)


def test_down_instance_degrades_per_instance_not_per_fleet():
    res_ok = federate.ScrapeResult(
        target=federate.Target("up", "http://up"), ok=True, wall_s=0.0,
        families=expofmt.parse_families(Registry().expose()))
    res_down = federate.ScrapeResult(
        target=federate.Target("down", "http://down"), ok=False,
        wall_s=0.1, families={}, error="connection refused")
    snap = federate.FederatedSnapshot([res_ok, res_down])
    _types, samples = expofmt.parse_exposition(snap.expose())
    assert samples[("pio_federate_up", frozenset(
        {("instance", "up")}))] == 1
    assert samples[("pio_federate_up", frozenset(
        {("instance", "down")}))] == 0


# -- two-process /federate e2e ----------------------------------------------

def _spawn_worker(*args):
    proc = subprocess.Popen(
        [sys.executable, WORKER, *args],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=TESTS_DIR,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    port_holder = []

    def read_port():
        line = proc.stdout.readline()
        if line.startswith("PORT "):
            port_holder.append(int(line.split()[1]))

    t = threading.Thread(target=read_port, daemon=True)
    t.start()
    t.join(timeout=60)
    if not port_holder:
        proc.kill()
        _out, err = proc.communicate(timeout=30)
        raise RuntimeError(f"worker never bound: {err[-2000:]}")
    return proc, port_holder[0]


def _stop_worker(proc):
    # communicate() closes the worker's stdin (its exit signal), then
    # drains stdout/stderr until the process ends
    try:
        return proc.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        return proc.communicate(timeout=30)


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), resp.read()


@pytest.fixture
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_federate_e2e_two_worker_processes(mem_storage, monkeypatch):
    from incubator_predictionio_tpu.servers.admin import AdminServer

    w1, p1 = _spawn_worker("--mode", "metrics",
                           "--observe", "0.004,0.004,0.004",
                           "--depth", "3", "--staleness", "100")
    w2, p2 = _spawn_worker("--mode", "metrics",
                           "--observe", "0.1,3.0",
                           "--depth", "5", "--staleness", "9000")
    admin = None
    try:
        monkeypatch.setenv(
            "PIO_FLEET_TARGETS",
            f"w1=127.0.0.1:{p1},w2=127.0.0.1:{p2}")
        federate.reset_fleet_engine()
        admin = AdminServer(ip="127.0.0.1", port=0)
        aport = admin.start_background()

        status, headers, body = _get(aport, "/federate")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        types, samples = expofmt.parse_exposition(body.decode())
        # both workers' scrapes landed
        assert samples[("pio_federate_up", frozenset(
            {("instance", "w1")}))] == 1
        assert samples[("pio_federate_up", frozenset(
            {("instance", "w2")}))] == 1
        # the latency histogram exists under DISTINCT instance labels
        b1, _s1, t1 = expofmt.histogram_series(
            samples, "pio_query_latency_seconds",
            frozenset({("instance", "w1")}))
        b2, _s2, t2 = expofmt.histogram_series(
            samples, "pio_query_latency_seconds",
            frozenset({("instance", "w2")}))
        assert t1 == 3 and t2 == 2
        # fleet-merged quantiles match HAND-merged bucket sums: merge
        # the two children's cumulative buckets by bound, then run the
        # standard interpolation — computed here independently of
        # obs/federate.py's own math
        merged = {}
        for buckets, total in ((b1, t1), (b2, t2)):
            prev = 0.0
            for le, cum in buckets:
                if le == float("inf"):
                    continue
                merged[le] = merged.get(le, 0.0) + (cum - prev)
                prev = cum
        total = t1 + t2

        def hand_quantile(q):
            rank = q * total
            cum, prev_le = 0.0, 0.0
            for le, c in sorted(merged.items()):
                if c > 0 and cum + c >= rank:
                    return prev_le + (le - prev_le) * (rank - cum) / c
                cum += c
                prev_le = le
            return max(merged)

        snap = federate.federate()
        m = snap.get("pio_query_latency_seconds")
        for q in (0.5, 0.95, 0.99):
            assert m.quantile(q) == pytest.approx(hand_quantile(q)), q
        # summed queue depth exists as one scrape
        assert m.count == total
        assert snap.get("pio_serve_queue_depth").total() == 8

        # fleet SLO mode: same objectives, federated registry
        status, _h, body = _get(aport, "/slo?fleet=1")
        assert status == 200
        payload = json.loads(body)
        assert payload["scope"] == "fleet"
        by_name = {s["name"]: s for s in payload["slos"]}
        assert by_name["serve_p99"]["totalObservations"] == total
        # staleness is worst-of: w2's 9000 s breaches the 3600 s bound
        # even though w1 is fresh — the gauge objective saw ONE bad tick
        assert not by_name["staleness"]["noData"]
        assert by_name["staleness"]["totalObservations"] >= 1
        eng = federate.fleet_slo_engine()
        assert eng.registry.get(
            "pio_model_staleness_seconds").max_value() == 9000
    finally:
        if admin is not None:
            admin.stop()
        federate.reset_fleet_engine()
        _stop_worker(w1)
        _stop_worker(w2)


def test_frontdoor_joins_the_observability_fleet(monkeypatch):
    """Satellite: the front door is a first-class federation target.
    Its ``/metrics`` carries the ``pio_frontdoor_*`` series AND the
    client-observed ``pio_query_latency_seconds`` it books per served
    query — so a fleet ``/slo`` whose targets include the door
    evaluates serve_p99 over what clients actually saw through it, not
    just per-worker dispatch histograms."""
    from incubator_predictionio_tpu.obs import slo as obs_slo
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )
    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Request,
        Response,
        Router,
    )

    r = Router()

    @r.post("/queries.json")
    def queries(request: Request) -> Response:
        return Response(200, {"itemScores": []})

    @r.get("/")
    def status_route(request: Request) -> Response:
        return Response(200, {"status": "alive"})

    worker = HttpServer(r, "127.0.0.1", 0, name="miniworker")
    wport = worker.start_background()
    fd = FrontDoor([("127.0.0.1", wport)],
                   FrontDoorConfig(probe_interval_s=5.0))
    fport = fd.start_background()
    lat = obs_metrics.REGISTRY.get("pio_query_latency_seconds")
    before = lat.count if lat is not None else 0
    try:
        for _ in range(5):
            req = urllib.request.Request(
                f"http://127.0.0.1:{fport}/queries.json",
                data=b'{"user": "u1", "num": 1}', method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        # the door booked the client-observed walls into the SAME
        # family the workers use
        lat = obs_metrics.REGISTRY.get("pio_query_latency_seconds")
        assert lat.count >= before + 5
        # federate the door like any worker: its exposition merges
        # under an instance label with the frontdoor series present
        target = federate.Target(
            instance="door", url=f"http://127.0.0.1:{fport}/metrics")
        snap = federate.FederatedSnapshot(
            [federate.scrape_target(target)])
        assert snap.up_instances() == ["door"]
        reqs = snap.get("pio_frontdoor_requests_total")
        assert reqs is not None and reqs.total() >= 5
        fleet_lat = snap.get("pio_query_latency_seconds")
        assert fleet_lat is not None
        below, total = fleet_lat.cumulative_below(0.25)
        assert total >= 5
        # the fleet SLO engine evaluates serve_p99 over the door's view
        eng = obs_slo.SLOEngine(
            specs=(obs_slo.SLOSpec(
                name="serve_p99",
                metric="pio_query_latency_seconds",
                threshold=0.25, target=0.99),),
            registry=federate.FleetRegistry(
                targets_fn=lambda: [target], max_age_s=0.0),
            min_tick_interval_s=0.0, export_gauges=False)
        out = eng.evaluate()[0]
        assert out["noData"] is False
        assert out["totalObservations"] >= 5
    finally:
        fd.stop()
        worker.stop()


def test_federate_unconfigured_is_explicit(mem_storage, monkeypatch):
    from incubator_predictionio_tpu.servers.admin import AdminServer

    monkeypatch.delenv("PIO_FLEET_TARGETS", raising=False)
    federate.reset_fleet_engine()
    admin = AdminServer(ip="127.0.0.1", port=0)
    aport = admin.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(aport, "/federate")
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(aport, "/slo?fleet=1")
        assert ei.value.code == 400
        # the process-scoped /slo still answers
        status, _h, body = _get(aport, "/slo")
        assert status == 200
        assert json.loads(body)["scope"] == "process"
    finally:
        admin.stop()
        federate.reset_fleet_engine()


# -- two-process trace propagation e2e --------------------------------------

def test_cross_process_trace_links_span_lines(monkeypatch, caplog):
    """One trace ID through two REAL processes: event server (here) →
    storage server (child process). Both emit span lines with the same
    trace ID, and the storage span's parentSpanId is the event span's
    spanId — the cross-process parenting contract."""
    from incubator_predictionio_tpu.servers.event_server import (
        EventServer,
        EventServerConfig,
    )

    worker, sport = _spawn_worker("--mode", "storage")
    es = None
    try:
        Storage.configure({
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_SOURCES_REM_TYPE": "remote",
            "PIO_STORAGE_SOURCES_REM_URL": f"http://127.0.0.1:{sport}",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "REM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        app_id = Storage.get_meta_data_apps().insert(App(0, "fleet-app"))
        Storage.get_meta_data_access_keys().insert(
            AccessKey("fleetkey", app_id))
        Storage.get_events().init(app_id)
        es = EventServer(EventServerConfig(ip="127.0.0.1", port=0))
        eport = es.start_background()

        tid = "fleet-trace-0001"
        body = json.dumps({
            "event": "rate", "entityType": "user", "entityId": "u1",
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 5},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{eport}/events.json?accessKey=fleetkey",
            data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "X-PIO-Trace-Id": tid})
        with caplog.at_level(logging.INFO, logger="pio.trace"):
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == 201
                assert resp.headers["X-PIO-Trace-Id"] == tid
    finally:
        if es is not None:
            es.stop()
        Storage.reset()
        _out, worker_err = _stop_worker(worker)

    local_lines = [r.getMessage() for r in caplog.records
                   if r.name == "pio.trace"]
    local_spans = trace_stitch.parse_span_lines(local_lines)
    remote_spans = trace_stitch.parse_span_lines(
        worker_err.splitlines())

    event_spans = [s for s in local_spans
                   if s["traceId"] == tid and s.get("server") == "event"]
    assert event_spans, local_spans
    event_span = event_spans[0]
    assert event_span["spanId"]

    storage_spans = [s for s in remote_spans if s["traceId"] == tid]
    assert storage_spans, worker_err[-2000:]
    # every storage hop of this request is parented under the event
    # server's span — the linkage crossed the process boundary
    for s in storage_spans:
        assert s["server"] == "storage"
        assert s["route"] == "/rpc"
        assert s["parentSpanId"] == event_span["spanId"], s

    # and the stitcher reassembles the cross-process tree
    roots = trace_stitch.build_tree(event_spans + storage_spans)
    assert len(roots) == 1
    assert roots[0] is event_span
    child_ids = {c.get("spanId") for c in roots[0]["children"]}
    assert {s.get("spanId") for s in storage_spans} <= child_ids
    rendered = trace_stitch.render_trace(tid, event_spans + storage_spans)
    assert "event POST /events.json" in rendered
    assert "storage POST /rpc" in rendered


def test_trace_stitch_cli_filters_and_lists(tmp_path, capsys):
    lines = [
        json.dumps({"span": "http.request", "server": "a", "method": "GET",
                    "route": "/x", "status": 200, "ts": 10.0,
                    "durationMs": 1.0, "traceId": "t1", "spanId": "aa"}),
        json.dumps({"span": "http.request", "server": "b", "method": "GET",
                    "route": "/y", "status": 200, "ts": 10.1,
                    "durationMs": 0.5, "traceId": "t1", "spanId": "bb",
                    "parentSpanId": "aa"}),
        "not json at all",
        json.dumps({"span": "http.request", "server": "a", "method": "GET",
                    "route": "/z", "status": 404, "ts": 11.0,
                    "durationMs": 0.2, "traceId": "t2", "spanId": "cc"}),
    ]
    log = tmp_path / "spans.log"
    log.write_text("\n".join(lines) + "\n")
    assert trace_stitch.main([str(log), "--trace", "t1"]) == 0
    out = capsys.readouterr().out
    assert "trace t1 (2 spans)" in out
    assert "t2" not in out
    assert trace_stitch.main([str(log), "--list"]) == 0
    out = capsys.readouterr().out
    assert "t1  2 spans" in out and "t2  1 spans" in out
    assert trace_stitch.main([str(log), "--trace", "missing"]) == 1
