"""Worker program for the two-process multi-host test.

Run by tests/test_distributed.py as ONE OF TWO coordinated processes:
each process owns 4 virtual CPU devices, `jax.distributed.initialize`
(via parallel.distributed.ensure_initialized) joins them into one 8-device
global runtime, and both run the same program — the multi-controller SPMD
model that replaces the reference's spark-submit executor fan-out
(tools/.../Runner.scala:101-213).

Exercises, in order:
1. coordinator bring-up from the PIO_* env trio,
2. a DCN-aware pod mesh over both processes' devices,
3. host-local batch feeding → one global array (the PEvents partition
   assignment role),
4. a global-sum collective across processes,
5. ONE ALS sweep on globally-sharded buckets, numerics-checked against the
   process-local single-device reference.

Prints "WORKER_OK <checksum>" on success; the parent asserts both
processes print the same checksum.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from incubator_predictionio_tpu.parallel import distributed  # noqa: E402

# jax.distributed.initialize must run before ANYTHING touches the XLA
# backend — and importing the ops package evaluates module-level jnp
# constants, so the join happens here, before those imports
_MULTI = distributed.ensure_initialized()

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from incubator_predictionio_tpu.ops import als_init, als_sweep  # noqa: E402
from incubator_predictionio_tpu.ops.sparse import build_padded_rows  # noqa: E402


def main() -> None:
    assert _MULTI, "expected a multi-process runtime"
    assert jax.process_count() == 2, jax.process_count()
    assert distributed.process_count() == 2
    assert distributed.is_multihost()
    assert len(jax.devices()) == 8, "global device view spans both processes"
    assert len(jax.local_devices()) == 4

    # -- pod mesh over every process's devices ----------------------------
    mesh = distributed.make_pod_mesh(("dp", "mp"), (2, -1))
    assert dict(mesh.shape) == {"dp": 2, "mp": 4}

    # -- host-local feeding into one global array + a DCN collective ------
    global_batch = 16
    sl = distributed.host_local_batch_slice(global_batch)
    full = np.arange(global_batch, dtype=np.float32) + 1.0
    sharding = NamedSharding(mesh, P(("dp", "mp")))
    garr = distributed.global_array_from_local(full[sl], sharding)
    total = jax.jit(
        jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
    np.testing.assert_allclose(np.asarray(total), full.sum())

    # -- one ALS sweep over the global mesh vs the local reference --------
    rng = np.random.default_rng(42)
    n_users, n_items, nnz, rank = 48, 32, 400, 8
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    vals = rng.uniform(1, 5, nnz).astype(np.float32)

    ref_state = als_sweep(
        als_init(jax.random.key(0), n_users, n_items, rank),
        build_padded_rows(users, items, vals, n_users),
        build_padded_rows(items, users, vals, n_items),
        l2=0.1,
    )

    rows = NamedSharding(mesh, P(("dp", "mp")))
    repl = NamedSharding(mesh, P())

    def put_bucket(b):
        return type(b)(
            row_ids=jax.device_put(b.row_ids, rows),
            cols=jax.device_put(b.cols, rows),
            vals=jax.device_put(b.vals, rows),
            mask=jax.device_put(b.mask, rows),
        )

    ub = [put_bucket(b) for b in build_padded_rows(
        users, items, vals, n_users, row_multiple=8)]
    ib = [put_bucket(b) for b in build_padded_rows(
        items, users, vals, n_items, row_multiple=8)]
    state0 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, repl),
        als_init(jax.random.key(0), n_users, n_items, rank))
    # validate=False: split-row validation fetches row_ids, which is not
    # possible for globally-sharded (cross-process) arrays — callers
    # validate BEFORE sharding (als_train does the same)
    out = als_sweep(state0, ub, ib, l2=0.1, validate=False)

    # re-replicate so every process holds the full factors for comparison
    gather = jax.jit(lambda t: t, out_shardings=repl)
    got = gather(out)
    np.testing.assert_allclose(
        np.asarray(ref_state.user_factors), np.asarray(got.user_factors),
        rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(ref_state.item_factors), np.asarray(got.item_factors),
        rtol=2e-4, atol=2e-5)

    checksum = float(np.abs(np.asarray(got.user_factors)).sum())
    print(f"WORKER_OK {checksum:.6f}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
