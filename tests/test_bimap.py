"""BiMap behavior (parity: data/src/test/.../storage/BiMapSpec.scala)."""

import pytest

from incubator_predictionio_tpu.data.bimap import BiMap


def test_string_int_dense_and_stable():
    m = BiMap.string_int(["b", "a", "b", "c"])
    assert len(m) == 3
    assert m["b"] == 0 and m["a"] == 1 and m["c"] == 2


def test_inverse_round_trip():
    m = BiMap.string_int(["x", "y"])
    inv = m.inverse
    for k in m:
        assert inv[m[k]] == k
    # inverse is O(1) view; double inverse round-trips
    assert inv.inverse.to_dict() == m.to_dict()


def test_lookup_variants():
    m = BiMap({"a": 1})
    assert m("a") == 1
    assert m.get("z") is None
    assert m.get_or_else("z", 99) == 99
    assert "a" in m and "z" not in m
    with pytest.raises(KeyError):
        m["z"]


def test_unique_values_enforced():
    with pytest.raises(ValueError):
        BiMap({"a": 1, "b": 1})


def test_take():
    m = BiMap.string_int(["a", "b", "c"])
    t = m.take(2)
    assert t.to_dict() == {"a": 0, "b": 1}
