"""BiMap behavior (parity: data/src/test/.../storage/BiMapSpec.scala)."""

import pytest

from incubator_predictionio_tpu.data.bimap import BiMap


def test_string_int_dense_and_stable():
    m = BiMap.string_int(["b", "a", "b", "c"])
    assert len(m) == 3
    assert m["b"] == 0 and m["a"] == 1 and m["c"] == 2


def test_inverse_round_trip():
    m = BiMap.string_int(["x", "y"])
    inv = m.inverse
    for k in m:
        assert inv[m[k]] == k
    # inverse is O(1) view; double inverse round-trips
    assert inv.inverse.to_dict() == m.to_dict()


def test_lookup_variants():
    m = BiMap({"a": 1})
    assert m("a") == 1
    assert m.get("z") is None
    assert m.get_or_else("z", 99) == 99
    assert "a" in m and "z" not in m
    with pytest.raises(KeyError):
        m["z"]


def test_unique_values_enforced():
    with pytest.raises(ValueError):
        BiMap({"a": 1, "b": 1})


def test_take():
    m = BiMap.string_int(["a", "b", "c"])
    t = m.take(2)
    assert t.to_dict() == {"a": 0, "b": 1}


# ---------------------------------------------------------------------------
# EntityMap (EntityMap.scala:27-99) + API-stability markers
# ---------------------------------------------------------------------------

def test_entity_id_ix_map():
    from incubator_predictionio_tpu.data.entity_map import EntityIdIxMap

    m = EntityIdIxMap.from_keys(["a", "b", "c"])
    assert len(m) == 3
    assert m("a") == 0 and m("c") == 2
    assert m(1) == "b"  # symmetric apply: int → id
    assert "b" in m and 2 in m and "z" not in m and 9 not in m
    assert m.get("z") is None and m.get(7, "dflt") == "dflt"
    t = m.take(2)
    assert len(t) == 2 and t("b") == 1 and "c" not in t


def test_entity_map_data_and_take():
    from incubator_predictionio_tpu.data.entity_map import EntityMap

    em = EntityMap({"u1": {"age": 30}, "u2": {"age": 40}, "u3": {"age": 50}})
    assert em.data("u2") == {"age": 40}
    assert em.data(em("u2")) == {"age": 40}      # by dense index
    assert em.get_data("ghost") is None
    assert em.get_or_else_data("ghost", {"age": 0}) == {"age": 0}
    assert em.get_or_else_data("ghost", lambda: {"age": 1}) == {"age": 1}
    t = em.take(2)
    assert len(t) == 2 and set(t.id_to_data) == {"u1", "u2"}


def test_extract_entity_map_from_event_store():
    from incubator_predictionio_tpu.data.datamap import DataMap
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.storage import App, Storage
    from incubator_predictionio_tpu.data.store import EventStore

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    try:
        app_id = Storage.get_meta_data_apps().insert(App(0, "emap"))
        dao = Storage.get_events()
        dao.insert(Event(event="$set", entity_type="item", entity_id="i1",
                         properties=DataMap({"year": 1999})), app_id)
        dao.insert(Event(event="$set", entity_type="item", entity_id="i2",
                         properties=DataMap({"year": 2005})), app_id)
        em = EventStore.extract_entity_map(app_name="emap",
                                           entity_type="item")
        assert len(em) == 2
        assert em.data("i2").get("year") == 2005
        assert em.data(em("i1")).get("year") == 1999
    finally:
        Storage.reset()


def test_api_stability_markers():
    from incubator_predictionio_tpu.data.entity_map import EntityMap
    from incubator_predictionio_tpu.utils.annotations import (
        api_stability,
        developer_api,
        experimental,
    )

    assert api_stability(EntityMap) == "Experimental"
    assert EntityMap.__doc__.startswith(":: Experimental ::")

    @developer_api
    def low_level():
        """Does internal things."""

    assert api_stability(low_level) == "DeveloperApi"
    assert ":: DeveloperApi ::" in low_level.__doc__
    assert "Does internal things." in low_level.__doc__
    assert api_stability(test_entity_map_data_and_take) == "stable"
