"""`pio upgrade` — the store migration/compaction verb (the reference's
HBase upgrade tool role, data/.../storage/hbase/upgrade/Upgrade.scala)."""

import numpy as np
import pytest

from incubator_predictionio_tpu.cli import commands
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage


def _ev(i, minutes=0):
    from datetime import datetime, timedelta, timezone

    return Event(
        event="rate", entity_type="user", entity_id=f"u{i}",
        target_entity_type="item", target_entity_id=f"i{i % 5}",
        properties=DataMap({"rating": float(1 + i % 5)}),
        event_time=datetime(2026, 1, 1, tzinfo=timezone.utc)
        + timedelta(minutes=minutes),
    )


@pytest.fixture
def cpplog_storage(tmp_path):
    native = __import__(
        "incubator_predictionio_tpu.native", fromlist=["load"])
    if native.load() is None:
        pytest.skip("native library unavailable")
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def sqlite_storage(tmp_path):
    Storage.configure({
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    yield
    Storage.reset()


def test_cpplog_compact_drops_dead_records_and_preserves_live(
        cpplog_storage):
    Storage.get_meta_data_apps().insert(App(0, "upapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("upapp").id
    dao = Storage.get_events()
    ids = dao.insert_batch([_ev(i, minutes=i) for i in range(40)], app_id)
    for eid in ids[:15]:  # tombstone 15 of 40
        assert dao.delete(eid, app_id)
    path = dao.client._file(dao.ns, app_id, None)
    bytes_dirty = path.stat().st_size
    before = [(e.event_id, e.entity_id, e.event_time,
               e.properties.get("rating"))
              for e in dao.find(app_id=app_id)]
    assert len(before) == 25

    results = commands.upgrade("upapp")
    assert len(results) == 1
    assert results[0]["events"] == 25
    assert results[0]["bytes_after"] < bytes_dirty  # tombstones reclaimed

    after = [(e.event_id, e.entity_id, e.event_time,
              e.properties.get("rating"))
             for e in dao.find(app_id=app_id)]
    assert after == before  # ids, times, properties, order all preserved
    # the store stays fully functional post-swap (reads AND writes)
    new_id = dao.insert(_ev(99, minutes=99), app_id)
    assert dao.get(new_id, app_id) is not None
    inter = dao.scan_interactions(
        app_id=app_id, event_names=("rate",), value_prop="rating")
    assert len(inter) == 26


def test_cpplog_compact_invalidates_traincache(cpplog_storage, monkeypatch):
    from incubator_predictionio_tpu.data.storage import traincache

    monkeypatch.setattr(traincache, "MIN_NNZ", 4)
    Storage.get_meta_data_apps().insert(App(0, "upapp2"))
    app_id = Storage.get_meta_data_apps().get_by_name("upapp2").id
    dao = Storage.get_events()
    from incubator_predictionio_tpu.data.storage.base import Interactions

    inter = Interactions(
        user_idx=np.arange(8, dtype=np.int32) % 3,
        item_idx=np.arange(8, dtype=np.int32) % 4,
        values=np.ones(8, np.float32),
        user_ids=["a", "b", "c"], item_ids=["w", "x", "y", "z"],
    )
    dao.import_interactions(inter, app_id)
    cpath = traincache.path_for(dao.client._file(dao.ns, app_id, None))
    assert cpath.exists()
    commands.upgrade("upapp2")
    assert not cpath.exists()  # entry numbering changed: cache must die
    back = dao.scan_interactions(
        app_id=app_id, event_names=("rate",), value_prop="rating")
    assert len(back) == 8


def test_sqlite_vacuum_reports_and_preserves(sqlite_storage):
    Storage.get_meta_data_apps().insert(App(0, "upsql"))
    app_id = Storage.get_meta_data_apps().get_by_name("upsql").id
    dao = Storage.get_events()
    ids = dao.insert_batch([_ev(i, minutes=i) for i in range(30)], app_id)
    for eid in ids[:10]:
        dao.delete(eid, app_id)
    results = commands.upgrade()
    assert results and results[0]["events"] == 20
    assert len(list(dao.find(app_id=app_id))) == 20


def test_memory_backend_reports_nothing_to_do():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    try:
        assert commands.upgrade() == []
    finally:
        Storage.reset()


def test_sqlite_compact_preserves_upsert_tie_order(sqlite_storage):
    """The find() tie-break contract rides on rowid order; VACUUM may
    renumber implicit rowids, so compact() must re-encode the contract
    order into the fresh rowids (base.py Events.find ORDER CONTRACT)."""
    from datetime import datetime, timezone

    Storage.get_meta_data_apps().insert(App(0, "tieorder"))
    app_id = Storage.get_meta_data_apps().get_by_name("tieorder").id
    dao = Storage.get_events()
    t = datetime(2026, 2, 1, tzinfo=timezone.utc)
    for eid, name in (("e1", "a"), ("e2", "b"), ("e3", "c")):
        dao.insert(Event(event=name, entity_type="user", entity_id="u",
                         properties=DataMap({}), event_time=t,
                         event_id=eid), app_id)
    # upsert the first one: moves to the end of its timestamp group
    dao.insert(Event(event="a2", entity_type="user", entity_id="u",
                     properties=DataMap({}), event_time=t,
                     event_id="e1"), app_id)
    before = [e.event for e in dao.find(app_id=app_id)]
    assert before == ["b", "c", "a2"]
    commands.upgrade()
    after = [e.event for e in dao.find(app_id=app_id)]
    assert after == before


def test_cpplog_compact_upgrades_bare_json_and_keeps_compact_records(
        cpplog_storage, tmp_path):
    """The native compaction must (a) byte-copy records that already carry
    sidecars — bulk-imported compact records may NOT inflate — and (b)
    add a sidecar to pre-sidecar bare-JSON records (the legacy format)
    so post-upgrade scans take the binary fast path."""
    import json as _json
    import struct

    from incubator_predictionio_tpu.data.storage import Storage as S

    Storage.get_meta_data_apps().insert(App(0, "fmtapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("fmtapp").id
    dao = Storage.get_events()
    # uniform batch → columnar compact records via the fast path
    ids = dao.insert_batch([_ev(i, minutes=i) for i in range(12)], app_id)
    path = dao.client._file(dao.ns, app_id, None)

    # forge a LEGACY bare-JSON record (flags=0) at the tail, hashes
    # matching the fields so find()'s hash pruning still works
    def fnv(s: str) -> int:
        h = 0xCBF29CE484222325
        for b in s.encode("utf-8"):
            h = ((h ^ b) * 0x100000001B3) % (1 << 64)
        return h

    doc = {"eventId": "f" * 32, "event": "rate", "entityType": "user",
           "entityId": "legacy", "targetEntityType": "item",
           "targetEntityId": "i9", "properties": {"rating": 2.5},
           "eventTime": "2026-01-01T00:30:00.000+00:00", "tags": [],
           "creationTime": "2026-01-01T00:30:00.000+00:00"}
    payload = _json.dumps(doc, separators=(",", ":")).encode()
    t_ms = 1767227400000  # 2026-01-01T00:30:00Z
    header = struct.pack(
        "<qQQQQIi", t_ms, fnv("user"), fnv("legacy"), fnv("rate"),
        fnv("f" * 32), len(payload), 0)
    dao.client.close()
    with open(path, "ab") as f:
        f.write(header + payload)
    S.reset()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_EV_PATH": str(path.parent),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    Storage.get_meta_data_apps().insert(App(0, "fmtapp"))
    dao = Storage.get_events()
    assert dao.get("f" * 32, app_id).entity_id == "legacy"
    size_before = path.stat().st_size

    res = dao.compact(app_id)
    assert res["events"] == 13
    # compact records byte-copied (no inflation): total growth is exactly
    # the ONE legacy record's new sidecar block (< 200 bytes), not the
    # 2-3x a JSON round-trip of the 12 compact records would cost
    assert 0 < res["bytes_after"] - size_before < 200
    # the legacy record now carries a sidecar: walk the file's headers
    flags_seen = []
    blob = path.read_bytes()
    off = 0
    while off + 48 <= len(blob):
        t, _e, _u, _n, _i, plen, flags = struct.unpack_from(
            "<qQQQQIi", blob, off)
        flags_seen.append(flags)
        off += 48 + plen
    assert all(f & 2 for f in flags_seen)  # every record has kSidecar
    # and everything still reads correctly
    assert dao.get("f" * 32, app_id).properties.get("rating") == 2.5
    inter = dao.scan_interactions(
        app_id=app_id, event_names=("rate",), value_prop="rating")
    assert len(inter) == 13
