"""json_codec corner cases — the JsonExtractor parity suite.

Reference counterpart: core/src/test/.../workflow/JsonExtractorSuite.scala
(386 LoC: Scala vs Java param classes × Json4s/Gson/Both modes). Here the
two extractor modes collapse into lenient (gson-shim) vs strict
(json4s-native); the corner cases are the same — numeric widening/string
coercion, missing-field defaults, camelCase wire names, nested
dataclasses, unions, enums, and round-tripping through to_jsonable.
"""

import dataclasses
import enum
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

import pytest

from incubator_predictionio_tpu.utils.json_codec import (
    ExtractionError,
    dumps,
    extract,
    extract_json,
    snake_to_camel,
    to_jsonable,
)


class Color(enum.Enum):
    RED = "red"
    BLUE = "blue"


@dataclasses.dataclass(frozen=True)
class Inner:
    name: str
    weight: float = 1.0


@dataclasses.dataclass(frozen=True)
class Params:
    __camel_case__ = True

    app_name: str
    num_iterations: int = 10
    seed: Optional[int] = None
    inner: Optional[Inner] = None
    tags: Tuple[str, ...] = ()
    table: Dict[str, float] = dataclasses.field(default_factory=dict)


def test_dataclass_camel_case_and_defaults():
    p = extract(Params, {"appName": "a", "numIterations": 3})
    assert p.app_name == "a" and p.num_iterations == 3
    assert p.seed is None and p.tags == () and p.table == {}
    # snake_case keys are also accepted (Python callers)
    assert extract(Params, {"app_name": "a"}).num_iterations == 10


def test_missing_required_field_names_the_field():
    with pytest.raises(ExtractionError, match="app_name"):
        extract(Params, {"numIterations": 3})


def test_nested_dataclass_and_collections():
    p = extract(Params, {
        "appName": "a",
        "inner": {"name": "n"},
        "tags": ["x", "y"],
        "table": {"k": 2},          # int widens to float in the dict value
    })
    assert p.inner == Inner(name="n", weight=1.0)
    assert p.tags == ("x", "y")
    assert p.table == {"k": 2.0} and type(p.table["k"]) is float


def test_lenient_gson_shim_coercions():
    # the reference's Gson mode parses strings into numbers/bools
    assert extract(int, "3") == 3
    assert extract(float, "3.5") == 3.5
    assert extract(bool, "true") is True
    assert extract(float, 3) == 3.0            # JSON int → float always
    assert extract(int, 3.0) == 3              # integral float → int


def test_strict_json4s_mode_rejects_coercions():
    with pytest.raises(ExtractionError):
        extract(int, "3", lenient=False)
    with pytest.raises(ExtractionError):
        extract(bool, "true", lenient=False)
    # int→float widening stays: JSON itself cannot distinguish them
    assert extract(float, 3, lenient=False) == 3.0


def test_bool_is_never_a_number():
    with pytest.raises(ExtractionError):
        extract(int, True)
    with pytest.raises(ExtractionError):
        extract(float, True)


def test_union_and_optional():
    assert extract(Optional[int], None) is None
    assert extract(Optional[int], 4) == 4
    # first matching member wins; errors accumulate into the message
    with pytest.raises(ExtractionError, match="No member"):
        extract(Optional[int], [1])


def test_enum_by_value_and_name():
    assert extract(Color, "red") is Color.RED
    assert extract(Color, "BLUE") is Color.BLUE
    with pytest.raises(ExtractionError):
        extract(Color, "green")


def test_datetime_iso8601():
    dt = extract(datetime, "2024-02-03T04:05:06.000Z")
    assert dt == datetime(2024, 2, 3, 4, 5, 6, tzinfo=timezone.utc)
    with pytest.raises(ExtractionError):
        extract(datetime, "not-a-time")


def test_fixed_and_variadic_tuples():
    assert extract(Tuple[int, str], [1, "a"]) == (1, "a")
    assert extract(Tuple[int, ...], [1, 2, 3]) == (1, 2, 3)
    with pytest.raises(ExtractionError, match="2 elements"):
        extract(Tuple[int, str], [1])


def test_any_list_dict_passthrough():
    assert extract(Any, {"x": 1}) == {"x": 1}
    assert extract(List[int], [1, 2]) == [1, 2]
    assert extract(dict, {"a": 1}) == {"a": 1}


def test_extract_json_invalid_text():
    with pytest.raises(ExtractionError, match="Invalid JSON"):
        extract_json(Params, "{nope")


def test_round_trip_through_to_jsonable():
    p = Params(app_name="a", num_iterations=7, seed=3,
               inner=Inner(name="n", weight=0.5), tags=("t",),
               table={"k": 1.5})
    wire = to_jsonable(p)
    assert wire["appName"] == "a"           # camelCase on the wire
    assert wire["inner"] == {"name": "n", "weight": 0.5}
    assert extract(Params, wire) == p
    # dumps is json.dumps over to_jsonable
    assert '"appName": "a"' in dumps(p)


def test_to_jsonable_enum_and_datetime():
    assert to_jsonable(Color.RED) == "red"
    s = to_jsonable(datetime(2024, 1, 1, tzinfo=timezone.utc))
    assert s.startswith("2024-01-01T00:00:00")


def test_snake_to_camel():
    assert snake_to_camel("app_name") == "appName"
    assert snake_to_camel("a") == "a"
    assert snake_to_camel("num_iterations_total") == "numIterationsTotal"
