"""Regression template (models/regression) — the last missing mainline
algorithm family (parity: examples/experimental/scala-parallel-regression
+ scala-local-regression)."""

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams, MetricEvaluator
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.models.regression import (
    DataSourceParams,
    LinearAlgorithmParams,
    MeanSquareError,
    Query,
    RegressionEngine,
    SGDAlgorithmParams,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.workflow import CoreWorkflow

W_TRUE = np.array([2.0, -1.0, 0.5])
INTERCEPT = 0.7


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def seeded_app():
    Storage.get_meta_data_apps().insert(App(0, "regapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("regapp").id
    dao = Storage.get_events()
    rng = np.random.default_rng(1)
    for i in range(150):
        x = rng.normal(size=3)
        y = float(x @ W_TRUE + INTERCEPT + rng.normal(0, 0.05))
        dao.insert(Event(
            event="$set", entity_type="point", entity_id=f"p{i}",
            properties=DataMap({"label": y,
                                "features": [float(v) for v in x]}),
        ), app_id)
    return app_id


def params(eval_k=0, algos=("linear", "sgd")):
    algo_params = {
        "linear": LinearAlgorithmParams(l2=0.0),
        "sgd": SGDAlgorithmParams(num_iterations=300, step_size=0.1),
    }
    return EngineParams(
        data_source_params=("", DataSourceParams(app_name="regapp",
                                                 eval_k=eval_k)),
        algorithm_params_list=[(a, algo_params[a]) for a in algos],
    )


def test_linear_recovers_planted_weights(seeded_app):
    engine = RegressionEngine().apply()
    models = engine.train(RuntimeContext(), params(algos=("linear",)))
    w = np.asarray(models[0].weights)
    np.testing.assert_allclose(w[:3], W_TRUE, atol=0.05)
    assert abs(w[3] - INTERCEPT) < 0.05  # intercept last


def test_sgd_agrees_with_exact_solve(seeded_app):
    engine = RegressionEngine().apply()
    models = engine.train(RuntimeContext(), params())
    w_lin = np.asarray(models[0].weights)
    w_sgd = np.asarray(models[1].weights)
    np.testing.assert_allclose(w_sgd, w_lin, atol=0.1)


def test_average_serving_over_both_algorithms(seeded_app):
    engine = RegressionEngine().apply()
    ep = params()
    models = engine.train(RuntimeContext(), ep)
    algos = engine.algorithms(ep)
    q = Query(features=(1.0, 2.0, -1.0))
    preds = [a.predict(m, q) for a, m in zip(algos, models)]
    serving = engine.serving(ep)
    served = serving.serve(q, preds)
    assert served == pytest.approx(sum(preds) / 2)
    truth = float(np.array(q.features) @ W_TRUE + INTERCEPT)
    assert abs(served - truth) < 0.2


def test_file_datasource_reads_lr_data_format(tmp_path):
    # the reference examples' lr_data.txt shape: "label f0 f1 f2"
    rows = ["1.5 1.0 0.0 0.0", "0.5 0.0 1.0 0.0", "2.5 1.0 1.0 1.0"]
    path = tmp_path / "lr_data.txt"
    path.write_text("\n".join(rows) + "\n")
    from incubator_predictionio_tpu.models.regression.engine import (
        RegressionDataSource,
    )

    ds = RegressionDataSource(DataSourceParams(filepath=str(path)))
    td = ds.read_training(RuntimeContext())
    assert len(td.labeled_points) == 3
    assert td.labeled_points[0].label == 1.5
    assert td.labeled_points[2].features == (1.0, 1.0, 1.0)


def test_eval_workflow_mse(seeded_app, tmp_path):
    engine = RegressionEngine().apply()
    evaluation = Evaluation()
    evaluation.engine_evaluator = (
        engine, MetricEvaluator(MeanSquareError(),
                                output_path=str(tmp_path / "best.json")))
    good = params(eval_k=3, algos=("linear",))
    iid, result = CoreWorkflow.run_evaluation(evaluation, [good])
    # planted noise sigma = 0.05 → MSE floor ≈ 0.0025
    assert result.best_score.score < 0.01
    assert (tmp_path / "best.json").exists()


def test_wire_format_parity():
    from incubator_predictionio_tpu.utils import json_codec

    q = json_codec.extract(Query, {"features": [1.0, 2.0, 3.0]})
    assert q.features == (1.0, 2.0, 3.0)
    # predictions are bare doubles on the wire (the reference serves
    # Double through LAverageServing)
    assert json_codec.to_jsonable(1.25) == 1.25
