"""Async replication: a follower StorageServer tails the leader's
sharded event log over the wire (data/storage/server.py ReplicationTail).

Covers the ISSUE-17 replication contract: the VectorCursor 5-tuple
survives the wire round-trip, an env-gated follower catches up and
serves byte-parity reads, new leader writes drain continuously, and the
follower resynchronizes through both a leader RESTART (torn tail) and a
leader compaction (generation/epoch bump)."""

from datetime import timedelta

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import base, cpplog
from incubator_predictionio_tpu.data.storage import remote as remote_backend
from incubator_predictionio_tpu.data.storage.server import StorageServer
from incubator_predictionio_tpu.utils.times import parse_iso8601

pytestmark = pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native", fromlist=["load"]).load()
    is None,
    reason="native library unavailable",
)

T0 = parse_iso8601("2022-01-01T00:00:00Z")

SCAN_KW = dict(app_id=1, entity_type="user", target_entity_type="item",
               event_names=("rate",), value_prop="rating")


def _ev(eid, minutes=0, target="i0", rating=1.0):
    return Event(event="rate", entity_type="user", entity_id=eid,
                 target_entity_type="item", target_entity_id=target,
                 properties=DataMap({"rating": rating}),
                 event_time=T0 + timedelta(minutes=minutes))


def _parity(a, b):
    assert list(a.user_ids) == list(b.user_ids)
    assert np.array_equal(a.user_idx, b.user_idx)
    assert np.array_equal(a.item_idx, b.item_idx)
    assert np.array_equal(a.values, b.values)


@pytest.fixture
def leader(tmp_path, monkeypatch):
    """A 2-writer-shard leader behind a StorageServer, plus a
    RemoteEvents DAO pointed at it."""
    monkeypatch.setenv("PIO_LOG_SHARDS", "2")
    cfg = base.StorageClientConfig(
        parallel=False, test=True,
        properties={"PATH": str(tmp_path / "leader")})
    (tmp_path / "leader").mkdir()
    client = cpplog.StorageClient(cfg)
    server = StorageServer(cpplog, client, cfg, host="127.0.0.1", port=0)
    port = server.start_background()
    rc = remote_backend.StorageClient(base.StorageClientConfig(
        test=True, properties={"URL": f"http://127.0.0.1:{port}"}))
    revents = remote_backend.RemoteEvents(rc, rc.config, prefix="t_")
    revents.init(1)
    yield server, revents, port, tmp_path / "leader", client
    rc.close()
    server.stop()


def _start_follower(tmp_path, monkeypatch, lport):
    monkeypatch.setenv("PIO_REPLICATE_FROM", f"http://127.0.0.1:{lport}")
    monkeypatch.setenv("PIO_REPLICATE_APPS", "1")
    monkeypatch.setenv("PIO_REPLICATE_PREFIX", "t_")
    monkeypatch.setenv("PIO_REPLICATE_INTERVAL_S", "0.05")
    fdir = tmp_path / "follower"
    fdir.mkdir()
    fcfg = base.StorageClientConfig(
        parallel=False, test=True, properties={"PATH": str(fdir)})
    fclient = cpplog.StorageClient(fcfg)
    follower = StorageServer(cpplog, fclient, fcfg,
                             host="127.0.0.1", port=0)
    fport = follower.start_background()
    follower.maybe_start_replication()
    assert follower.replication is not None
    fc = remote_backend.StorageClient(base.StorageClientConfig(
        test=True, properties={"URL": f"http://127.0.0.1:{fport}"}))
    fevents = remote_backend.RemoteEvents(fc, fc.config, prefix="t_")
    return follower, fevents, fc


def test_vector_cursor_survives_the_wire(leader):
    _server, revents, _port, _dir, _client = leader
    ids = revents.insert_batch(
        [_ev(f"u{i}", i, target=f"i{i % 3}", rating=float(i % 5) + 0.5)
         for i in range(40)], 1)
    assert len(ids) == 40
    cur = revents.tail_cursor(app_id=1)
    assert isinstance(cur, base.VectorCursor)
    assert len(cur) == 2  # one component per writer shard
    inter, _times, append_ms, cur2, reset = revents.read_interactions_since(
        base.VectorCursor((0, 0)), **SCAN_KW)
    assert isinstance(cur2, base.VectorCursor) and not reset
    assert len(inter) == 40 and len(append_ms) == 40
    assert cur2 == cur
    inter3, _t, _a, cur3, _r = revents.read_interactions_since(
        cur2, **SCAN_KW)
    assert len(inter3) == 0 and cur3 == cur2


def test_follower_catches_up_and_drains_new_writes(
        leader, tmp_path, monkeypatch):
    _server, revents, lport, _dir, _client = leader
    revents.insert_batch(
        [_ev(f"u{i}", i, target=f"i{i % 3}", rating=float(i % 5) + 0.5)
         for i in range(40)], 1)
    follower, fevents, fc = _start_follower(tmp_path, monkeypatch, lport)
    try:
        assert follower.replication.wait_caught_up(timeout_s=30)
        assert follower.replication._lag_total(1) == 0
        _parity(fevents.scan_interactions(**SCAN_KW),
                revents.scan_interactions(**SCAN_KW))
        # continuous drain: new leader writes appear on the follower
        revents.insert_batch(
            [_ev(f"u{i}", 100 + i) for i in range(40, 55)], 1)
        assert follower.replication.wait_caught_up(timeout_s=30)
        assert len(fevents.scan_interactions(**SCAN_KW)) == 55
    finally:
        fc.close()
        follower.stop()


def test_follower_resyncs_after_leader_restart(
        leader, tmp_path, monkeypatch):
    """Kill the leader mid-replication, bring it back ON THE SAME PORT
    with the same directory, keep writing: the tail must ride through
    the connection errors and converge on the superset (the torn-tail /
    epoch resync path)."""
    server, revents, lport, ldir, _client = leader
    revents.insert_batch([_ev(f"u{i}", i) for i in range(30)], 1)
    follower, fevents, fc = _start_follower(tmp_path, monkeypatch, lport)
    server2 = None
    try:
        assert follower.replication.wait_caught_up(timeout_s=30)
        server.stop()
        cfg2 = base.StorageClientConfig(
            parallel=False, test=True, properties={"PATH": str(ldir)})
        client2 = cpplog.StorageClient(cfg2)
        server2 = StorageServer(cpplog, client2, cfg2,
                                host="127.0.0.1", port=lport)
        assert server2.start_background() == lport
        rc2 = remote_backend.StorageClient(base.StorageClientConfig(
            test=True, properties={"URL": f"http://127.0.0.1:{lport}"}))
        try:
            rev2 = remote_backend.RemoteEvents(rc2, rc2.config, prefix="t_")
            rev2.insert_batch(
                [_ev(f"u{i}", 200 + i) for i in range(30, 45)], 1)
            assert follower.replication.wait_caught_up(timeout_s=30)
            _parity(fevents.scan_interactions(**SCAN_KW),
                    rev2.scan_interactions(**SCAN_KW))
            assert len(fevents.scan_interactions(**SCAN_KW)) == 45
        finally:
            rc2.close()
    finally:
        fc.close()
        follower.stop()
        if server2 is not None:
            server2.stop()


def test_follower_resyncs_after_leader_compaction(
        leader, tmp_path, monkeypatch):
    """Leader-side compaction renumbers entries under the follower's
    cursor (generation/epoch bump): the tail must detect it, resync the
    affected shards, and converge rather than diverge or wedge."""
    _server, revents, lport, _dir, lclient = leader
    ids = revents.insert_batch([_ev(f"u{i}", i) for i in range(30)], 1)
    follower, fevents, fc = _start_follower(tmp_path, monkeypatch, lport)
    try:
        assert follower.replication.wait_caught_up(timeout_s=30)
        for eid in ids[::3]:
            assert revents.delete(eid, 1)
        # compaction is an operator-side op on the storage host itself
        ldao = cpplog.CppLogEvents(lclient, None, prefix="t_")
        stats = ldao.compact(1)
        assert stats["events"] > 0
        revents.insert_batch(
            [_ev(f"u{i}", 300 + i) for i in range(30, 40)], 1)
        assert follower.replication.wait_caught_up(timeout_s=30)
        _parity(fevents.scan_interactions(**SCAN_KW),
                revents.scan_interactions(**SCAN_KW))
    finally:
        fc.close()
        follower.stop()
