"""CLI verb coverage (parity: tools/.../console/Console.scala matrix +
the integration suite's BasicAppUsecases)."""

import json
import os

import numpy as np
import pytest

from incubator_predictionio_tpu.cli.main import main
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import Storage


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_version_and_help():
    assert main(["version"]) == 0
    assert main([]) == 1


def test_status():
    assert main(["status"]) == 0


def test_app_lifecycle(capsys):
    assert main(["app", "new", "CliApp", "--description", "d"]) == 0
    out = capsys.readouterr().out
    assert "Access Key:" in out
    # duplicate fails
    assert main(["app", "new", "CliApp"]) == 1
    assert main(["app", "list"]) == 0
    assert "CliApp" in capsys.readouterr().out
    assert main(["app", "show", "CliApp"]) == 0
    # channels
    assert main(["app", "channel-new", "CliApp", "chan-a"]) == 0
    assert main(["app", "channel-new", "CliApp", "chan-a"]) == 1  # dup
    assert main(["app", "channel-new", "CliApp", "bad name!"]) == 1
    assert main(["app", "channel-delete", "CliApp", "chan-a", "-f"]) == 0
    assert main(["app", "channel-delete", "CliApp", "ghost", "-f"]) == 1
    # data + delete
    assert main(["app", "data-delete", "CliApp", "-f"]) == 0
    assert main(["app", "delete", "CliApp", "-f"]) == 0
    assert main(["app", "show", "CliApp"]) == 1


def test_accesskey_lifecycle(capsys):
    main(["app", "new", "KeyApp"])
    capsys.readouterr()
    assert main(["accesskey", "new", "KeyApp", "--key", "my-key",
                 "--events", "rate", "buy"]) == 0
    assert main(["accesskey", "list", "KeyApp"]) == 0
    out = capsys.readouterr().out
    assert "my-key" in out and "rate, buy" in out
    assert main(["accesskey", "delete", "my-key"]) == 0
    assert main(["accesskey", "delete", "my-key"]) == 1
    assert main(["accesskey", "new", "GhostApp"]) == 1


def test_import_export_round_trip(tmp_path, capsys):
    main(["app", "new", "IOApp"])
    src = tmp_path / "events.jsonl"
    events = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": i}, "eventTime": "2020-01-01T00:00:00.000Z"}
        for i in range(5)
    ]
    src.write_text("\n".join(json.dumps(e) for e in events))
    assert main(["import", "--appid-or-name", "IOApp",
                 "--input", str(src)]) == 0
    dst = tmp_path / "out.jsonl"
    assert main(["export", "--appid-or-name", "IOApp",
                 "--output", str(dst)]) == 0
    lines = [json.loads(l) for l in dst.read_text().splitlines()]
    assert len(lines) == 5
    assert {l["entityId"] for l in lines} == {f"u{i}" for i in range(5)}
    # malformed line fails loudly with position
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"entityType": "user"}\n')
    assert main(["import", "--appid-or-name", "IOApp",
                 "--input", str(bad)]) == 1


def test_parquet_export_import_round_trip(tmp_path, capsys):
    """--format parquet on both verbs (EventsToFile.scala:44 parity),
    preserving properties / tags / prId / times through the round trip."""
    pytest.importorskip("pyarrow")
    main(["app", "new", "PqApp"])
    main(["app", "new", "PqApp2"])
    from incubator_predictionio_tpu.data.store import EventStore

    EventStore.write([
        Event(event="rate", entity_type="user", entity_id=f"u{i}",
              target_entity_type="item", target_entity_id="i1",
              properties=DataMap({"rating": float(i), "nested": {"a": [i]}}),
              tags=("t1", "t2"), pr_id="pr-9" if i == 0 else None)
        for i in range(3)
    ], app_name="PqApp")
    pq_file = tmp_path / "events.parquet"
    assert main(["export", "--appid-or-name", "PqApp",
                 "--output", str(pq_file), "--format", "parquet"]) == 0
    assert pq_file.stat().st_size > 0
    assert main(["import", "--appid-or-name", "PqApp2",
                 "--input", str(pq_file), "--format", "parquet"]) == 0
    got = sorted(EventStore.find(app_name="PqApp2"),
                 key=lambda e: e.entity_id)
    assert [e.entity_id for e in got] == ["u0", "u1", "u2"]
    assert got[1].properties.get("rating") == 1.0
    assert got[1].properties.get("nested") == {"a": [1]}
    assert got[0].tags == ("t1", "t2")
    assert got[0].pr_id == "pr-9"
    assert got[2].event_time is not None


def _seed_quickstart_events(app_name):
    from incubator_predictionio_tpu.data.store import EventStore

    rng = np.random.default_rng(0)
    events = []
    for u in range(30):
        for i in rng.choice(20, size=8, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
            ))
    EventStore.write(events, app_name=app_name)


def test_build_train_from_engine_json(tmp_path, monkeypatch, capsys):
    main(["app", "new", "MyApp1"])
    _seed_quickstart_events("MyApp1")
    variant = {
        "id": "cli-test",
        "engineFactory":
            "incubator_predictionio_tpu.models.recommendation:RecommendationEngine",
        "datasource": {"params": {"appName": "MyApp1"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 8, "numIterations": 5, "lambda": 0.05, "seed": 1,
        }}],
    }
    (tmp_path / "engine.json").write_text(json.dumps(variant))
    monkeypatch.chdir(tmp_path)
    assert main(["build"]) == 0
    assert main(["train"]) == 0
    out = capsys.readouterr().out
    assert "Engine instance ID:" in out
    from incubator_predictionio_tpu.cli.commands import (
        engine_id_for_variant_path,
    )
    # engine identity is directory-derived (manifest-id semantics), the
    # variant id only names the params variant — two engines shipping the
    # default variant id must not collide in the instance registry
    latest = Storage.get_meta_data_engine_instances().get_latest_completed(
        engine_id_for_variant_path(str(tmp_path / "engine.json"), variant),
        "NOT_VERSIONED", "cli-test"
    )
    assert latest is not None
    assert latest.status == "COMPLETED"
    # camelCase params round-trip through the stored instance
    assert '"numIterations": 5' in latest.algorithms_params
    from incubator_predictionio_tpu.cli import commands as cli_commands
    engine, _ = cli_commands.engine_from_variant(variant)
    restored = engine.engine_params_from_instance(latest)
    assert restored.algorithm_params_list[0][1].num_iterations == 5
    assert restored.algorithm_params_list[0][1].lambda_ == 0.05


def test_train_missing_engine_json(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert main(["train"]) == 1
    assert main(["build"]) == 1


def test_eval_via_cli(tmp_path, monkeypatch, capsys):
    main(["app", "new", "MyApp1"])
    _seed_quickstart_events("MyApp1")
    repo_examples = os.path.join(os.path.dirname(__file__), "..", "examples",
                                 "recommendation-quickstart")
    monkeypatch.chdir(repo_examples)
    monkeypatch.setattr("sys.path", ["."] + __import__("sys").path)
    assert main(["eval", "evaluation:evaluation",
                 "evaluation:engine_params_generator",
                 "--output-best", str(tmp_path / "best.json")]) == 0
    out = capsys.readouterr().out
    assert "Evaluation completed" in out
    assert (tmp_path / "best.json").exists()
    best = json.loads((tmp_path / "best.json").read_text())
    assert best["algorithmParamsList"][0]["name"] == "als"


def test_undeploy_nothing_running():
    assert main(["undeploy", "--port", "59999"]) == 1


def test_import_fast_path_uniform_batch(tmp_path, capsys, monkeypatch):
    """A uniform id-less interaction batch routes through the backend's
    native columnar import (cpplog), and the events remain readable
    through the generic query path."""
    from incubator_predictionio_tpu.cli import commands
    from incubator_predictionio_tpu import native

    if native.load() is None:
        pytest.skip("native library unavailable")
    Storage.reset()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / "ev"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    monkeypatch.setattr(commands, "_FAST_IMPORT_MIN", 10)
    main(["app", "new", "FastApp"])
    capsys.readouterr()
    src = tmp_path / "events.jsonl"
    docs = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i % 7}",
         "targetEntityType": "item", "targetEntityId": f"i{i % 5}",
         "properties": {"rating": float(1 + i % 4)},
         "eventTime": f"2020-01-01T00:00:{i % 60:02d}.000Z"}
        for i in range(60)
    ]
    src.write_text("\n".join(json.dumps(d) for d in docs))
    assert main(["import", "--appid-or-name", "FastApp",
                 "--input", str(src)]) == 0
    assert "native columnar path" in capsys.readouterr().out
    inter = Storage.get_events().scan_interactions(
        app_id=1, entity_type="user", target_entity_type="item",
        event_names=("rate",), value_prop="rating")
    assert len(inter) == 60
    evs = list(Storage.get_events().find(app_id=1, limit=100))
    assert len(evs) == 60 and all(e.event == "rate" for e in evs)

    # events WITH ids must keep the per-event path (id-preserving upsert)
    src2 = tmp_path / "with_ids.jsonl"
    docs2 = [dict(d, eventId=f"e{i:032d}") for i, d in enumerate(docs)]
    src2.write_text("\n".join(json.dumps(d) for d in docs2))
    assert main(["import", "--appid-or-name", "FastApp",
                 "--input", str(src2)]) == 0
    out = capsys.readouterr().out
    assert "native columnar path" not in out
    assert Storage.get_events().get(
        "e" + "0" * 31 + "0", 1) is not None  # explicit id preserved


def test_accelerator_watchdog_times_out_and_propagates_errors(monkeypatch):
    """A chip claimed by another process blocks device init forever; the
    probe must turn that into an actionable error, and real init errors
    must surface as themselves."""
    import time

    from incubator_predictionio_tpu.cli import main as climain
    import jax

    monkeypatch.setattr(jax, "devices", lambda: time.sleep(30))
    with pytest.raises(climain.CommandError, match="holds the chip"):
        climain._ensure_accelerator(0.2)

    def boom():
        raise RuntimeError("no backend at all")

    monkeypatch.setattr(jax, "devices", boom)
    with pytest.raises(climain.CommandError,
                       match="initialization failed.*no backend"):
        climain._ensure_accelerator(5.0)

    monkeypatch.setattr(jax, "devices", lambda: ["dev0"])
    climain._ensure_accelerator(5.0)  # healthy path: no raise


def test_fast_import_then_export_roundtrip(tmp_path, capsys, monkeypatch):
    """Events landed via the columnar fast path are compact (sidecar-only)
    records; export must render them as full canonical JSON events."""
    from incubator_predictionio_tpu import native
    from incubator_predictionio_tpu.cli import commands

    if native.load() is None:
        pytest.skip("native library unavailable")
    Storage.reset()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / "ev"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    monkeypatch.setattr(commands, "_FAST_IMPORT_MIN", 10)
    main(["app", "new", "RoundTrip"])
    capsys.readouterr()
    src = tmp_path / "in.jsonl"
    docs = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i % 4}",
         "targetEntityType": "item", "targetEntityId": f"i{i % 3}",
         "properties": {"rating": float(1 + i % 5)},
         "eventTime": f"2021-05-01T00:00:{i % 60:02d}.000Z"}
        for i in range(30)
    ]
    src.write_text("\n".join(json.dumps(d) for d in docs))
    assert main(["import", "--appid-or-name", "RoundTrip",
                 "--input", str(src)]) == 0
    assert "native columnar path" in capsys.readouterr().out
    dst = tmp_path / "out.jsonl"
    assert main(["export", "--appid-or-name", "RoundTrip",
                 "--output", str(dst)]) == 0
    lines = [json.loads(l) for l in dst.read_text().splitlines()]
    assert len(lines) == 30
    for got, want in zip(lines, docs):
        assert got["event"] == want["event"]
        assert got["entityId"] == want["entityId"]
        assert got["targetEntityId"] == want["targetEntityId"]
        assert got["properties"] == want["properties"]
        assert got["eventTime"].startswith(want["eventTime"][:19])
        assert len(got["eventId"]) == 32  # generated ids present
    # and the exported file re-imports cleanly (per-event path: it now
    # carries eventIds)
    assert main(["import", "--appid-or-name", "RoundTrip",
                 "--input", str(dst)]) == 0
    out = capsys.readouterr().out
    assert "native columnar path" not in out  # ids force the upsert path
