"""Pallas kernel correctness vs the dense XLA references.

Runs in interpret mode on the CPU test mesh (conftest pins JAX_PLATFORMS=cpu);
the same kernels compile with Mosaic on real TPU — mirroring how the
reference validates distributed behavior on local[4] Spark before a real
cluster (reference: core/src/test/.../workflow/BaseTest.scala:71-88).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.ops.attention import dot_product_attention
from incubator_predictionio_tpu.ops.pallas_kernels import (
    flash_attention,
    score_and_top_k_pallas,
)
from incubator_predictionio_tpu.ops.topk import score_and_top_k


def _rand(key, *shape):
    return jax.random.normal(jax.random.key(key), shape, jnp.float32)


class TestPallasTopK:
    def test_matches_xla_reference(self):
        items = _rand(0, 500, 24)
        user = _rand(1, 24)
        ref = np.asarray(score_and_top_k(user, items, k=7))
        got = np.asarray(score_and_top_k_pallas(
            user, items, k=7, interpret=True, block_items=128))
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-5)
        np.testing.assert_array_equal(got[1], ref[1])

    def test_exclusions_cannot_displace_candidates(self):
        # exclude more items than one block's candidate budget — the dense
        # in-kernel mask must keep results exact anyway
        items = _rand(2, 300, 16)
        user = _rand(3, 16)
        exclude = jnp.arange(250, dtype=jnp.int32)  # only 50 items remain
        ref = np.asarray(score_and_top_k(user, items, k=5, exclude=exclude))
        got = np.asarray(score_and_top_k_pallas(
            user, items, k=5, exclude=exclude, interpret=True,
            block_items=128))
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-5)
        np.testing.assert_array_equal(got[1], ref[1])

    def test_allowed_mask_and_negative_exclude(self):
        items = _rand(4, 260, 8)
        user = _rand(5, 8)
        mask = np.ones(260, bool)
        mask[::3] = False
        exclude = jnp.asarray([-1, 7, -1, 11], jnp.int32)
        ref = np.asarray(score_and_top_k(
            user, items, k=4, exclude=exclude,
            allowed_mask=jnp.asarray(mask)))
        got = np.asarray(score_and_top_k_pallas(
            user, items, k=4, exclude=exclude,
            allowed_mask=jnp.asarray(mask), interpret=True,
            block_items=128))
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-5)
        np.testing.assert_array_equal(got[1], ref[1])

    def test_k_exceeding_allowed_returns_neg_inf_fillers(self):
        items = _rand(6, 40, 8)
        user = _rand(7, 8)
        mask = np.zeros(40, bool)
        mask[:3] = True  # only 3 allowed, ask for 6
        got = np.asarray(score_and_top_k_pallas(
            user, items, k=6, allowed_mask=jnp.asarray(mask),
            interpret=True, block_items=128))
        assert (got[0][3:] <= -1e37).all()
        # filler slots must never leak padding item ids (>= n_items)
        np.testing.assert_array_equal(got[1][3:], -1)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q = _rand(10, 2, 100, 2, 32)
        k = _rand(11, 2, 100, 2, 32)
        v = _rand(12, 2, 100, 2, 32)
        ref = dot_product_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal, interpret=True,
                              q_block=32, kv_block=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_ragged_kv_valid(self):
        q = _rand(13, 2, 40, 2, 16)
        k = _rand(14, 2, 40, 2, 16)
        v = _rand(15, 2, 40, 2, 16)
        valid = np.zeros((2, 40), bool)
        valid[0, :17] = True
        valid[1, :33] = True
        ref = dot_product_attention(q, k, v, causal=True,
                                    kv_valid=jnp.asarray(valid))
        got = flash_attention(q, k, v, causal=True,
                              kv_valid=jnp.asarray(valid), interpret=True,
                              q_block=16, kv_block=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_fully_masked_rows_are_zero(self):
        # with causal + all keys invalid, output must be exactly 0 (the
        # invariant ring attention relies on), not NaN
        q = _rand(16, 1, 8, 1, 16)
        k = _rand(17, 1, 8, 1, 16)
        v = _rand(18, 1, 8, 1, 16)
        valid = jnp.zeros((1, 8), bool)
        got = np.asarray(flash_attention(
            q, k, v, causal=True, kv_valid=valid, interpret=True,
            q_block=8, kv_block=8))
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got, np.zeros_like(got))

    def test_decode_single_query_row(self):
        q = _rand(19, 1, 1, 2, 32)
        k = _rand(20, 1, 64, 2, 32)
        v = _rand(21, 1, 64, 2, 32)
        # a length-1 query attending over a 64-long KV cache, non-causal
        ref = dot_product_attention(q, k, v, causal=False)
        got = flash_attention(q, k, v, causal=False, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)


class TestFlashAttentionGrad:
    def test_grad_matches_dense_reference(self):
        # sequence engines train THROUGH the attention op — the fused
        # kernel must be differentiable (custom VJP via the blockwise path)
        q = _rand(30, 1, 24, 2, 16)
        k = _rand(31, 1, 24, 2, 16)
        v = _rand(32, 1, 24, 2, 16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, interpret=True,
                q_block=8, kv_block=8) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        gq, gk, gv = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=3e-5)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=3e-5)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=3e-5)


class TestAlsCgKernel:
    """Fused bucket solve (Gram + CG in VMEM) vs the XLA assembly path."""

    def _problem(self, seed=0, M=400, K=64, B=24, D=48):
        rng = np.random.default_rng(seed)
        table = rng.normal(0, 0.3, (M, K)).astype(np.float32)
        cols = rng.integers(0, M, (B, D)).astype(np.int32)
        vals = rng.normal(3.5, 1.0, (B, D)).astype(np.float32)
        mask = (rng.random((B, D)) < 0.8).astype(np.float32)
        mask[3] = 0.0  # empty row must solve to exactly 0
        return table, cols, vals, mask

    @pytest.mark.parametrize("rows", [1, 8])
    @pytest.mark.parametrize("dtype,prec,tol", [
        (jnp.float32, jax.lax.Precision.HIGHEST, 1e-4),
        (jnp.bfloat16, jax.lax.Precision.DEFAULT, 2e-2),
    ])
    def test_matches_solve_bucket(self, dtype, prec, tol, rows):
        from incubator_predictionio_tpu.ops import als
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            als_solve_cg_pallas,
        )

        table, cols, vals, mask = self._problem()
        src = jnp.asarray(table).astype(dtype)
        ref = als._solve_bucket(
            src, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            0.1, reg_nnz=True, compute_dtype=dtype, precision=prec,
            cg_iters=16)
        got = als_solve_cg_pallas(
            src, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            0.1, reg_nnz=True, iters=16, interpret=True,
            rows_per_program=rows)
        rel = float(jnp.max(jnp.abs(ref - got))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        assert rel < tol, rel
        assert bool(jnp.all(got[3] == 0.0))

    @pytest.mark.parametrize("rows", [1, 8])
    def test_multi_tile_d_and_no_reg_nnz(self, rows):
        """D=1024 streams two 512-wide tiles through the accumulator;
        B=13 forces row-group padding in the grouped variant."""
        from incubator_predictionio_tpu.ops import als
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            als_solve_cg_pallas,
        )

        table, cols, vals, mask = self._problem(seed=1, M=600, K=32, B=13,
                                                D=1024)
        src = jnp.asarray(table)
        ref = als._solve_bucket(
            src, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            0.05, reg_nnz=False, cg_iters=16)
        got = als_solve_cg_pallas(
            src, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            0.05, reg_nnz=False, iters=16, interpret=True,
            rows_per_program=rows)
        rel = float(jnp.max(jnp.abs(ref - got))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        assert rel < 1e-4, rel

    _PARITY_CACHE: dict = {}

    def _parity_problem(self, als):
        """Planted problem + the rows-independent XLA reference, computed
        once and shared by both layout params (the baseline runs with the
        kernel off, so re-training it per param would be pure waste)."""
        if not self._PARITY_CACHE:
            rng = np.random.default_rng(7)
            n_u, n_i, k_true, nnz = 120, 60, 4, 4000
            u = rng.normal(0, 1, (n_u, k_true)).astype(np.float32)
            v = rng.normal(0, 1, (n_i, k_true)).astype(np.float32)
            users = rng.integers(0, n_u, nnz).astype(np.int32)
            items = rng.integers(0, n_i, nnz).astype(np.int32)
            ratings = np.einsum("nk,nk->n", u[users], v[items]).astype(
                np.float32)
            kw = dict(n_users=n_u, n_items=n_i, rank=16, iterations=8,
                      l2=0.02, bf16_sweeps=4, max_width=64)
            old = als._ALS_KERNEL
            als._ALS_KERNEL = "off"
            try:
                st_xla, _ = als.als_train(users, items, ratings, **kw)
            finally:
                als._ALS_KERNEL = old
            self._PARITY_CACHE.update(
                users=users, items=items, ratings=ratings, kw=kw,
                st_xla=st_xla)
        c = self._PARITY_CACHE
        return c["users"], c["items"], c["ratings"], c["kw"], c["st_xla"]

    @pytest.mark.parametrize("rows", [1, 8])
    def test_full_training_parity(self, monkeypatch, rows):
        """als_train with the kernel forced on (interpret on CPU) reaches
        the same fit as the XLA path — the planted-recovery guarantee
        holds through the fused solve, including the mixed bf16+f32
        schedule and the split-row heavy path (max_width forces splits),
        in BOTH program layouts."""
        from incubator_predictionio_tpu.ops import als
        from incubator_predictionio_tpu.ops import pallas_kernels as pk
        monkeypatch.setattr(pk, "_ALS_ROWS", rows)

        users, items, ratings, kw, st_xla = self._parity_problem(als)
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        # this problem's buckets are narrower than the default min-D
        # routing cut — force every bucket through the kernel
        monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
        st_krn, _ = als.als_train(users, items, ratings, **kw)
        r_xla = als.rmse(st_xla, users, items, ratings)
        r_krn = als.rmse(st_krn, users, items, ratings)
        # both fit the planted structure; the kernel keeps its Gram in f32
        # so it may be (slightly) more accurate than the bf16 XLA path
        assert r_krn < max(1.15 * r_xla, r_xla + 0.02), (r_krn, r_xla)
        assert r_krn < 0.1, r_krn

    @pytest.mark.parametrize("fused_mode", ["on", "off"])
    def test_min_d_routing(self, monkeypatch, fused_mode):
        """With the kernel enabled, buckets narrower than _KERNEL_MIN_D
        stay on the XLA path (the padding tax region) while wide buckets
        route through the fused solve — decided per bucket at trace
        time, in BOTH kernel generations (fused gather vs two-stage)."""
        from incubator_predictionio_tpu.ops import als

        monkeypatch.setenv("PIO_ALS_FUSED_GRAM", fused_mode)
        widths = []
        real = als._solve_bucket_kernel
        real_fused = als._solve_bucket_fused

        def spy(gsrc, cols, vals, mask, l2, reg_nnz, cg_iters,
                kernel_rows=1, x0=None):
            assert fused_mode == "off", "two-stage kernel ran in fused mode"
            widths.append(cols.shape[1])
            return real(gsrc, cols, vals, mask, l2, reg_nnz=reg_nnz,
                        cg_iters=cg_iters, kernel_rows=kernel_rows, x0=x0)

        def spy_fused(gsrc, yty, cols, vals, mask, l2, reg_nnz, cg_iters,
                      implicit=False, alpha=0.0, x0=None):
            assert fused_mode == "on", "fused kernel ran while forced off"
            widths.append(cols.shape[1])
            return real_fused(gsrc, yty, cols, vals, mask, l2,
                              reg_nnz=reg_nnz, cg_iters=cg_iters,
                              implicit=implicit, alpha=alpha, x0=x0)

        monkeypatch.setattr(als, "_solve_bucket_kernel", spy)
        monkeypatch.setattr(als, "_solve_bucket_fused", spy_fused)
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        monkeypatch.setattr(als, "_KERNEL_MIN_D", 64)

        rng = np.random.default_rng(3)
        n_u, n_i = 300, 40
        # ~85% of users rate <16 items (narrow buckets), a few rate 100+
        # (wide buckets) — both routing branches must appear
        degs = np.where(rng.random(n_u) < 0.85, rng.integers(2, 12, n_u),
                        rng.integers(100, 160, n_u)).astype(np.int64)
        users = np.repeat(np.arange(n_u, dtype=np.int32), degs)
        items = rng.integers(0, n_i, len(users)).astype(np.int32)
        ratings = rng.normal(3.5, 1.0, len(users)).astype(np.float32)
        als.als_train(users, items, ratings, n_users=n_u, n_items=n_i,
                      rank=8, iterations=1, l2=0.05)
        assert widths, "no bucket routed through the kernel"
        assert all(w >= 64 for w in widths), widths


def test_flash_block_table_selection(monkeypatch):
    """default_flash_blocks picks the measured per-length optimum and the
    PIO_FLASH_BLOCKS override parses (malformed values fall back)."""
    from incubator_predictionio_tpu.ops import pallas_kernels as pk

    assert pk.default_flash_blocks(1024) == (2048, 512)
    assert pk.default_flash_blocks(8192) == (2048, 512)
    assert pk.default_flash_blocks(8193) == (1024, 1024)
    assert pk.default_flash_blocks(16384) == (1024, 1024)
    assert pk.default_flash_blocks(1 << 20) == (1024, 1024)

    monkeypatch.setenv("PIO_FLASH_BLOCKS", "4096:256x512,16384:512x1024")
    parsed = pk._parse_block_env()
    assert parsed == ((4096, 256, 512), (1 << 62, 512, 1024))

    monkeypatch.setenv("PIO_FLASH_BLOCKS", "garbage")
    assert pk._parse_block_env() is None


def test_als_probe_compiles_the_variant_the_caller_runs(monkeypatch):
    """als_kernel_available(warm=...) must probe the EXACT kernel variant
    the caller will dispatch (warm adds the x0 operand — a different
    Mosaic kernel) and cache per variant, so a cold-only probe can never
    green-light a warm run or vice versa (the ADVICE.md round-5 probe
    gap)."""
    from incubator_predictionio_tpu.ops import pallas_kernels as pk

    probed = []

    def fake_probe(fn, what):
        probed.append(what)
        return True

    monkeypatch.setattr(pk, "pallas_available", lambda: True)
    monkeypatch.setattr(pk, "_probe_kernel_runs", fake_probe)
    monkeypatch.setattr(pk, "_als_ok", {})

    assert pk.als_kernel_available(warm=True)
    assert pk.als_kernel_available(warm=False)
    assert pk.als_kernel_available(warm=True)   # cached, no new probe
    assert probed == ["ALS bucket CG solve (warm)",
                      "ALS bucket CG solve (cold)"]
    # the fused-gather generation is a DIFFERENT kernel family again
    # (in-kernel jnp.take gather; implicit adds the yty operand) — each
    # (warm, fused, implicit) variant probes and caches separately, so
    # production can never run a fused/implicit kernel the probe only
    # green-lit in its two-stage/explicit form
    assert pk.als_kernel_available(warm=True, fused=True)
    assert pk.als_kernel_available(warm=False, fused=True, implicit=True)
    assert pk.als_kernel_available(warm=True, fused=True)  # cached
    assert probed[2:] == [
        "ALS fused gather+Gram CG solve (warm)",
        "ALS fused gather+Gram CG solve (cold, implicit)"]
