"""Engine wiring tests (parity: core/src/test/.../controller/EngineTest.scala)."""

import pytest

from fake_engine import (
    AP,
    DSP,
    PP,
    SP,
    Algorithm0,
    Algorithm1,
    DataSource0,
    FailingDataSource,
    Model,
    NoArgDataSource,
    Preparator0,
    Prediction,
    Query,
    SanityFailDataSource,
    Serving0,
    SupplementServing,
    make_engine,
)
from incubator_predictionio_tpu.core import (
    EmptyParams,
    Engine,
    EngineParams,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
    doer,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext


@pytest.fixture
def ctx():
    return RuntimeContext()


def params(ds=1, pp=2, algos=(("algo0", AP(3)),), sp=4):
    return EngineParams(
        data_source_params=("", DSP(ds)),
        preparator_params=("", PP(pp)),
        algorithm_params_list=list(algos),
        serving_params=("", SP(sp)),
    )


def test_train_single_algo(ctx):
    models = make_engine().train(ctx, params())
    assert models == [Model(ds_id=1, pp_id=2, ap_id=3)]


def test_train_multi_algo_ordering(ctx):
    ep = params(algos=[("algo0", AP(10)), ("algo1", AP(20)), ("algo0", AP(30))])
    models = make_engine().train(ctx, ep)
    assert models == [
        Model(1, 2, 10),
        Model(1, 2, 120),  # Algorithm1 encodes 100+id
        Model(1, 2, 30),
    ]


def test_train_propagates_params(ctx):
    models = make_engine().train(ctx, params(ds=7, pp=8, algos=[("algo0", AP(9))]))
    assert models == [Model(7, 8, 9)]


def test_unknown_algo_name(ctx):
    with pytest.raises(ValueError, match="algorithm"):
        make_engine().train(ctx, params(algos=[("nope", AP(1))]))


def test_single_class_map_accepts_empty_name(ctx):
    engine = Engine(DataSource0, Preparator0, Algorithm0, Serving0)
    models = engine.train(ctx, params(algos=[("", AP(5))]))
    assert models == [Model(1, 2, 5)]


def test_stop_after_read_and_prepare(ctx):
    e = make_engine()
    with pytest.raises(StopAfterReadInterruption):
        e.train(ctx, params(), WorkflowParams(stop_after_read=True))
    with pytest.raises(StopAfterPrepareInterruption):
        e.train(ctx, params(), WorkflowParams(stop_after_prepare=True))


def test_sanity_check_runs_and_can_be_skipped(ctx):
    engine = Engine(SanityFailDataSource, Preparator0, Algorithm0, Serving0)
    ep = params(algos=[("", AP(1))])
    with pytest.raises(ValueError, match="sanity failed"):
        engine.train(ctx, ep)
    # SanityFailDataSource's TD can't prepare (wrong type), so stop right after read
    with pytest.raises(StopAfterReadInterruption):
        engine.train(
            ctx, ep,
            WorkflowParams(skip_sanity_check=True, stop_after_read=True),
        )


def test_data_source_error_propagates(ctx):
    engine = Engine(FailingDataSource, Preparator0, Algorithm0, Serving0)
    with pytest.raises(RuntimeError, match="data source boom"):
        engine.train(ctx, params(algos=[("", AP(1))]))


def test_doer_no_arg_constructor(ctx):
    got = doer(NoArgDataSource, EmptyParams())
    assert isinstance(got, NoArgDataSource)
    engine = Engine(NoArgDataSource, Preparator0, Algorithm0, Serving0)
    models = engine.train(ctx, params(algos=[("", AP(1))]))
    assert models[0].ds_id == -99


def test_eval_shape_and_join(ctx):
    ep = params(algos=[("algo0", AP(1)), ("algo1", AP(2))])
    result = make_engine().eval(ctx, ep)
    assert len(result) == 2  # two eval sets from DataSource0
    for ex, (info, qpas) in enumerate(result):
        assert info.ex == ex
        assert len(qpas) == 3
        for q, p, a in qpas:
            assert isinstance(p, Prediction)
            assert q.qx == a.qx  # join preserved the pairing
            assert p.model.ap_id == 1  # Serving0 returns first algo's prediction


def test_eval_serving_sees_original_query(ctx):
    engine = Engine(DataSource0, Preparator0, {"algo0": Algorithm0}, SupplementServing)
    result = engine.eval(ctx, params(algos=[("algo0", AP(1))]))
    # algorithms saw the supplemented query (qx+1000)
    for _info, qpas in result:
        for q, p, _a in qpas:
            assert p.qx == q.qx + 1000


def test_batch_eval_per_candidate(ctx):
    eps = [params(algos=[("algo0", AP(i))]) for i in (1, 2, 3)]
    out = make_engine().batch_eval(RuntimeContext(), eps)
    assert [ep.algorithm_params_list[0][1].id for ep, _ in out] == [1, 2, 3]
    for _ep, data in out:
        assert len(data) == 2


def test_jvalue_to_engine_params():
    engine = make_engine()
    variant = {
        "id": "default",
        "engineFactory": "whatever",
        "datasource": {"params": {"id": 11}},
        "preparator": {"params": {"id": 12}},
        "algorithms": [
            {"name": "algo0", "params": {"id": 13, "mult": 2}},
            {"name": "algo1", "params": {"id": 14}},
        ],
        "serving": {"params": {"id": 15}},
    }
    ep = engine.jvalue_to_engine_params(variant)
    assert ep.data_source_params == ("", DSP(11))
    assert ep.preparator_params == ("", PP(12))
    assert ep.algorithm_params_list == [("algo0", AP(13, 2)), ("algo1", AP(14))]
    assert ep.serving_params == ("", SP(15))


def test_jvalue_missing_sections_default_empty():
    # Missing sections fall back to EmptyParams (Engine.scala:361-380)
    ep = make_engine().jvalue_to_engine_params({"id": "x"})
    assert ep.data_source_params == ("", EmptyParams())
    assert ep.algorithm_params_list == []


def test_prepare_deploy_passthrough_and_retrain(ctx):
    from incubator_predictionio_tpu.core.persistent_model import RetrainMarker

    engine = make_engine()
    ep = params()
    models = engine.train(ctx, ep)
    served = engine.prepare_deploy(ctx, ep, "inst1", models)
    assert served == models
    retrained = engine.prepare_deploy(ctx, ep, "inst1", [RetrainMarker()])
    assert retrained == models
