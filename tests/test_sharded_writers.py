"""Multi-writer sharded event log (data/storage/cpplog.py).

The contracts under test, per docs/production.md "Planet-scale ingest":

- **Differential**: a log written through N writer shards scans
  byte-identical to the same events written through the single-writer
  layout — same rows, same order, same first-seen id-table blobs — at
  shard counts {1, 2, 7}, including deletes/tombstones, and through the
  traincache tail fold.
- **Vector cursor**: ``tail_cursor``/``read_interactions_since`` keep
  the 5-tuple freshness-stamp contract on sharded layouts — cursors
  grow monotonically under appends, and segment roll, compaction, and a
  writer reload surface as a RESET (or a cursor that compares behind),
  exactly the speed-overlay resync trigger.
"""

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    StorageClientConfig,
    base,
    cpplog,
    traincache,
)
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.utils.times import from_millis

pytestmark = pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native", fromlist=["load"]).load()
    is None,
    reason="native library unavailable",
)

SHARD_COUNTS = (1, 2, 7)

SCAN_KW = dict(entity_type="user", target_entity_type="item",
               event_names=("rate",), value_prop="rating")


@pytest.fixture
def make_store(tmp_path, monkeypatch):
    """Factory: a fresh cpplog Events DAO with ``shards`` writer
    shards under its own directory. PIO_LOG_SHARDS only applies to NEW
    logs, so it is set around client creation per store."""
    monkeypatch.setattr(traincache, "MIN_NNZ", 4)
    clients = []

    def build(shards: int, sub: str):
        monkeypatch.setenv("PIO_LOG_SHARDS", str(shards))
        d = tmp_path / sub
        d.mkdir(exist_ok=True)
        client = cpplog.StorageClient(
            StorageClientConfig(properties={"PATH": str(d)}))
        clients.append(client)
        dao = cpplog.CppLogEvents(client, None, prefix="t_")
        dao.init(1)
        monkeypatch.delenv("PIO_LOG_SHARDS")
        return dao

    yield build
    for c in clients:
        c.close()


def _build_log(dao, seed: int = 0, n: int = 240):
    """Same logical stream into any layout: a columnar bulk import with
    DISTINCT times (the byte-identity precondition — equal-time ties
    break by unit order, which legitimately differs across layouts),
    per-event inserts with an explicit-id pool (upsert tombstones), and
    deletes."""
    rng = np.random.default_rng(seed)
    # disjoint time range per seed — repeat builds must not collide
    times = 1000 + seed * 10_000_000 + 7 * rng.permutation(n).astype(
        np.int64)
    inter = Interactions(
        user_idx=rng.integers(0, 23, n).astype(np.int32),
        item_idx=rng.integers(0, 11, n).astype(np.int32),
        values=(1.0 + rng.integers(0, 5, n)).astype(np.float32),
        user_ids=[f"u{k}" for k in range(23)],
        item_ids=[f"i{k}" for k in range(11)],
    )
    assert dao.import_interactions(inter, 1, times=times,
                                   id_seed=seed + 17) == n
    ids = []
    for k in range(30):
        ids.append(dao.insert(Event(
            event="rate", entity_type="user", entity_id=f"x{k % 5}",
            target_entity_type="item", target_entity_id=f"i{k % 4}",
            properties=DataMap({"rating": float(k)}),
            event_time=from_millis(900_000_000 + seed * 10_000 + 3 * k),
            event_id=f"{k % 9:032d}",  # small pool → upsert tombstones
        ), 1))
    for eid in ids[::4]:
        assert dao.delete(eid, 1)


def _assert_byte_identical(a, b):
    assert np.array_equal(a.user_idx, b.user_idx)
    assert np.array_equal(a.item_idx, b.item_idx)
    assert np.array_equal(a.values, b.values)
    for ta, tb in ((a.user_ids, b.user_ids), (a.item_ids, b.item_ids)):
        assert bytes(ta.blob) == bytes(tb.blob)
        assert np.array_equal(ta.offsets, tb.offsets)


def _scan(dao, **kw):
    kw = {**SCAN_KW, **kw}
    return dao.scan_interactions(app_id=1, **kw)


# -- differential: multi-writer merge vs single-writer ---------------------

@pytest.mark.parametrize("shards", SHARD_COUNTS)
def test_multiwriter_scan_byte_identical(make_store, shards):
    ref = make_store(1, "ref")
    got = make_store(shards, f"sh{shards}")
    _build_log(ref)
    _build_log(got)
    assert got.client.shards(got.ns, 1, None) == shards
    _assert_byte_identical(_scan(ref, use_cache=False, seed_cache=False),
                           _scan(got, use_cache=False, seed_cache=False))


@pytest.mark.parametrize("shards", (2, 7))
def test_multiwriter_identical_across_roll_and_compact(make_store, shards):
    """Tiering (hot→cold roll) and per-segment compaction renumber
    entries and move bytes between files — the merged scan must not
    change by a byte relative to the plain single-writer layout."""
    ref = make_store(1, "ref")
    got = make_store(shards, f"sh{shards}")
    _build_log(ref)
    _build_log(got)
    assert got.maybe_roll(1, limit_bytes=1) >= 1  # every hot seals
    got.compact(1)
    _build_log(got, seed=1, n=60)   # post-roll appends land in new hots
    _build_log(ref, seed=1, n=60)
    _assert_byte_identical(_scan(ref, use_cache=False, seed_cache=False),
                           _scan(got, use_cache=False, seed_cache=False))


@pytest.mark.parametrize("shards", (2, 7))
def test_multiwriter_traincache_tail_fold_identical(make_store, shards):
    """Cache seeded at import, tail appended afterwards: the warm scan
    (cache + tail fold through the merged-cursor path) must equal the
    cold full scan on a sharded layout."""
    dao = make_store(shards, "warm")
    n = 12
    inter = Interactions(
        user_idx=(np.arange(n, dtype=np.int32) % 5),
        item_idx=(np.arange(n, dtype=np.int32) % 3),
        values=np.arange(1, n + 1, dtype=np.float32),
        user_ids=[f"u{k}" for k in range(5)],
        item_ids=[f"i{k}" for k in range(3)],
    )
    assert dao.import_interactions(
        inter, 1, times=1000 + np.arange(n, dtype=np.int64)) == n
    for k in range(5):
        dao.insert(Event(
            event="rate", entity_type="user", entity_id=f"tail{k}",
            target_entity_type="item", target_entity_id="i0",
            properties=DataMap({"rating": 9.0 + k}),
            event_time=from_millis(5000 + k)), 1)
    warm = _scan(dao)
    assert len(warm) == n + 5
    cold = _scan(dao, use_cache=False, seed_cache=False)
    _assert_byte_identical(warm, cold)


# -- vector cursor contract ------------------------------------------------

def _read_since(dao, cursor):
    return dao.read_interactions_since(cursor, app_id=1, **SCAN_KW)


def test_vector_cursor_monotonic_under_appends(make_store):
    dao = make_store(3, "cur")
    cur = dao.tail_cursor(app_id=1)
    assert isinstance(cur, base.VectorCursor)
    assert int(cur) == 0
    seen = 0
    for step in range(4):
        _build_log(dao, seed=step, n=30)
        inter, _times, append_ms, new_cur, reset = _read_since(dao, cur)
        assert not reset
        assert isinstance(new_cur, base.VectorCursor)
        assert len(inter) > 0
        assert len(append_ms) == len(inter)
        # vector order: strictly ahead on at least one shard, behind on
        # none (the any-behind comparison is the overlay's reset trigger)
        assert not (new_cur < cur)
        assert int(new_cur) > int(cur)
        seen += len(inter)
        cur = new_cur
    # drained: nothing new, cursor stable
    inter, _t, _a, again, reset = _read_since(dao, cur)
    assert len(inter) == 0 and not reset and again == cur


def test_vector_cursor_resets_on_compaction(make_store):
    dao = make_store(3, "cur")
    _build_log(dao, n=60)
    first = _read_since(dao, base.VectorCursor(
        (0,) * dao.client.shards(dao.ns, 1, None)))
    cur = first[3]
    dao.compact(1)  # tombstones drop → entries renumber → gen bumps
    inter, _t, _a, new_cur, reset = _read_since(dao, cur)
    assert reset, "compaction must surface as a reset"
    assert len(inter) == 0
    # the overlay protocol after a reset: full scan + fresh tail cursor
    assert len(_scan(dao, use_cache=False, seed_cache=False)) > 0
    fresh = dao.tail_cursor(app_id=1)
    inter2, _t2, _a2, cur2, reset2 = _read_since(dao, fresh)
    assert not reset2 and len(inter2) == 0 and cur2 == fresh


def test_vector_cursor_resets_on_roll(make_store):
    dao = make_store(2, "cur")
    _build_log(dao, n=60)
    cur = _read_since(dao, base.VectorCursor((0, 0)))[3]
    assert dao.maybe_roll(1, limit_bytes=1) >= 1
    _inter, _t, _a, _nc, reset = _read_since(dao, cur)
    assert reset, "a hot→cold seal renumbers the shard; cursors resync"


def test_writer_reload_preserves_layout_and_data(make_store, tmp_path,
                                                 monkeypatch):
    """A writer restart (close + reopen on the same directory) keeps
    the shard layout pinned by the meta file, scans identically, and a
    pre-reload cursor never silently skips events: the post-reload read
    either resets or replays from a cursor that compares behind."""
    dao = make_store(3, "reload")
    _build_log(dao, n=90)
    before = _scan(dao, use_cache=False, seed_cache=False)
    cur = _read_since(dao, base.VectorCursor((0, 0, 0)))[3]
    dao.client.close()

    # NO PIO_LOG_SHARDS this time: the .shards meta must pin 3
    client2 = cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(tmp_path / "reload")}))
    try:
        dao2 = cpplog.CppLogEvents(client2, None, prefix="t_")
        assert client2.shards("t_", 1, None) == 3
        after = _scan(dao2, use_cache=False, seed_cache=False)
        _assert_byte_identical(before, after)
        inter, _t, _a, new_cur, reset = _read_since(dao2, cur)
        if not reset and len(inter) == 0:
            # no events may be lost between the old cursor and the tail:
            # replaying from zero must not exceed what the old cursor
            # plus the (empty) incremental read accounts for
            assert int(new_cur) >= int(cur) or new_cur < cur
        full = _read_since(dao2, base.VectorCursor((0, 0, 0)))
        assert len(full[0]) == len(after)
    finally:
        client2.close()


def test_shard_spray_is_stable_per_entity(make_store):
    """An entity's whole history lands on ONE shard (per-entity order
    survives sharding): re-inserting the same user always routes to the
    same segment file."""
    dao = make_store(5, "spray")
    sizes = {}
    for rounds in range(3):
        for k in range(40):
            dao.insert(Event(
                event="rate", entity_type="user", entity_id=f"u{k}",
                target_entity_type="item", target_entity_id="i0",
                properties=DataMap({"rating": 1.0}),
                event_time=from_millis(1000 + rounds * 100 + k)), 1)
        counts = tuple(
            int(dao.client.lib.pio_evlog_entry_count(
                dao.client.handle_path(dao._hot_path(1, None, s))))
            for s in range(5))
        if sizes:
            prev_total = sum(sizes["counts"])
            # growth is proportional per shard: a shard that had p% of
            # the keys keeps getting exactly those keys
            grown = [c - p for c, p in zip(counts, sizes["counts"])]
            assert grown == list(sizes["delta"]), (grown, sizes)
        else:
            sizes["delta"] = counts
        sizes["counts"] = counts
    assert sum(1 for c in sizes["counts"] if c) >= 2, (
        "40 keys over 5 shards should hit at least 2 shards")
