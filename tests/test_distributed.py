"""Multi-HOST (two-process) runtime test for parallel/distributed.py.

The multichip suite proves 8-device sharding inside one process; this
proves the multi-controller story across PROCESS boundaries — two
coordinated Python processes, 4 virtual CPU devices each, joined by
`jax.distributed.initialize` into one 8-device runtime (the TPU-pod
model replacing the reference's Spark executors). Each worker runs
tests/distributed_worker.py: coordinator bring-up, pod mesh, host-local
batch feeding, a cross-process collective, and one numerics-checked ALS
sweep on globally-sharded buckets.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_pod_runtime():
    worker = Path(__file__).parent / "distributed_worker.py"
    repo_root = str(Path(__file__).parent.parent)
    port = _free_port()
    procs = []
    base = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    base["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, base.get("PYTHONPATH")) if p)
    for pid in range(2):
        env = dict(
            base,
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES="2",
            PIO_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    finally:
        # a hung worker must not outlive the test holding the coordinator
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    oks = [line for out in outs for line in out.splitlines()
           if line.startswith("WORKER_OK")]
    assert len(oks) == 2, outs
    # both controllers computed the SAME global model
    assert oks[0] == oks[1], oks
