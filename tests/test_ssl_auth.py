"""SSL configuration + server-key authentication tests.

Parity targets: common/.../configuration/SSLConfiguration.scala (https on
all servers) and common/.../authentication/KeyAuthentication.scala
(enforced accessKey on /stop,/reload).
"""

import json
import ssl
import subprocess
import urllib.request

import pytest

from incubator_predictionio_tpu.utils.ssl_config import (
    ServerKeyConfig,
    SSLConfig,
    load_server_conf,
    load_server_key,
    load_ssl_config,
    parse_server_conf,
)

CONF = """
# comment
// another comment
pio.server.ssl-certfile = /tmp/server.crt
pio.server.ssl-keyfile  = "/tmp/server.key"
pio.server.key-auth-enforced = true
pio.server.accessKey = sekrit
"""


def test_parse_server_conf():
    conf = parse_server_conf(CONF)
    assert conf["pio.server.ssl-certfile"] == "/tmp/server.crt"
    assert conf["pio.server.ssl-keyfile"] == "/tmp/server.key"
    assert conf["pio.server.key-auth-enforced"] == "true"


def test_parse_server_conf_inline_comments():
    conf = parse_server_conf(
        "pio.server.ssl-keyfile-pass = secret        # optional\n"
        "pio.server.accessKey = ab#cd   // trailing\n"
    )
    assert conf["pio.server.ssl-keyfile-pass"] == "secret"
    assert conf["pio.server.accessKey"] == "ab#cd"  # '#' inside value kept


def test_load_from_conf_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_CONF_DIR", str(tmp_path))
    (tmp_path / "server.conf").write_text(CONF)
    assert load_ssl_config().certfile == "/tmp/server.crt"
    key = load_server_key()
    assert key.auth_enforced is True
    assert key.check("sekrit") is True
    assert key.check("wrong") is False
    assert key.check(None) is False


def test_missing_conf_is_permissive(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_CONF_DIR", str(tmp_path))
    assert load_server_conf() == {}
    assert load_ssl_config().enabled is False
    assert load_ssl_config().ssl_context() is None
    key = load_server_key()
    assert key.auth_enforced is False
    assert key.check(None) is True  # authEnforced=false passes everything


def _make_self_signed(tmp_path):
    crt, key = tmp_path / "server.crt", tmp_path / "server.key"
    proc = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=localhost"],
        capture_output=True,
    )
    if proc.returncode != 0:
        pytest.skip("openssl unavailable for self-signed cert generation")
    return crt, key


def test_https_round_trip(tmp_path, monkeypatch):
    """A server constructed with server.conf TLS material serves https."""
    crt, key = _make_self_signed(tmp_path)
    conf_dir = tmp_path / "conf"
    conf_dir.mkdir()
    (conf_dir / "server.conf").write_text(
        f"pio.server.ssl-certfile = {crt}\n"
        f"pio.server.ssl-keyfile = {key}\n"
    )
    monkeypatch.setenv("PIO_CONF_DIR", str(conf_dir))

    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Response,
        Router,
    )
    from incubator_predictionio_tpu.utils.ssl_config import load_ssl_config

    router = Router()

    @router.get("/")
    def root(request):
        return Response(200, {"secure": True})

    server = HttpServer(router, "127.0.0.1", 0,
                        ssl_context=load_ssl_config().ssl_context())
    port = server.start_background()
    try:
        client_ctx = ssl.create_default_context()
        client_ctx.check_hostname = False
        client_ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(
            f"https://127.0.0.1:{port}/", context=client_ctx, timeout=10
        ) as resp:
            assert json.loads(resp.read()) == {"secure": True}
    finally:
        server.stop()


def test_prediction_server_key_auth_from_conf(tmp_path, monkeypatch):
    """/stop,/reload reject without the conf-enforced key when the server
    has no explicit --server-key."""
    conf_dir = tmp_path / "conf"
    conf_dir.mkdir()
    (conf_dir / "server.conf").write_text(
        "pio.server.key-auth-enforced = true\n"
        "pio.server.accessKey = sekrit\n"
    )
    monkeypatch.setenv("PIO_CONF_DIR", str(conf_dir))

    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.utils.http import HttpError, Request

    server = PredictionServer.__new__(PredictionServer)
    server.config = ServerConfig(server_key=None)
    server._conf_server_key = load_server_key()

    def req(query):
        return Request("POST", "/stop", query, {}, b"")

    with pytest.raises(HttpError):
        server._check_server_key(req({}))
    with pytest.raises(HttpError):
        server._check_server_key(req({"accessKey": "wrong"}))
    server._check_server_key(req({"accessKey": "sekrit"}))  # passes


def test_server_key_config_check():
    k = ServerKeyConfig(auth_enforced=True, key="k1")
    assert k.check("k1") and not k.check("k2") and not k.check(None)
    assert ServerKeyConfig(auth_enforced=False).check(None)
