"""Speed layer: fold-in correctness, compile-cache discipline, cursor +
overlay semantics, serving integration, and the cold-start quality claim.

The acceptance contract this file pins:
- the batched device fold-in matches a dense numpy least-squares
  reference within tolerance at EVERY bucket-ladder size,
- steady-state fold-in serves from the fixed bucket ladder (the jit
  compile-cache counter stops growing),
- the overlay is invalidated wholesale on hot model swap and per-user
  on newer events,
- on a planted cold-start workload the speed layer's recall is strictly
  better than the averaged-recent-views fallback it replaces,
- TTL/staleness decisions run on the injectable clock (no sleeps).
"""

import json
import urllib.request

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.data.store import EventStore
from incubator_predictionio_tpu.speed.cache import TTLCache
from incubator_predictionio_tpu.speed.foldin import (
    FoldInSolver,
    dense_reference_solve,
    foldin_compile_cache_size,
)
from incubator_predictionio_tpu.speed.overlay import (
    SpeedOverlay,
    SpeedOverlayConfig,
)
from incubator_predictionio_tpu.utils.times import FakeClock, now_utc


# ---------------------------------------------------------------------------
# storage scaffolding
# ---------------------------------------------------------------------------

@pytest.fixture
def mem_store():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    Storage.get_meta_data_apps().insert(App(0, "speedapp"))
    yield "speedapp"
    Storage.reset()


def _rate(app, user, item, value, event="rate", prop="rating"):
    EventStore.write([Event(
        event=event, entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties=DataMap({prop: float(value)}),
        event_time=now_utc())], app)


# ---------------------------------------------------------------------------
# fold-in differential vs the dense reference
# ---------------------------------------------------------------------------

def test_foldin_matches_dense_reference_every_bucket():
    rng = np.random.default_rng(0)
    M, K = 300, 16
    other = rng.normal(0, 0.3, (M, K)).astype(np.float32)
    solver = FoldInSolver(other, l2=0.05, reg_nnz=True, implicit=False)
    # degrees landing in every ladder bucket, including the boundaries
    degrees = [1, 7, 8, 9, 31, 32, 33, 127, 128, 200, 511, 512]
    rows = []
    for d in degrees:
        cols = rng.integers(0, M, d).astype(np.int32)
        vals = rng.normal(3.5, 1.0, d).astype(np.float32)
        rows.append((cols, vals))
    out = solver.solve(rows)
    for (cols, vals), got in zip(rows, out):
        ref = dense_reference_solve(other, cols, vals, 0.05)
        err = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-12)
        assert err < 1e-3, (len(cols), err)


def test_foldin_truncates_over_ladder_history_to_newest():
    rng = np.random.default_rng(1)
    M, K = 100, 8
    other = rng.normal(0, 0.3, (M, K)).astype(np.float32)
    solver = FoldInSolver(other, l2=0.1)
    cols = rng.integers(0, M, 700).astype(np.int32)
    vals = rng.normal(0, 1.0, 700).astype(np.float32)
    got = solver.solve([(cols, vals)])[0]
    ref = dense_reference_solve(other, cols[-512:], vals[-512:], 0.1)
    assert np.max(np.abs(got - ref)) < 1e-3


def test_foldin_implicit_matches_dense_reference():
    rng = np.random.default_rng(2)
    M, K = 150, 8
    other = rng.normal(0, 0.3, (M, K)).astype(np.float32)
    solver = FoldInSolver(other, l2=0.05, implicit=True, alpha=2.0)
    for d in (1, 8, 30, 128):
        cols = rng.integers(0, M, d).astype(np.int32)
        vals = np.abs(rng.normal(1.0, 0.5, d)).astype(np.float32)
        got = solver.solve([(cols, vals)])[0]
        ref = dense_reference_solve(other, cols, vals, 0.05,
                                    implicit=True, alpha=2.0)
        err = np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-12)
        assert err < 1e-3, (d, err)


def test_foldin_empty_history_is_zero():
    other = np.ones((10, 4), np.float32)
    solver = FoldInSolver(other, l2=0.1)
    out = solver.solve([(np.empty(0, np.int32), np.empty(0, np.float32)),
                        (np.asarray([1], np.int32),
                         np.asarray([2.0], np.float32))])
    assert np.all(out[0] == 0.0)
    assert np.any(out[1] != 0.0)


def test_foldin_steady_state_no_recompiles():
    """THE no-per-query-recompilation assert: after the bucket ladder is
    warm, arbitrary (batch, degree) traffic adds ZERO compiled
    variants."""
    rng = np.random.default_rng(3)
    M, K = 80, 8
    other = rng.normal(0, 0.3, (M, K)).astype(np.float32)
    solver = FoldInSolver(other, l2=0.1)

    def random_rows(n):
        out = []
        for _ in range(n):
            d = int(rng.integers(1, 700))
            out.append((rng.integers(0, M, d).astype(np.int32),
                        rng.normal(0, 1, d).astype(np.float32)))
        return out

    # warm the FULL ladder: every width × every power-of-two batch size
    from incubator_predictionio_tpu.speed.foldin import (
        _max_batch,
        _width_ladder,
    )

    solver.warmup()
    for width in _width_ladder():
        b = 1
        while b <= _max_batch():
            solver.solve([(np.arange(width, dtype=np.int32) % M,
                           np.ones(width, np.float32))] * b)
            b *= 2
    warm = foldin_compile_cache_size()
    # the process-wide counter also holds other tests' flag variants
    # (implicit/explicit compile separately); the contract here is that
    # the warm ladder makes further growth impossible.
    # steady state: 30 more rounds of arbitrary traffic — ZERO growth
    for _ in range(30):
        solver.solve(random_rows(int(rng.integers(1, 80))))
    assert foldin_compile_cache_size() == warm, (
        "fold-in recompiled outside the fixed bucket ladder")


# ---------------------------------------------------------------------------
# tail cursor + read_interactions_since
# ---------------------------------------------------------------------------

def test_tail_cursor_memory(mem_store):
    app = mem_store
    assert EventStore.tail_cursor(app) == 0
    _rate(app, "u1", "i1", 4.0)
    _rate(app, "u2", "i2", 3.0)
    c1 = EventStore.tail_cursor(app)
    assert c1 == 2
    inter, times, appends, new_c, reset = \
        EventStore.read_interactions_since(
            0, app, event_names=("rate",), value_prop="rating")
    assert new_c == 2 and len(inter) == 2 and not reset
    assert list(inter.user_ids) == ["u1", "u2"]
    # the memory backend stamps exact per-slot append walls
    assert appends.shape == (2,) and (appends > 0).all()
    # only the tail after the cursor
    _rate(app, "u3", "i1", 5.0)
    inter2, _t, _a, new_c2, _r = EventStore.read_interactions_since(
        c1, app, event_names=("rate",), value_prop="rating")
    assert new_c2 == 3 and len(inter2) == 1
    assert list(inter2.user_ids) == ["u3"]
    # non-matching events advance the cursor but contribute no rows
    EventStore.write([Event(
        event="$set", entity_type="item", entity_id="i9",
        properties=DataMap({"categories": ["x"]}),
        event_time=now_utc())], app)
    inter3, _t, _a, new_c3, _r = EventStore.read_interactions_since(
        new_c2, app, event_names=("rate",), value_prop="rating")
    assert new_c3 == 4 and len(inter3) == 0


def test_tail_skips_deleted_and_superseded_events(mem_store):
    """A deleted event must not replay through the tail read (training
    scans exclude it; the speed layer must match), and an upsert's
    superseded version must not either — while cursor POSITIONS stay
    monotonic."""
    app = mem_store
    eids = EventStore.write([Event(
        event="rate", entity_type="user", entity_id="gdpr",
        target_entity_type="item", target_entity_id="i1",
        properties=DataMap({"rating": 4.0}), event_time=now_utc())], app)
    _rate(app, "u2", "i2", 3.0)
    EventStore.delete([eids[0]], app)
    inter, _t, _a, new_c, reset = EventStore.read_interactions_since(
        0, app, event_names=("rate",), value_prop="rating")
    assert not reset and new_c == 2       # positions preserved
    assert list(inter.user_ids) == ["u2"]  # deleted event gone
    # upsert: only the NEWEST write of an explicit id replays
    EventStore.write([Event(
        event="rate", entity_type="user", entity_id="u3",
        target_entity_type="item", target_entity_id="i3",
        properties=DataMap({"rating": 1.0}), event_time=now_utc(),
        event_id="fixed-id")], app)
    EventStore.write([Event(
        event="rate", entity_type="user", entity_id="u3",
        target_entity_type="item", target_entity_id="i3",
        properties=DataMap({"rating": 2.0}), event_time=now_utc(),
        event_id="fixed-id")], app)
    inter2, _t, _a, _c, _r = EventStore.read_interactions_since(
        0, app, event_names=("rate",), value_prop="rating")
    u3_vals = [float(v) for u, v in zip(inter2.user_idx, inter2.values)
               if inter2.user_ids[int(u)] == "u3"]
    assert u3_vals == [2.0]


def test_tail_cursor_cpplog(tmp_path):
    cpplog = pytest.importorskip(
        "incubator_predictionio_tpu.data.storage.cpplog")
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )

    cfg = StorageClientConfig(properties={"PATH": str(tmp_path)})
    try:
        client = cpplog.StorageClient(cfg)
    except Exception:
        pytest.skip("native library unavailable")
    dao = cpplog.CppLogEvents(client, cfg, prefix="t_")
    try:
        assert dao.tail_cursor(1) == 0
        dao.import_interactions(
            Interactions(
                user_idx=np.asarray([0, 1], np.int32),
                item_idx=np.asarray([0, 1], np.int32),
                values=np.asarray([4.0, 3.0], np.float32),
                user_ids=IdTable.from_list(["u1", "u2"]),
                item_ids=IdTable.from_list(["i1", "i2"])),
            1, event_name="rate", value_prop="rating")
        c1 = dao.tail_cursor(1)
        assert c1 == 2
        inter, times, appends, new_c, reset = dao.read_interactions_since(
            0, 1, event_names=("rate",), value_prop="rating")
        assert new_c == 2 and len(inter) == 2 and not reset
        assert list(inter.user_ids) == ["u1", "u2"]
        # this process wrote the batch: its append mark covers the tail
        assert appends.shape == (2,) and (appends > 0).all()
        dao.import_interactions(
            Interactions(
                user_idx=np.asarray([0], np.int32),
                item_idx=np.asarray([0], np.int32),
                values=np.asarray([5.0], np.float32),
                user_ids=IdTable.from_list(["u3"]),
                item_ids=IdTable.from_list(["i1"])),
            1, event_name="rate", value_prop="rating")
        inter2, _t, _a, new_c2, _r = dao.read_interactions_since(
            c1, 1, event_names=("rate",), value_prop="rating")
        assert new_c2 == 3 and len(inter2) == 1
        assert list(inter2.user_ids) == ["u3"]
        # empty tail round-trips cleanly
        inter3, _t, _a, new_c3, _r = dao.read_interactions_since(new_c2, 1)
        assert new_c3 == new_c2 and len(inter3) == 0
        # compaction renumbers entries: an old cursor must RESET even
        # when appends push the entry count past its old value (a bare
        # count comparison would silently misread the delta)
        eid = dao.insert(Event(
            event="rate", entity_type="user", entity_id="u9",
            target_entity_type="item", target_entity_id="i1",
            properties=DataMap({"rating": 1.0}),
            event_time=now_utc()), 1)
        dao.delete(eid, 1)
        pre_compact = dao.tail_cursor(1)
        dao.compact(1)
        dao.import_interactions(
            Interactions(
                user_idx=np.zeros(4, np.int32),
                item_idx=np.zeros(4, np.int32),
                values=np.ones(4, np.float32),
                user_ids=IdTable.from_list(["u4"]),
                item_ids=IdTable.from_list(["i1"])),
            1, event_name="rate", value_prop="rating")
        # entry count now exceeds the pre-compaction position...
        assert dao.tail_cursor(1) != pre_compact
        _i, _t, _a, _c, reset = dao.read_interactions_since(
            pre_compact, 1, event_names=("rate",), value_prop="rating")
        assert reset is True  # ...but the generation mismatch catches it
    finally:
        client.close()


# ---------------------------------------------------------------------------
# overlay semantics
# ---------------------------------------------------------------------------

def _overlay(app, other, idx, clock, **cfg_kw):
    kw = dict(app_name=app, event_names=("rate",), value_prop="rating",
              l2=0.05, ttl_s=30.0)
    kw.update(cfg_kw)
    return SpeedOverlay(SpeedOverlayConfig(**kw), other, idx, clock=clock)


def test_overlay_fold_in_and_per_user_invalidation(mem_store):
    app = mem_store
    rng = np.random.default_rng(4)
    other = rng.normal(0, 0.3, (20, 8)).astype(np.float32)
    idx = {f"i{k}": k for k in range(20)}
    clock = FakeClock()
    ov = _overlay(app, other, idx, clock)
    assert ov.enabled
    _rate(app, "alice", "i3", 4.0)
    _rate(app, "alice", "i7", 2.0)
    s = ov.poll()
    assert s["solved"] == 1 and s["tail_rows"] == 2
    vec = ov.lookup("alice")
    assert vec is not None
    ref = dense_reference_solve(other, [3, 7], [4.0, 2.0], 0.05)
    assert np.allclose(vec, ref, atol=1e-3)
    # newer per-user event invalidates the entry the moment the poll
    # sees it — and lookup misses until the re-solve lands
    _rate(app, "alice", "i1", 5.0)
    ov.poll(max_keys=0)  # mark dirty without re-solving
    assert ov.lookup("alice") is None
    assert not ov.covers("alice")
    ov.poll()
    vec2 = ov.lookup("alice")
    ref2 = dense_reference_solve(other, [3, 7, 1], [4.0, 2.0, 5.0], 0.05)
    assert np.allclose(vec2, ref2, atol=1e-3)


def test_overlay_ttl_and_wholesale_invalidation(mem_store):
    app = mem_store
    other = np.eye(8, dtype=np.float32)[: 8]
    idx = {f"i{k}": k for k in range(8)}
    clock = FakeClock()
    ov = _overlay(app, other, idx, clock, ttl_s=10.0)
    _rate(app, "bob", "i1", 4.0)
    ov.poll()
    assert ov.covers("bob")
    # TTL expiry through the clock seam — no sleeps
    clock.advance(10.5)
    assert ov.lookup("bob") is None
    # refold, then hot-swap invalidation clears everything at once
    ov.poll()  # bob is no longer dirty: nothing to refold
    _rate(app, "carol", "i2", 3.0)
    ov.poll()
    assert ov.covers("carol")
    ov.invalidate_all()
    assert not ov.covers("carol")
    assert ov.lookup("carol") is None


def test_overlay_key_version_bumps_on_new_events(mem_store):
    app = mem_store
    other = np.eye(4, dtype=np.float32)
    ov = _overlay(app, other, {f"i{k}": k for k in range(4)}, FakeClock())
    assert ov.key_version("dave") == 0
    _rate(app, "dave", "i0", 1.0)
    ov.poll(max_keys=0)
    v1 = ov.key_version("dave")
    assert v1 >= 1
    _rate(app, "dave", "i1", 1.0)
    ov.poll(max_keys=0)
    assert ov.key_version("dave") > v1


def test_overlay_cursor_reset_invalidates(mem_store):
    app = mem_store
    other = np.eye(4, dtype=np.float32)
    ov = _overlay(app, other, {f"i{k}": k for k in range(4)}, FakeClock())
    _rate(app, "erin", "i0", 2.0)
    ov.poll()
    assert ov.covers("erin")
    # simulate a log rewrite: drop the table (cursor goes backwards)
    app_id = Storage.get_meta_data_apps().get_by_name(app).id
    Storage.get_events().remove(app_id)
    Storage.get_events().init(app_id)
    s = ov.poll()
    assert s.get("reset") is True
    assert not ov.covers("erin")


def test_overlay_item_side_fold_in(mem_store):
    """key_side='target': a brand-new ITEM's row is solved from its
    events against frozen user factors (the similarproduct orientation).
    """
    app = mem_store
    rng = np.random.default_rng(5)
    user_factors = rng.normal(0, 0.3, (10, 8)).astype(np.float32)
    uidx = {f"u{k}": k for k in range(10)}
    ov = SpeedOverlay(
        SpeedOverlayConfig(
            app_name=app, event_names=("view",), value_prop=None,
            event_values={"view": 1.0}, key_side="target",
            l2=0.05, implicit=True, alpha=1.0),
        user_factors, uidx, clock=FakeClock())
    for u in ("u1", "u4", "u7"):
        EventStore.write([Event(
            event="view", entity_type="user", entity_id=u,
            target_entity_type="item", target_entity_id="newitem",
            event_time=now_utc())], app)
    s = ov.poll()
    assert s["solved"] == 1
    vec = ov.lookup("newitem")
    ref = dense_reference_solve(user_factors, [1, 4, 7], [1.0, 1.0, 1.0],
                                0.05, implicit=True, alpha=1.0)
    assert np.allclose(vec, ref, atol=1e-3)


# ---------------------------------------------------------------------------
# TTL micro-cache + clock seam
# ---------------------------------------------------------------------------

def test_ttl_cache_clock_and_version():
    clock = FakeClock()
    cache = TTLCache(maxsize=2, ttl_s=5.0, clock=clock)
    loads = []

    def loader():
        loads.append(1)
        return "v"

    assert cache.get_or_load("k", loader, version=1) == "v"
    assert cache.get_or_load("k", loader, version=1) == "v"
    assert len(loads) == 1
    # version bump invalidates immediately
    assert cache.get_or_load("k", loader, version=2) == "v"
    assert len(loads) == 2
    # TTL expiry through the clock seam
    clock.advance(5.1)
    assert cache.get_or_load("k", loader, version=2) == "v"
    assert len(loads) == 3
    # bounded: LRU eviction at maxsize
    cache.put("a", 1)
    cache.put("b", 2)
    cache.put("c", 3)
    assert len(cache) == 2


def test_ecommerce_micro_cache_dedupes_and_invalidates(mem_store):
    """The recent-events read runs once per write window, not once per
    query — and a new write (cursor bump) invalidates immediately."""
    from incubator_predictionio_tpu.models.ecommerce.engine import (
        ECommAlgorithm,
        ECommAlgorithmParams,
    )

    app = mem_store
    algo = ECommAlgorithm(ECommAlgorithmParams(app_name=app, rank=4))
    _rate(app, "fresh", "i0", 1.0, event="view")

    calls = []
    real = EventStore.find_by_entity

    class _Model:
        item_bimap = {"i0": 0, "i1": 1}

        class _B(dict):
            pass
    model = _Model()
    model.item_bimap = __import__(
        "incubator_predictionio_tpu.data.bimap",
        fromlist=["BiMap"]).BiMap({"i0": 0, "i1": 1})

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    EventStore.find_by_entity = staticmethod(counting)
    try:
        r1 = algo._recent_items(model, "fresh")
        r2 = algo._recent_items(model, "fresh")
        assert r1 == r2 == [0]
        assert len(calls) == 1  # second read served from the micro-cache
        # a new write bumps the store cursor → immediate refetch
        _rate(app, "fresh", "i1", 1.0, event="view")
        r3 = algo._recent_items(model, "fresh")
        assert len(calls) == 2
        assert set(r3) == {0, 1}
    finally:
        EventStore.find_by_entity = staticmethod(real)


# ---------------------------------------------------------------------------
# serving integration: prediction server end-to-end
# ---------------------------------------------------------------------------

def _call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read() or b"null")


def test_prediction_server_speed_layer_e2e(mem_store, monkeypatch):
    """Deploy the real recommendation engine, ingest events for an
    unknown user, poll the overlay, and watch /queries.json serve them;
    /reload proves the wholesale hot-swap invalidation; /status reports
    staleness + overlay stats."""
    from incubator_predictionio_tpu.core.params import EngineParams
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithmParams,
        DataSourceParams,
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.workflow import CoreWorkflow

    app = mem_store
    rng = np.random.default_rng(7)
    for u in range(12):
        for i in rng.choice(20, 6, replace=False):
            _rate(app, f"u{u}", f"i{i}", float(rng.integers(1, 6)))
    engine = RecommendationEngine().apply()
    ep = EngineParams(
        data_source_params=("", DataSourceParams(app_name=app)),
        algorithm_params_list=[("als", ALSAlgorithmParams(
            rank=4, num_iterations=5, lambda_=0.05, seed=1))],
    )
    CoreWorkflow.run_train(engine, ep, engine_variant="speedtest")
    server = PredictionServer(engine, ServerConfig(
        ip="127.0.0.1", port=0, engine_variant="speedtest",
        server_key="sk", micro_batch=0))
    monkeypatch.setenv("PIO_SPEED_POLL_S", "3600")  # poll manually
    port = server.start_background()
    try:
        assert len(server._speed_overlays) == 1
        overlay = server._speed_overlays[0]
        # unknown user, no events: empty result
        _st, r = _call(port, "POST", "/queries.json",
                       {"user": "newbie", "num": 3})
        assert r["itemScores"] == []
        # events arrive; the overlay folds the user in
        for i in ("i1", "i2", "i3"):
            _rate(app, "newbie", i, 5.0)
        s = overlay.poll()
        assert s["solved"] >= 1
        _st, r2 = _call(port, "POST", "/queries.json",
                        {"user": "newbie", "num": 3})
        assert len(r2["itemScores"]) == 3
        # /status: staleness + overlay stats
        _st, info = _call(port, "GET", "/")
        assert info["modelStalenessSec"] >= 0
        assert info["speedOverlay"]["overlays"] == 1
        assert info["speedOverlay"]["size"] >= 1
        assert info["speedOverlay"]["foldins"] >= 1
        # hot swap: /reload replaces the overlay and invalidates the old
        # one wholesale — the new overlay starts empty
        _st, _ = _call(port, "POST", "/reload?accessKey=sk", {})
        assert _st == 200
        assert not overlay.covers("newbie")       # old overlay: emptied
        new_overlay = server._speed_overlays[0]
        assert new_overlay is not overlay
        assert not new_overlay.covers("newbie")   # fresh overlay: empty
        _st, r3 = _call(port, "POST", "/queries.json",
                        {"user": "newbie", "num": 3})
        assert r3["itemScores"] == []             # until the next poll
        new_overlay.poll()
        _st, r4 = _call(port, "POST", "/queries.json",
                        {"user": "newbie", "num": 3})
        assert len(r4["itemScores"]) == 3
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# planted cold-start workload: fold-in beats averaged recent views
# ---------------------------------------------------------------------------

def test_cold_start_recall_beats_averaged_recent_views():
    """The quality claim: for users the deployed model never saw, the
    exact device fold-in ranks strictly better than the averaged
    recent-views fallback it replaces (ecommerce recentFeatures)."""
    from incubator_predictionio_tpu.ops.als import als_train_implicit

    rng = np.random.default_rng(11)
    K0, n_items, n_train, n_cold = 4, 250, 80, 24
    u_true = rng.normal(0, 1.0, (n_train + n_cold, K0))
    v_true = rng.normal(0, 1.0, (n_items, K0))
    pref = u_true @ v_true.T                       # [U, I] true affinity

    def sample_views(u, n):
        p = np.exp(pref[u] / 1.5)
        p /= p.sum()
        return rng.choice(n_items, size=n, replace=False, p=p)

    users, items = [], []
    for u in range(n_train):
        for i in sample_views(u, 25):
            users.append(u)
            items.append(i)
    state = als_train_implicit(
        np.asarray(users, np.int32), np.asarray(items, np.int32),
        np.ones(len(users), np.float32),
        n_users=n_train, n_items=n_items, rank=8, iterations=12,
        l2=0.05, alpha=2.0, seed=3)
    item_factors = np.asarray(state.item_factors)

    solver = FoldInSolver(item_factors, l2=0.05, implicit=True, alpha=2.0)
    k = 20
    fold_recall, avg_recall = [], []
    for cu in range(n_train, n_train + n_cold):
        viewed = sample_views(cu, 15)
        truth_rank = np.argsort(-pref[cu])
        truth_top = [i for i in truth_rank if i not in set(viewed)][:k]
        # speed layer: exact implicit fold-in
        vec = solver.solve([(viewed.astype(np.int32),
                             np.ones(len(viewed), np.float32))])[0]
        scores_f = item_factors @ vec
        # the replaced fallback: mean of the viewed items' factors
        scores_a = item_factors @ item_factors[viewed].mean(axis=0)
        for scores, acc in ((scores_f, fold_recall),
                            (scores_a, avg_recall)):
            s = scores.copy()
            s[viewed] = -np.inf                    # unseen-only serving
            top = np.argsort(-s)[:k]
            acc.append(len(set(top) & set(truth_top)) / k)
    fold_r, avg_r = float(np.mean(fold_recall)), float(np.mean(avg_recall))
    assert fold_r > avg_r, (fold_r, avg_r)


# ---------------------------------------------------------------------------
# overlay lock discipline (regressions for the races pio-lint's
# unguarded-shared-state pass surfaced: cursor written outside the lock
# on the reset path and read unlocked by enabled/poll, last_lag and the
# fold-in budget rung written by the poller but read by stats() scrapes)
# ---------------------------------------------------------------------------

class _AuditedOverlay(SpeedOverlay):
    """Asserts the overlay lock is held for every post-init write of the
    attributes the race fix moved under it."""

    _AUDITED = frozenset({"cursor", "last_lag", "_budget_rung"})

    def __setattr__(self, name, value):
        if name in self._AUDITED and getattr(self, "_audit_on", False):
            assert self._lock.locked(), (
                f"write of {name} without the overlay lock")
        object.__setattr__(self, name, value)


def test_overlay_guarded_write_discipline(mem_store):
    app = mem_store
    other = np.eye(4, dtype=np.float32)
    idx = {f"i{k}": k for k in range(4)}
    ov = _AuditedOverlay(
        SpeedOverlayConfig(app_name=app, event_names=("rate",),
                           value_prop="rating", l2=0.05, ttl_s=30.0),
        other, idx, clock=FakeClock())
    ov._audit_on = True
    assert ov.enabled                  # cursor read takes the lock now
    _rate(app, "zoe", "i1", 3.0)
    s = ov.poll()   # normal path: cursor advance + lag + rung adapt
    assert s["solved"] == 1
    st = ov.stats()
    assert st["cursor"] == s["cursor"]
    assert st["cursorLagEvents"] == s["lag"]
    assert st["foldinBudget"] >= 1
    # reset path (log rewrite): the cursor rewind must also land under
    # the lock, atomically with the derived-state invalidation
    app_id = Storage.get_meta_data_apps().get_by_name(app).id
    Storage.get_events().remove(app_id)
    Storage.get_events().init(app_id)
    s2 = ov.poll()
    assert s2.get("reset") is True
    assert ov.stats()["cursor"] == s2["cursor"]
